"""Deterministic fault injection for the async pipeline.

A `FaultPlan` is a seeded, JSON-loadable schedule of faults, each fired
once when the run crosses a step (`at_step`) or wall-clock (`at_s`)
trigger — `polybeast --chaos_plan plan.json` arms it against a live
run. Every injected fault increments a `chaos.<kind>.injected` counter,
which is what lets scripts/chaos_run.py assert that recovery telemetry
EXACTLY matches what was injected (not merely "the run survived").

Both runtimes are injectable (ISSUE 12): on the Python runtime the
transport faults ride a FaultingTransport wrap threaded into the
ActorPool; with `--native_runtime` they route through the C++ pool's
FaultHooks entry points (`attach_native_pool`, csrc/chaos.h) — the
process-level classes (server SIGKILL, state-table poison, SIGTERM)
are runtime-agnostic either way.

Fault classes (FAULT_KINDS):

    env_server_sigkill   SIGKILL env-server process `target` (uncleanest
                         possible death: abandoned sockets + shm rings)
    transport_sever      cut actor `target`'s transport mid-stream (the
                         socket is shut down under the actor's feet)
    transport_blackhole  actor `target`'s receives stall for
                         `duration_s` (network partition that heals)
    transport_delay      add `delay_s` to actor `target`'s transport ops
                         for `duration_s` (congestion/brown-out)
    shm_corrupt_header   stomp the length header of the next queued
                         frame in actor `target`'s shm recv ring
    shm_corrupt_payload  flip payload bytes of the next queued frame
                         (may decode clean — corruption is not always
                         detectable; recovery counters are asserted for
                         the header class, see the plan docs)
    state_table_poison   poison the DeviceStateTable (the donated-
                         dispatch failure mode, runtime/state_table.py)
    learner_stall        stall the learner AND the serving threads for
                         `duration_s` (the shared-chip overload model:
                         a busy learner chip slows inference dispatch
                         too) — the fault that makes the admission
                         gate shed for real (ISSUE 14). Injected via
                         the driver-installed `throttle()` gate; no
                         target needed.
    preempt_sigterm      SIGTERM this process (preemption: the driver's
                         graceful checkpoint-and-exit path)

Plan JSON:

    {"seed": 7,
     "faults": [
       {"kind": "env_server_sigkill", "at_step": 400, "target": 0},
       {"kind": "transport_sever", "at_step": 900, "target": 1},
       {"kind": "state_table_poison", "at_step": 1400}
     ]}

The controller runs a small poll thread inside the driver process; a
fault whose target is momentarily un-injectable (an actor between
connections) stays due and fires on a later tick, so the injected
counts are exact, not best-effort.
"""

import dataclasses
import json
import logging
import os
import random
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from torchbeast_tpu import telemetry

log = logging.getLogger(__name__)

FAULT_KINDS = (
    "env_server_sigkill",
    "transport_sever",
    "transport_blackhole",
    "transport_delay",
    "shm_corrupt_header",
    "shm_corrupt_payload",
    "state_table_poison",
    "learner_stall",
    "preempt_sigterm",
)

# A due-but-uninjectable fault (e.g. sever while its actor is between
# connections) is retried every poll tick; after this many failed
# attempts it is abandoned with an error log so a misconfigured plan
# (bad target) cannot spin forever.
_MAX_ATTEMPTS = 3000


@dataclasses.dataclass
class FaultSpec:
    kind: str
    at_step: Optional[int] = None
    at_s: Optional[float] = None
    target: int = 0
    duration_s: float = 1.0
    delay_s: float = 0.05
    # -- runtime bookkeeping (not part of the JSON schema) --
    fired: bool = False
    abandoned: bool = False
    attempts: int = 0

    def validate(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"Unknown fault kind {self.kind!r}; know {FAULT_KINDS}"
            )
        if self.at_step is None and self.at_s is None:
            raise ValueError(
                f"Fault {self.kind!r} needs a trigger: at_step or at_s"
            )

    def due(self, step: int, elapsed_s: float) -> bool:
        if self.at_step is not None and step >= self.at_step:
            return True
        return self.at_s is not None and elapsed_s >= self.at_s

    def as_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "at_step": self.at_step,
            "at_s": self.at_s,
            "target": self.target,
            "duration_s": self.duration_s,
            "delay_s": self.delay_s,
            "fired": self.fired,
            "abandoned": self.abandoned,
        }


class FaultPlan:
    """A seeded schedule of FaultSpecs.

    The seed drives nothing inside the specs themselves (triggers are
    explicit) — it seeds the controller's jitter-free bookkeeping RNG
    reserved for future randomized targeting, and rides the artifact so
    a chaos run is reproducible from its JSON alone.
    """

    def __init__(self, faults: List[FaultSpec], seed: int = 0):
        self.seed = seed
        self.faults = list(faults)
        for f in self.faults:
            f.validate()
        self.rng = random.Random(seed)

    # The plan JSON schema: everything a user may write. The runtime
    # bookkeeping fields (fired/abandoned/attempts) are deliberately
    # NOT accepted — a summary/as_dict round-trip carrying
    # `"fired": true` back in would silently disarm the fault.
    _SCHEMA_KEYS = frozenset(
        {"kind", "at_step", "at_s", "target", "duration_s", "delay_s"}
    )

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "FaultPlan":
        if not isinstance(data, dict):
            raise ValueError(f"Fault plan must be an object, got {data!r}")
        faults = []
        for entry in data.get("faults", []):
            unknown = set(entry) - cls._SCHEMA_KEYS
            if unknown:
                raise ValueError(
                    f"Fault entry has unknown keys {sorted(unknown)}: "
                    f"{entry!r}"
                )
            faults.append(FaultSpec(**entry))
        return cls(faults, seed=int(data.get("seed", 0)))

    @classmethod
    def from_json(cls, path: str) -> "FaultPlan":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.faults:
            out[f.kind] = out.get(f.kind, 0) + 1
        return out

    def as_dict(self) -> Dict[str, Any]:
        return {
            "seed": self.seed,
            "faults": [f.as_dict() for f in self.faults],
        }


class FaultingTransport:
    """A transport wrapper that the ChaosController can reach into.

    Wraps any SocketTransport/ShmTransport (same send/recv_sized/recv/
    close surface). Sever closes the write side of the underlying
    socket from the chaos thread, so an actor blocked in recv wakes
    with the same ConnectionError/EOF a real cable cut produces; delay
    and blackhole windows are consulted per operation.
    """

    def __init__(self, inner, actor_index: int, controller):
        self._inner = inner
        self._actor = actor_index
        self._controller = controller

    # -- chaos hooks ------------------------------------------------------
    def sever(self) -> None:
        sock = getattr(self._inner, "_sock", None)
        if sock is None:  # pragma: no cover - every transport has one
            return
        try:
            sock.shutdown(2)  # SHUT_RDWR: unblocks a parked recv
        except OSError:
            pass  # already dead: the sever still "fired"

    def recv_ring(self):
        """The shm recv ring, or None for socket transports."""
        return getattr(self._inner, "_recv_ring", None)

    # -- transport surface ------------------------------------------------
    def send(self, value: Any) -> int:
        self._controller.perturb(self._actor)
        return self._inner.send(value)

    def recv_sized(self) -> Tuple[Any, int]:
        self._controller.perturb(self._actor)
        return self._inner.recv_sized()

    def recv(self) -> Any:
        return self.recv_sized()[0]

    def unlink_segments(self) -> None:
        unlink = getattr(self._inner, "unlink_segments", None)
        if unlink is not None:
            unlink()

    def close(self) -> None:
        self._controller._unregister(self._actor, self)
        self._inner.close()


class ChaosController:
    """Arms a FaultPlan against a live driver.

    The driver attaches handles as they come up (`attach_servers`,
    `attach_state_table`, `set_step_fn`) and threads `wrap_transport`
    into its ActorPool; `start()` runs the poll loop. Injection is
    counted in `chaos.<kind>.injected` the instant it happens.
    """

    def __init__(self, plan: FaultPlan, registry=None,
                 poll_interval_s: float = 0.02):
        self.plan = plan
        self._poll_s = poll_interval_s
        reg = registry if registry is not None else telemetry.get_registry()
        self._counters = {
            kind: reg.counter(f"chaos.{kind}.injected")
            for kind in FAULT_KINDS
        }
        # Attached by the driver thread while the poll thread may
        # already be reading (re-attachment after a rebuild is legal):
        # all of these ride the controller lock (RACE burn-down, ISSUE 7).
        self._server_supervisor = None  # guarded-by: self._lock
        self._state_table = None  # guarded-by: self._lock
        self._native_pool = None  # guarded-by: self._lock
        self._step_fn: Callable[[], int] = lambda: 0  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._transports: Dict[int, FaultingTransport] = {}  # guarded-by: self._lock
        # actor -> (kind, window_end_monotonic, delay_s)
        self._windows: Dict[int, Tuple[str, float, float]] = {}  # guarded-by: self._lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_at: Optional[float] = None
        # learner_stall window end (monotonic); consulted by throttle()
        # from the learner loop and the serving threads.
        self._stall_until = 0.0  # guarded-by: self._lock

    # -- driver attachment ------------------------------------------------
    def attach_servers(self, supervisor) -> None:
        """A polybeast_env.ServerSupervisor (or anything with a
        `.processes` list of live mp.Process members)."""
        with self._lock:
            self._server_supervisor = supervisor

    def attach_state_table(self, table) -> None:
        with self._lock:
            self._state_table = table

    def attach_native_pool(self, pool) -> None:
        """A native (_tbt_core) ActorPool built with fault_hooks=True:
        its connections live in C++ actor threads where the Python
        FaultingTransport wrap cannot reach, so transport faults route
        through the pool's C++ FaultHooks entry points instead
        (chaos_sever / chaos_window / chaos_corrupt_ring, csrc/chaos.h)
        — same fault classes, same injected-exact accounting
        (ISSUE 12)."""
        with self._lock:
            self._native_pool = pool

    def set_step_fn(self, fn: Callable[[], int]) -> None:
        with self._lock:
            self._step_fn = fn

    def wrap_transport(self, transport, actor_index: int):
        wrapped = FaultingTransport(transport, actor_index, self)
        with self._lock:
            self._transports[actor_index] = wrapped
        return wrapped

    def _unregister(self, actor_index: int, wrapped) -> None:
        with self._lock:
            if self._transports.get(actor_index) is wrapped:
                del self._transports[actor_index]

    # -- per-op perturbation (called from FaultingTransport) --------------
    def perturb(self, actor_index: int) -> None:
        with self._lock:
            window = self._windows.get(actor_index)
        if window is None:
            return
        kind, until, delay_s = window
        now = time.monotonic()
        if now >= until:
            with self._lock:
                if self._windows.get(actor_index) == window:
                    del self._windows[actor_index]
            return
        if kind == "transport_delay":
            time.sleep(delay_s)
        else:  # blackhole: hold the op until the window heals
            time.sleep(max(0.0, until - now))

    # -- learner_stall gate (called from driver loops) --------------------
    def stall_remaining(self) -> float:
        """Seconds left in the active learner_stall window (0 = none)."""
        with self._lock:
            until = self._stall_until
        return max(0.0, until - time.monotonic())

    def throttle(self) -> None:
        """The shared-chip stall model (ISSUE 14): the driver installs
        this at the learner's update-dispatch site and the serving
        loops' per-batch site (inference_loop's throttle_fn). Outside a
        stall window it is one lock acquire; inside, it sleeps the
        window out in short slices so shutdown never waits on it."""
        while not self._stop.is_set():
            remaining = self.stall_remaining()
            if remaining <= 0:
                return
            time.sleep(min(remaining, 0.05))

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "ChaosController":
        self._started_at = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="chaos-controller"
        )
        self._thread.start()
        log.info(
            "Chaos armed: %d faults (%s), seed %d",
            len(self.plan.faults),
            ", ".join(sorted(self.plan.counts())),
            self.plan.seed,
        )
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def done(self) -> bool:
        return all(f.fired or f.abandoned for f in self.plan.faults)

    def injected_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.plan.faults:
            if f.fired:
                out[f.kind] = out.get(f.kind, 0) + 1
        return out

    def summary(self) -> Dict[str, Any]:
        return {
            "seed": self.plan.seed,
            "injected": self.injected_counts(),
            "abandoned": [
                f.as_dict() for f in self.plan.faults if f.abandoned
            ],
            "pending": [
                f.as_dict()
                for f in self.plan.faults
                if not f.fired and not f.abandoned
            ],
        }

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            with self._lock:
                step_fn = self._step_fn
            step = step_fn()
            elapsed = time.monotonic() - self._started_at
            for fault in self.plan.faults:
                if fault.fired or fault.abandoned:
                    continue
                if not fault.due(step, elapsed):
                    continue
                try:
                    ok = self._inject(fault)
                except Exception:  # noqa: BLE001
                    ok = False
                    log.exception(
                        "Chaos injector %s raised; will retry", fault.kind
                    )
                if ok:
                    fault.fired = True
                    self._counters[fault.kind].inc()
                    log.warning(
                        "Chaos injected: %s (target %d) at step %d / %.1fs",
                        fault.kind, fault.target, step, elapsed,
                    )
                else:
                    fault.attempts += 1
                    if fault.attempts >= _MAX_ATTEMPTS:
                        fault.abandoned = True
                        log.error(
                            "Chaos fault %s (target %d) could not be "
                            "injected after %d attempts; abandoning it.",
                            fault.kind, fault.target, fault.attempts,
                        )
            if self.done():
                return

    # -- injectors --------------------------------------------------------
    def _native_pool_handle(self):
        with self._lock:
            return self._native_pool

    def _live_transport(self, target: int) -> Optional[FaultingTransport]:
        with self._lock:
            if not self._transports:
                return None
            if target in self._transports:
                return self._transports[target]
            return None

    def _inject(self, fault: FaultSpec) -> bool:
        kind = fault.kind
        if kind == "env_server_sigkill":
            with self._lock:
                sup = self._server_supervisor
            if sup is None or not getattr(sup, "processes", None):
                return False
            proc = sup.processes[fault.target % len(sup.processes)]
            if not proc.is_alive() or proc.pid is None:
                return False  # mid-respawn: retry next tick
            os.kill(proc.pid, signal.SIGKILL)
            return True
        if kind == "transport_sever":
            native = self._native_pool_handle()
            if native is not None:
                # C++ FaultHooks: shutdown(SHUT_RDWR) on the actor's
                # live transport; False while it is between connections
                # (retry next tick), same as the Python wrap path.
                return bool(native.chaos_sever(fault.target))
            t = self._live_transport(fault.target)
            if t is None:
                return False
            t.sever()
            return True
        if kind in ("transport_blackhole", "transport_delay"):
            native = self._native_pool_handle()
            if native is not None:
                return bool(native.chaos_window(
                    fault.target, kind, fault.duration_s, fault.delay_s
                ))
            if self._live_transport(fault.target) is None:
                return False
            with self._lock:
                self._windows[fault.target] = (
                    kind,
                    time.monotonic() + fault.duration_s,
                    fault.delay_s,
                )
            return True
        if kind in ("shm_corrupt_header", "shm_corrupt_payload"):
            header = kind == "shm_corrupt_header"
            native = self._native_pool_handle()
            if native is not None:
                # ShmRing::corrupt_tail_frame — poke parity with the
                # Python path below, tail-stability checked C++-side.
                return bool(native.chaos_corrupt_ring(
                    fault.target, header
                ))
            t = self._live_transport(fault.target)
            ring = t.recv_ring() if t is not None else None
            if ring is None:
                return False
            return _corrupt_ring(ring, header=header)
        if kind == "state_table_poison":
            with self._lock:
                table = self._state_table
            if table is None:
                return False
            table.poison()
            return True
        if kind == "learner_stall":
            # Armed unconditionally: the gate is pull-based (the driver
            # loops consult throttle()), so there is no handle to wait
            # for — the window simply starts now.
            with self._lock:
                self._stall_until = time.monotonic() + fault.duration_s
            return True
        if kind == "preempt_sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
            return True
        raise ValueError(f"Unknown fault kind {kind!r}")  # pragma: no cover


def _corrupt_ring(ring, header: bool) -> bool:
    """Stomp the frame queued at the ring's tail (False when the ring is
    momentarily empty — the controller retries next tick). Header
    corruption writes an impossible frame length, which the reader's
    next read_frame deterministically rejects as WireError; payload
    corruption flips bytes that decode may or may not notice.

    The post-stomp tail check confirms the bytes landed in a frame the
    reader had not CONSUMED — there remains a narrow window where the
    reader is inside read_frame with the pre-stomp header already
    latched, in which case the fault counts as injected but produces no
    WireError. Corruption faults are therefore injected-exact but only
    recovery-probable; plans that assert recovery == injected should
    use the sever/SIGKILL/poison classes (as chaos_run does)."""
    import struct

    cap = ring.capacity
    tail = ring._u64[ring._TAIL]
    head = ring._u64[ring._HEAD]
    if head - tail < 8:  # need a real frame, not just a marker
        return False
    pos = int(tail % cap)
    if cap - pos < 4:
        pos = 0  # implicit wrap: the frame starts at the ring base
    if header:
        # 0xDEADBEEF: not WRAP/INLINE, way past any sane length.
        # (Stomping a WRAP marker is equally observable: the reader
        # decodes the bogus length and rejects it.)
        ring.poke(pos, (0xDEADBEEF).to_bytes(4, "little"))
    else:
        (length,) = struct.unpack_from("<I", ring._data, pos)
        if length >= ring._INLINE:  # WRAP/INLINE marker: no payload here
            return False
        # Flip at most 4 payload bytes, clamped to the payload AND the
        # data region (a tiny frame near the ring end must not make the
        # poke slice run past either bound).
        n = min(4, int(length), cap - pos - 4)
        if n <= 0:
            return False
        ring.poke(pos + 4, b"\xa5\x5a\xa5\x5a"[:n])
    # If the reader consumed the frame while we were stomping, the bytes
    # landed in free space the producer will overwrite — the fault did
    # NOT observably fire; report failure so the controller retries.
    return ring._u64[ring._TAIL] == tail
