"""Supervised recovery: the pipeline health state machine, the
inference-thread supervisor, and the learner stall watchdog.

Before this module, the failure modes these cover each ended a run its
own way: a poisoned DeviceStateTable killed the inference thread loudly
and the run *wedged* (actors blocked on a batcher nobody drains), a
stalled learner was invisible until someone read the SPS logs, and a
dying actor fleet either went unnoticed or took the whole run down with
the first error. Here every one of them flows through ONE health state
machine:

    HEALTHY --degrade--> DEGRADED --recover--> HEALTHY
        \\                   |
         \\---halt---> HALTED <--halt (terminal)

exported as the `health.state` gauge (0/1/2), with the driver's monitor
loop turning HALTED into a checkpoint-then-clean-exit instead of a hang.
"""

import logging
import sys
import threading
import time
import traceback
from typing import Callable, List, Optional, Tuple

from torchbeast_tpu import telemetry

log = logging.getLogger(__name__)

HEALTHY, DEGRADED, HALTED = 0, 1, 2
STATE_NAMES = {HEALTHY: "HEALTHY", DEGRADED: "DEGRADED", HALTED: "HALTED"}


class PipelineHealth:
    """Thread-safe pipeline health with telemetry export.

    Transitions are logged and counted (`health.transitions`); the
    current state rides the `health.state` gauge (0=HEALTHY,
    1=DEGRADED, 2=HALTED). HALTED is terminal — `halted` is a
    threading.Event the driver's monitor loop waits on so a halt cuts
    the 5s monitor sleep short instead of racing it.
    """

    def __init__(self, registry=None):
        reg = registry if registry is not None else telemetry.get_registry()
        self._gauge = reg.gauge("health.state")
        self._transitions = reg.counter("health.transitions")
        self._lock = threading.Lock()
        self._state = HEALTHY  # guarded-by: self._lock
        self._reasons: List[Tuple[str, str]] = []  # guarded-by: self._lock
        # Active degradation causes, keyed so independent subsystems
        # can't erase each other's DEGRADED state: the stall watchdog
        # recovering must not mask a concurrent poison (and vice
        # versa), and a STICKY cause (actor attrition — retired actors
        # never come back) blocks recovery for the rest of the run.
        # key -> (reason, sticky); guarded-by: self._lock
        self._causes: dict = {}
        self.halted = threading.Event()
        self._gauge.set(HEALTHY)

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    @property
    def state_name(self) -> str:
        return STATE_NAMES[self.state]

    @property
    def is_halted(self) -> bool:
        return self.halted.is_set()

    def reasons(self) -> List[Tuple[str, str]]:
        """(state_name, reason) transition history, oldest first."""
        with self._lock:
            return list(self._reasons)

    def _transition(self, new_state: int, reason: str) -> bool:
        with self._lock:
            if self._state == HALTED:
                return False  # terminal
            if new_state == self._state:
                return False
            self._state = new_state
            self._reasons.append((STATE_NAMES[new_state], reason))
            if len(self._reasons) > 64:
                del self._reasons[:-64]
        self._gauge.set(new_state)
        self._transitions.inc()
        level = logging.ERROR if new_state == HALTED else logging.WARNING
        log.log(
            level, "Pipeline health -> %s: %s",
            STATE_NAMES[new_state], reason,
        )
        if new_state == HALTED:
            self.halted.set()
        return True

    def degrade(self, reason: str, key: Optional[str] = None,
                sticky: bool = False) -> bool:
        """HEALTHY -> DEGRADED (no-op transition when already
        DEGRADED/HALTED, but the cause is recorded either way).

        `key` names the cause so the matching recover(key=...) clears
        exactly it; default is the reason text. `sticky=True` marks a
        permanent cause (retired actors don't come back): it can never
        be cleared, so the run stays DEGRADED until halt."""
        with self._lock:
            self._causes[key or reason] = (reason, sticky)
        return self._transition(DEGRADED, reason)

    def recover(self, reason: str, key: Optional[str] = None) -> bool:
        """Clear a degradation cause; DEGRADED -> HEALTHY only once NO
        cause remains (a stall recovering must not mask a concurrent
        poison, and sticky causes block recovery for good). `key=None`
        clears every non-sticky cause (a caller-agnostic all-clear).
        Never leaves HALTED."""
        with self._lock:
            if key is None:
                self._causes = {
                    k: v for k, v in self._causes.items() if v[1]
                }
            else:
                entry = self._causes.get(key)
                if entry is not None and not entry[1]:
                    del self._causes[key]
            remaining = [r for r, _ in self._causes.values()]
        if remaining:
            log.warning(
                "Health: %s, but staying DEGRADED (remaining: %s)",
                reason, "; ".join(remaining),
            )
            return False
        return self._transition(HEALTHY, reason)

    def halt(self, reason: str) -> bool:
        """Terminal: the driver checkpoints and exits cleanly."""
        return self._transition(HALTED, reason)


def dump_thread_stacks(header: str) -> None:
    """Log every live thread's stack — the stall watchdog's diagnostic
    dump (where exactly is the pipeline stuck?)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    lines = [header]
    for ident, frame in frames.items():
        lines.append(
            f"--- thread {names.get(ident, '?')} ({ident}) ---"
        )
        lines.append("".join(traceback.format_stack(frame)))
    log.error("%s", "\n".join(lines))


class InferenceSupervisor:
    """Run N serving-loop threads and recover a poisoned state table.

    The DeviceStateTable donates its buffer into every dispatch, so a
    failed dispatch poisons it and the serving loop re-raises rather
    than serve garbage (runtime/inference.py). Before this supervisor
    that re-raise ended the thread AND the run: actors blocked forever
    on a batcher nobody drained. Now the supervisor catches the typed
    poison error, rebuilds the table from initial state (all actor
    slots reset — in-flight rollouts restart from the failed batch's
    retry path), and restarts the thread, under `restart_budget`
    rebuilds per run. Budget exhaustion transitions health to HALTED so
    the driver checkpoints and exits instead of hanging.

    `loop_fn()` is one serving loop (it returns when the batcher
    closes); the supervisor owns the threads so the driver never touches
    raw inference threads again.

    Telemetry: `recovery.table_rebuilds` and
    `recovery.inference_restarts` each count ONE per poison event
    (sibling threads re-entering after a rebuild don't re-count), which
    is what lets the chaos harness assert recovery == injected exactly.
    """

    def __init__(
        self,
        loop_fn: Callable[[], None],
        num_threads: int,
        state_table=None,
        restart_budget: int = 3,
        health: Optional[PipelineHealth] = None,
        registry=None,
        name: str = "inference",
        extra_loop_fns: Optional[List[Callable[[], None]]] = None,
    ):
        # `extra_loop_fns` (ISSUE 14): replica serving loops ride the
        # SAME supervisor as the central ones — they share the state
        # table, so a poison event must rebuild once and restart ALL
        # serving threads under one budget/generation, not race two
        # supervisors over the same table.
        self._loops = [loop_fn] * num_threads + list(extra_loop_fns or [])
        self._num_threads = len(self._loops)
        self._table = state_table
        self._budget = restart_budget
        self._health = health
        self._name = name
        reg = registry if registry is not None else telemetry.get_registry()
        self._tm_rebuilds = reg.counter("recovery.table_rebuilds")
        self._tm_restarts = reg.counter("recovery.inference_restarts")
        self._lock = threading.Lock()
        self._restarts = 0  # guarded-by: self._lock
        self._recovery_gen = 0  # guarded-by: self._lock
        self._exhausted = False  # guarded-by: self._lock
        # Appended by N serving threads, polled by the driver monitor
        # (RACE burn-down, ISSUE 7): exposed through the locked
        # `errors` property.
        self._errors: List[BaseException] = []  # guarded-by: self._lock
        self._threads: List[threading.Thread] = []

    @property
    def errors(self) -> List[BaseException]:
        with self._lock:
            return list(self._errors)

    @property
    def restarts(self) -> int:
        with self._lock:
            return self._restarts

    def start(self) -> None:
        self._threads = [
            threading.Thread(
                target=self._run, args=(i,), daemon=True,
                name=f"{self._name}-{i}",
            )
            for i in range(len(self._loops))
        ]
        for t in self._threads:
            t.start()

    def alive_count(self) -> int:
        return sum(1 for t in self._threads if t.is_alive())

    def join(self, timeout: Optional[float] = None) -> None:
        for t in self._threads:
            t.join(timeout=timeout)

    @staticmethod
    def _is_poison_error(e: BaseException) -> bool:
        # runtime.errors is jax-free, so this never drags jax into a
        # process that only supervises.
        from torchbeast_tpu.runtime.errors import StateTablePoisonedError

        return isinstance(e, StateTablePoisonedError)

    def _run(self, index: int) -> None:
        loop_fn = self._loops[index]
        while True:
            with self._lock:
                gen = self._recovery_gen
            try:
                loop_fn()
                return  # batcher closed: clean shutdown
            except BaseException as e:  # noqa: BLE001
                if self._is_poison_error(e) or (
                    self._table is not None
                    and getattr(self._table, "poisoned", False)
                ):
                    if self._recover(index, gen):
                        continue
                    return  # budget exhausted; health already HALTED
                # Not a poisoning: a real serving bug. Record it and die
                # loudly; actors drain their retry budgets against the
                # survivors and the health machine degrades from there.
                with self._lock:
                    self._errors.append(e)
                log.exception(
                    "Inference thread %d failed (unrecoverable)", index
                )
                if self._health is not None and self.alive_count() <= 1:
                    # alive_count still includes this dying thread.
                    self._health.halt(
                        f"all inference threads dead (last error: {e})"
                    )
                raise

    def _recover(self, index: int, gen_at_entry: int) -> bool:
        """Rebuild the poisoned table (once per poison event) and tell
        the calling thread whether to re-enter its serving loop."""
        with self._lock:
            if self._exhausted:
                return False
            table = self._table
            if table is None or not getattr(table, "poisoned", False):
                # A sibling already rebuilt for this poison event (our
                # generation predates its recovery): just re-enter.
                if self._recovery_gen != gen_at_entry:
                    return True
                return False
            if self._restarts >= self._budget:
                self._exhausted = True
                if self._health is not None:
                    self._health.halt(
                        "inference restart budget exhausted "
                        f"({self._restarts}/{self._budget} rebuilds)"
                    )
                return False
            self._restarts += 1
            self._recovery_gen += 1
            table.rebuild()
            self._tm_rebuilds.inc()
            self._tm_restarts.inc()
            n = self._restarts
        if self._health is not None:
            self._health.degrade(
                f"state table poisoned; rebuilt "
                f"(restart {n}/{self._budget})",
                key="state_table_poison",
            )
            self._health.recover(
                "inference restarted on the rebuilt state table",
                key="state_table_poison",
            )
        log.warning(
            "Inference thread %d: state table poisoned; rebuilt from "
            "initial state and restarting (restart %d/%d)",
            index, n, self._budget,
        )
        return True


class LearnerWatchdog:
    """Detect a stalled learner: no `ping()` within `deadline_s`.

    The learner loop pings once per update dispatch. A stall (actor
    starvation, a wedged queue, a hung collective) transitions health
    to DEGRADED with a structured reason, dumps every thread's stack
    plus the caller's `dump_fn()` diagnostics, and counts
    `learner.stalls`; pings resuming transitions back to HEALTHY. The
    watchdog never halts on its own — stall length is workload-relative
    and the min-live-actors / inference-budget paths own terminal
    decisions.

    `deadline_s <= 0` disables the watchdog (start() is a no-op).
    """

    def __init__(
        self,
        deadline_s: float,
        health: Optional[PipelineHealth] = None,
        dump_fn: Optional[Callable[[], dict]] = None,
        registry=None,
        name: str = "learner",
    ):
        self.deadline_s = deadline_s
        self._health = health
        self._dump_fn = dump_fn
        self._name = name
        reg = registry if registry is not None else telemetry.get_registry()
        self._tm_stalls = reg.counter("learner.stalls")
        self._last_ping = time.monotonic()
        self._stalled = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def ping(self) -> None:
        # beastlint: disable=RACE  single-writer monotonic float: only the learner thread writes at runtime, the GIL makes the store atomic, and the watchdog reading one stale value merely delays stall detection by a poll tick
        self._last_ping = time.monotonic()

    @property
    def stalled(self) -> bool:
        return self._stalled

    def start(self) -> "LearnerWatchdog":
        if self.deadline_s <= 0:
            return self
        self._last_ping = time.monotonic()  # the clock starts now
        self._thread = threading.Thread(
            target=self._watch, daemon=True, name=f"{self._name}-watchdog"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _watch(self) -> None:
        poll = max(0.1, min(self.deadline_s / 4.0, 5.0))
        while not self._stop.wait(poll):
            idle = time.monotonic() - self._last_ping
            if not self._stalled and idle > self.deadline_s:
                self._stalled = True
                self._tm_stalls.inc()
                reason = (
                    f"{self._name} made no update dispatch for "
                    f"{idle:.1f}s (deadline {self.deadline_s}s)"
                )
                if self._health is not None:
                    self._health.degrade(
                        reason, key=f"{self._name}_stall"
                    )
                self._dump(reason)
            elif self._stalled and idle <= self.deadline_s:
                self._stalled = False
                if self._health is not None:
                    self._health.recover(
                        f"{self._name} update dispatches resumed",
                        key=f"{self._name}_stall",
                    )

    def _dump(self, reason: str) -> None:
        diag = ""
        if self._dump_fn is not None:
            try:
                diag = f"\ndiagnostics: {self._dump_fn()}"
            except Exception:  # noqa: BLE001
                log.exception("Watchdog dump_fn failed")
        dump_thread_stacks(f"Learner stall watchdog fired: {reason}{diag}")
