"""Exponential backoff with decorrelated jitter and an optional deadline.

The repo had two retry loops and both were wrong in the same way: an
actor whose env server died re-dialed the dead address as fast as
`connect_transport` would fail (a tight loop against a refused socket),
and the env-server supervisor respawned a crash-looping child every
poll tick. Worse, a mass server restart woke every actor at once — a
thundering herd against the fresh listener. Decorrelated jitter
(`sleep = uniform(base, prev * 3)`, capped) spreads the herd and grows
the idle period geometrically, while the deadline turns "retry forever"
into a bounded budget that surfaces as a typed error.

Stdlib-only and side-effect-free except for `time.sleep`, so every
retry loop in runtime/ and polybeast_env can adopt it without new deps.
"""

import random
import threading
import time
from typing import Optional


class BackoffDeadline(TimeoutError):
    """Raised by `Backoff.sleep()` once the total-elapsed deadline has
    passed: the caller's retry budget is exhausted."""


class Backoff:
    """Decorrelated-jitter exponential backoff.

    next_delay() draws `uniform(base_s, prev * 3)` clamped to
    [base_s, cap_s] — the AWS "decorrelated jitter" variant, which both
    spreads synchronized retriers apart and keeps the expected delay
    growing geometrically. `reset()` re-arms after proven recovery (the
    actor pool resets once a full unroll has streamed, mirroring its
    reconnect-budget refill).

    `deadline_s` bounds TOTAL time spent sleeping + waiting since the
    first `sleep()` after construction/reset; exceeding it raises
    BackoffDeadline instead of sleeping again.

    `rng`: pass a seeded `random.Random` for deterministic schedules
    (chaos harness / tests); default draws fresh entropy.
    """

    def __init__(
        self,
        base_s: float = 0.1,
        cap_s: float = 5.0,
        deadline_s: Optional[float] = None,
        rng: Optional[random.Random] = None,
    ):
        if base_s <= 0:
            raise ValueError(f"base_s must be > 0, got {base_s}")
        if cap_s < base_s:
            raise ValueError(
                f"cap_s {cap_s} must be >= base_s {base_s}"
            )
        self.base_s = base_s
        self.cap_s = cap_s
        self.deadline_s = deadline_s
        self._rng = rng if rng is not None else random.Random()
        self._prev = 0.0
        self._started = None  # first sleep() since reset
        self.attempts = 0

    def next_delay(self) -> float:
        """The next jittered delay (advances the schedule, no sleeping)."""
        hi = max(self.base_s, min(self.cap_s, self._prev * 3.0))
        delay = self._rng.uniform(self.base_s, hi)
        self._prev = delay
        self.attempts += 1
        return delay

    def sleep(self, wake: Optional[threading.Event] = None) -> float:
        """Sleep the next jittered delay; returns the delay slept.

        `wake`: an optional Event that cuts the sleep short (pipeline
        shutdown must not wait out a backoff). Raises BackoffDeadline
        when the cumulative elapsed time since the first sleep (after
        construction or reset()) exceeds deadline_s.
        """
        now = time.monotonic()
        if self._started is None:
            self._started = now
        if (
            self.deadline_s is not None
            and now - self._started > self.deadline_s
        ):
            raise BackoffDeadline(
                f"backoff deadline of {self.deadline_s}s exceeded after "
                f"{self.attempts} attempts"
            )
        delay = self.next_delay()
        if wake is not None:
            wake.wait(delay)
        else:
            time.sleep(delay)
        return delay

    def reset(self) -> None:
        """Re-arm after proven recovery: the next delay starts from
        base_s again and the deadline window restarts."""
        self._prev = 0.0
        self._started = None
        self.attempts = 0
