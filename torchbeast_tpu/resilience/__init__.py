"""Chaos-hardened pipeline machinery (ISSUE 6 tentpole).

IMPALA's headline claim is tolerance to actor failure at datacenter
scale, and Podracer-style fleets run on preemptible TPUs where workers
die as a matter of course — yet failure handling used to be scattered
per-component and nothing ever exercised those paths together. This
package is the missing layer:

- `backoff`:    exponential backoff with decorrelated jitter + deadline,
                adopted by the actor reconnect loop and env-server
                respawn (a mass server restart must not thundering-herd
                the listener; a dead address must not be re-dialed in a
                tight loop).
- `supervisor`: the pipeline health state machine
                (HEALTHY/DEGRADED/HALTED, exported as a gauge), the
                inference-thread supervisor that rebuilds a poisoned
                DeviceStateTable under a bounded budget, and the
                learner stall watchdog.
- `chaos`:      deterministic, seeded fault injection (`FaultPlan`,
                JSON-loadable via `--chaos_plan`): env-server SIGKILL,
                transport sever/blackhole/delay, shm-ring corruption,
                state-table poisoning, mid-run SIGTERM — every injected
                fault counted in telemetry so recovery can be asserted
                exactly (scripts/chaos_run.py).

Stays importable without jax: only `supervisor` touches device state,
and only through the DeviceStateTable handle it is given.
"""

from torchbeast_tpu.resilience.backoff import (  # noqa: F401
    Backoff,
    BackoffDeadline,
)
from torchbeast_tpu.resilience.chaos import (  # noqa: F401
    FAULT_KINDS,
    ChaosController,
    FaultingTransport,
    FaultPlan,
    FaultSpec,
)
from torchbeast_tpu.resilience.supervisor import (  # noqa: F401
    InferenceSupervisor,
    LearnerWatchdog,
    PipelineHealth,
)
