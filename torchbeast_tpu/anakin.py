"""Anakin: the fully-on-TPU IMPALA trainer for jittable environments.

The Podracer "Anakin" architecture (arXiv:2104.06272): when the env itself
is a JAX function, the ENTIRE actor-learner iteration — vmapped env steps,
policy forward, rollout assembly, V-trace, losses, optimizer update — fuses
into one jitted XLA program with `lax.scan` over the unroll. No host in the
loop at all; multi-chip scaling is the same replicated-params /
batch-sharded jit as the poly learner (parallel/dp.py). Nothing in the
reference corresponds to this: it is the capability the TPU-first design
unlocks (its envs are C++/OpenCV-bound, SURVEY.md §7 design stance).

The rollout kept on device preserves the same batch layout and on-policy
invariants as the host-side collectors (slot 0 = boundary step, agent
output at slot i computed from env output at slot i-1), so the SAME
learner.compute_loss is reused unchanged.

Run:  python -m torchbeast_tpu.anakin --env Catch --total_steps 200000
"""

import argparse
import logging
import os
import time
from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from torchbeast_tpu import learner as learner_lib
from torchbeast_tpu.envs.jax_env import create_jax_env
from torchbeast_tpu.models import create_model
from torchbeast_tpu.utils import (
    FileWriter,
    load_checkpoint,
    save_checkpoint,
)

log = logging.getLogger("torchbeast_tpu.anakin")


def _configure_logging():
    """Called from main(), NOT at import: importing this module (as
    every test does) must not mutate global logging state."""
    logging.basicConfig(
        format=(
            "[%(levelname)s:%(process)d %(module)s:%(lineno)d "
            "%(asctime)s] %(message)s"
        ),
        level=logging.INFO,
    )


def _agent_out_dict(out):
    return {
        "action": out.action,
        "policy_logits": out.policy_logits,
        "baseline": out.baseline,
    }


class ActorCarry(NamedTuple):
    """Cross-update actor state (the on-device analog of the rollout
    collector's pending env/agent outputs + recurrent state)."""

    env_state: Any
    env_out: Any  # dict of [B, ...]
    agent_out: Any  # dict of [B, ...]
    agent_state: Any
    rng: Any


def make_parser():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--env", default="Catch")
    parser.add_argument("--xpid", default=None)
    parser.add_argument("--savedir", default="~/logs/torchbeast_tpu")
    parser.add_argument("--total_steps", type=int, default=200000)
    parser.add_argument("--batch_size", type=int, default=64,
                        help="Parallel on-device environments.")
    parser.add_argument("--unroll_length", type=int, default=16)
    parser.add_argument("--model", default="mlp",
                        choices=["mlp", "shallow", "deep", "pipelined_mlp", "transformer"])
    parser.add_argument("--use_lstm", action="store_true")
    parser.add_argument("--num_experts", type=int, default=0,
                        help="Transformer-only: top-2 MoE FFN with N "
                             "experts (load-balance loss in objective).")
    parser.add_argument("--seed", type=int, default=1234)
    parser.add_argument("--num_devices", type=int, default=1,
                        help="Data-parallel devices (envs sharded, params "
                             "replicated, ICI all-reduce).")
    parser.add_argument("--checkpoint_interval_s", type=int, default=600)
    parser.add_argument("--log_interval_updates", type=int, default=20)
    # Loss/optimizer knobs (reference defaults).
    parser.add_argument("--entropy_cost", type=float, default=0.0006)
    parser.add_argument("--entropy_cost_final", type=float, default=None,
                        help="Linearly anneal entropy cost to this over "
                             "total_steps (default: constant).")
    parser.add_argument("--baseline_cost", type=float, default=0.5)
    parser.add_argument("--discounting", type=float, default=0.99)
    parser.add_argument("--reward_clipping", default="abs_one",
                        choices=["abs_one", "none"])
    parser.add_argument("--learning_rate", type=float, default=4.8e-4)
    parser.add_argument("--alpha", type=float, default=0.99)
    parser.add_argument("--momentum", type=float, default=0.0)
    parser.add_argument("--epsilon", type=float, default=0.01)
    parser.add_argument("--grad_norm_clipping", type=float, default=40.0)
    return parser


def make_train_step(env, model, optimizer, hp: learner_lib.HParams, mesh=None):
    """One fused iteration: T env/policy steps (scan) + learner update.

    (params, opt_state, carry) -> (params, opt_state, carry, stats)
    """
    T = hp.unroll_length

    def policy_step(params, rng, env_out, agent_state):
        """T=1 forward on [B, ...] env outputs (shared learner.act_body)."""
        inputs = {
            k: env_out[k]
            for k in ("frame", "reward", "done", "last_action")
        }
        out, new_state = learner_lib.act_body(
            model, params, rng, inputs, agent_state
        )
        return _agent_out_dict(out), new_state

    def rollout_step(params, carry: ActorCarry, _):
        rng, key = jax.random.split(carry.rng)
        agent_out, agent_state = policy_step(
            params, key, carry.env_out, carry.agent_state
        )
        env_state, env_out = jax.vmap(env.step)(
            carry.env_state, agent_out["action"]
        )
        new_carry = ActorCarry(
            env_state=env_state,
            env_out=env_out,
            agent_out=agent_out,
            agent_state=agent_state,
            rng=rng,
        )
        # Emitted slot pairs env output i with the agent output computed
        # from env output i-1 (collector pairing invariant).
        return new_carry, (env_out, agent_out)

    def train_step(params, opt_state, carry: ActorCarry):
        initial_agent_state = carry.agent_state
        boundary = (carry.env_out, carry.agent_out)

        carry, (env_seq, agent_seq) = jax.lax.scan(
            partial(rollout_step, params), carry, None, length=T
        )

        # Prepend the boundary step -> [T+1, B, ...] learner batch.
        batch = {
            k: jnp.concatenate([boundary[0][k][None], env_seq[k]], axis=0)
            for k in boundary[0]
        }
        for k in boundary[1]:
            batch[k] = jnp.concatenate(
                [boundary[1][k][None], agent_seq[k]], axis=0
            )

        grads, stats = jax.grad(
            lambda p: learner_lib.compute_loss(
                model, p, batch, initial_agent_state, hp,
                entropy_cost=learner_lib.entropy_schedule(hp)(opt_state),
            ),
            has_aux=True,
        )(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        stats["grad_norm"] = optax.global_norm(grads)
        return params, opt_state, carry, stats

    if mesh is None:
        return jax.jit(train_step, donate_argnums=(0, 1, 2))

    from torchbeast_tpu.parallel import mesh as mesh_lib

    repl = mesh_lib.replicated(mesh)
    data = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("data")
    )
    state_sh = mesh_lib.state_sharding(mesh)

    carry_shardings = ActorCarry(
        env_state=data, env_out=data, agent_out=data,
        agent_state=state_sh, rng=repl,
    )
    return jax.jit(
        train_step,
        in_shardings=(repl, repl, carry_shardings),
        out_shardings=(repl, repl, carry_shardings, repl),
        donate_argnums=(0, 1, 2),
    )


def initial_carry(env, model, batch_size: int, rng):
    """Reset all envs + prime the boundary agent output (state advance
    discarded, collector convention). Param-init keys derive from `rng`,
    so --seed changes the initialization like the host drivers."""
    rng, env_key, prime_key, init_key, action_key = jax.random.split(rng, 5)
    env_keys = jax.random.split(env_key, batch_size)

    def init_one(key):
        return env.initial(key)

    env_state, env_out = jax.vmap(init_one)(env_keys)
    agent_state = model.initial_state(batch_size)

    model_inputs = {
        k: env_out[k]
        for k in ("frame", "reward", "done", "last_action")
    }
    params = model.init(
        {"params": init_key, "action": action_key},
        {k: v[None] for k, v in model_inputs.items()},
        agent_state,
    )
    out, _ = learner_lib.act_body(
        model, params, prime_key, model_inputs, agent_state
    )
    agent_out = _agent_out_dict(out)
    carry = ActorCarry(
        env_state=env_state,
        env_out=env_out,
        agent_out=agent_out,
        agent_state=agent_state,
        rng=rng,
    )
    return params, carry


def train(flags):
    if flags.xpid is None:
        flags.xpid = "anakin-%s" % time.strftime("%Y%m%d-%H%M%S")
    plogger = FileWriter(
        xpid=flags.xpid, xp_args=vars(flags), rootdir=flags.savedir
    )
    checkpoint_path = os.path.join(
        os.path.expanduser(flags.savedir), flags.xpid, "model.ckpt"
    )

    env = create_jax_env(flags.env)
    hp = learner_lib.HParams(
        discounting=flags.discounting,
        baseline_cost=flags.baseline_cost,
        entropy_cost=flags.entropy_cost,
        entropy_cost_final=getattr(flags, "entropy_cost_final", None),
        reward_clipping=flags.reward_clipping,
        learning_rate=flags.learning_rate,
        rmsprop_alpha=flags.alpha,
        rmsprop_eps=flags.epsilon,
        rmsprop_momentum=flags.momentum,
        grad_norm_clipping=flags.grad_norm_clipping,
        total_steps=flags.total_steps,
        unroll_length=flags.unroll_length,
        batch_size=flags.batch_size,
    )
    extra = {}
    if getattr(flags, "num_experts", 0):
        if flags.model != "transformer":
            raise ValueError(
                "--num_experts applies to --model transformer only"
            )
        extra["num_experts"] = flags.num_experts
    model = create_model(
        flags.model, num_actions=env.num_actions, use_lstm=flags.use_lstm,
        **extra,
    )
    optimizer = learner_lib.make_optimizer(hp)

    mesh = None
    if flags.num_devices > 1:
        from torchbeast_tpu.parallel import create_mesh

        if flags.batch_size % flags.num_devices != 0:
            raise ValueError(
                f"batch_size {flags.batch_size} not divisible by "
                f"num_devices {flags.num_devices}"
            )
        mesh = create_mesh(flags.num_devices)
        log.info("Anakin over %d devices", flags.num_devices)

    rng = jax.random.PRNGKey(flags.seed)
    params, carry = initial_carry(env, model, flags.batch_size, rng)
    opt_state = optimizer.init(params)

    step = 0
    if os.path.exists(checkpoint_path):
        restored = load_checkpoint(
            checkpoint_path,
            params_template=params,
            opt_state_template=opt_state,
        )
        params, opt_state = restored["params"], restored["opt_state"]
        step = restored["step"]
        log.info("Resuming preempted job at step %d", step)

    if mesh is not None:
        from torchbeast_tpu.parallel import replicate

        params = replicate(mesh, params)
        opt_state = replicate(mesh, opt_state)
        # Shard the carry along the env-batch axis.
        train_step = make_train_step(env, model, optimizer, hp, mesh)
    else:
        train_step = make_train_step(env, model, optimizer, hp)

    frames_per_update = flags.unroll_length * flags.batch_size
    last_log_time = time.time()
    last_log_step = step
    last_checkpoint = time.time()
    stats_host = {}

    try:
        successful = True
        update = 0
        while step < flags.total_steps:
            params, opt_state, carry, stats = train_step(
                params, opt_state, carry
            )
            step += frames_per_update
            update += 1

            if update % flags.log_interval_updates == 0:
                stats_host = learner_lib.episode_stat_postprocess(
                    jax.device_get(stats)
                )
                stats_host["step"] = step
                plogger.log(stats_host)

                now = time.time()
                if now - last_log_time > 5:
                    sps = (step - last_log_step) / (now - last_log_time)
                    last_log_time, last_log_step = now, step
                    log.info(
                        "Steps %d @ %.1f SPS. Loss %.4f. %s",
                        step, sps,
                        stats_host.get("total_loss", float("nan")),
                        f"Return {stats_host['mean_episode_return']:.2f}."
                        if "mean_episode_return" in stats_host else "",
                    )
                if now - last_checkpoint > flags.checkpoint_interval_s:
                    save_checkpoint(
                        checkpoint_path,
                        params=params, opt_state=opt_state, step=step,
                        flags=vars(flags), stats=stats_host,
                    )
                    last_checkpoint = now
    except KeyboardInterrupt:
        pass
    except BaseException:
        successful = False
        raise
    finally:
        try:
            save_checkpoint(
                checkpoint_path,
                params=params, opt_state=opt_state, step=step,
                flags=vars(flags), stats=stats_host,
            )
        except Exception:
            # An interrupt mid-train_step can leave params pointing at
            # donated (deleted) buffers; losing the exit checkpoint must
            # not also lose the logger close.
            log.exception("Final checkpoint failed")
        plogger.close(successful=successful)
    log.info("Learning finished after %d steps.", step)
    stats_host["step"] = step
    return stats_host


def main(flags):
    _configure_logging()
    return train(flags)


def cli():
    from torchbeast_tpu.utils import install_preemption_handler

    install_preemption_handler()  # SIGTERM -> clean checkpointed exit
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    main(make_parser().parse_args())


if __name__ == "__main__":
    cli()
