"""Pipeline span tracing, exportable as Chrome trace-event JSON.

Two granularities:

- `Tracer.span(name)` — a context-managed duration span on the calling
  thread (nesting renders as stacked bars in chrome://tracing /
  Perfetto, which nest "X" events on one tid by containment).
- `Tracer.stage(name)` — a StageTrace that travels WITH a request
  across threads: each pipeline stage calls `.stamp("stage")` as the
  request passes (actor -> wire -> inference-queue -> batch -> dispatch
  -> reply; learner dequeue -> stage -> update), and `.finish()` emits
  one span per consecutive stamp pair. This is how a single slow
  request's time is attributed to queue wait vs. batch wait vs. reply.

Events land in a bounded ring buffer (old events drop, hot paths never
block or grow memory); `export_chrome(path)` writes the standard
{"traceEvents": [...]} JSON that chrome://tracing and Perfetto load
directly. Orphaned spans (begun, never ended) are tracked and counted
but never exported — a crashed stage can't leave half-open garbage in
the trace. stdlib only; timestamps are perf_counter-based (monotonic),
mapped once to the wall clock for the export's displayTimeUnit.
"""

import collections
import contextlib
import itertools
import json
import threading
import time
from typing import Dict, List, Optional

from torchbeast_tpu.telemetry.metrics import _ENABLED


class _OpenSpan:
    __slots__ = ("name", "cat", "start", "tid", "args", "ended")

    def __init__(self, name, cat, start, tid, args):
        self.name = name
        self.cat = cat
        self.start = start
        self.tid = tid
        self.args = args
        self.ended = False


class StageTrace:
    """Stamps one request's passage through named pipeline stages.

    Thread-safe by handoff: exactly one thread holds the request at a
    time (the same discipline the request payload itself rides on), so
    stamps append without a lock. `finish()` (idempotent) emits the
    per-stage spans into the owning tracer.
    """

    __slots__ = ("_tracer", "name", "_stamps", "_done", "args")

    def __init__(self, tracer: "Tracer", name: str, **args):
        self._tracer = tracer
        self.name = name
        self._stamps = [("start", time.perf_counter())]
        self._done = False
        self.args = args or None

    def stamp(self, stage: str) -> None:
        if not self._done:
            self._stamps.append((stage, time.perf_counter()))

    def stages(self) -> List[str]:
        return [s for s, _ in self._stamps[1:]]

    def finish(self) -> None:
        if self._done:
            return
        self._done = True
        prev_t = self._stamps[0][1]
        for stage, t in self._stamps[1:]:
            self._tracer.add_complete(
                f"{self.name}.{stage}", self.name, prev_t, t - prev_t,
                args=self.args,
            )
            prev_t = t
        if len(self._stamps) > 1:
            self._tracer.add_complete(
                self.name, self.name, self._stamps[0][1],
                self._stamps[-1][1] - self._stamps[0][1], args=self.args,
            )


class Tracer:
    def __init__(self, max_events: int = 32768, gated: bool = False):
        self._events = collections.deque(maxlen=max_events)
        self._gated = gated
        self._ids = itertools.count(1)
        self._open: Dict[int, _OpenSpan] = {}
        self._open_lock = threading.Lock()
        self._tid_lock = threading.Lock()
        self._tids: Dict[int, int] = {}
        # One perf_counter<->wall-clock correspondence for the export.
        self._wall_at_zero = time.time() - time.perf_counter()

    def enabled(self) -> bool:
        return not (self._gated and not _ENABLED[0])

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._tid_lock:
                tid = self._tids.setdefault(ident, len(self._tids) + 1)
        return tid

    def add_complete(
        self, name: str, cat: str, start: float, dur: float,
        tid: Optional[int] = None, args: Optional[dict] = None,
    ) -> None:
        """Record a completed span (Chrome 'X' event). `start` is a
        perf_counter timestamp; `dur` seconds."""
        if not self.enabled():
            return
        event = {
            "name": name,
            "cat": cat or "span",
            "ph": "X",
            "ts": start * 1e6,
            "dur": max(dur, 0.0) * 1e6,
            "pid": 0,
            "tid": tid if tid is not None else self._tid(),
        }
        if args:
            event["args"] = dict(args)
        self._events.append(event)

    def begin(self, name: str, cat: str = "", **args) -> Optional[int]:
        """Open a span by token (for spans that end on another code
        path). Returns the token, or None when tracing is disabled."""
        if not self.enabled():
            return None
        token = next(self._ids)
        span = _OpenSpan(
            name, cat, time.perf_counter(), self._tid(), args or None
        )
        with self._open_lock:
            self._open[token] = span
        return token

    def end(self, token: Optional[int], **args) -> bool:
        """Close a span opened with begin(). Unknown/already-ended/None
        tokens are a no-op (returns False) — double-end can't corrupt
        the trace."""
        if token is None:
            return False
        with self._open_lock:
            span = self._open.pop(token, None)
        if span is None or span.ended:
            return False
        span.ended = True
        merged = dict(span.args or {})
        merged.update(args)
        self.add_complete(
            span.name, span.cat, span.start,
            time.perf_counter() - span.start,
            tid=span.tid, args=merged or None,
        )
        return True

    def open_count(self) -> int:
        """Spans begun but not yet ended (orphans, if it stays > 0)."""
        with self._open_lock:
            return len(self._open)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "", **args):
        """Duration span on the calling thread; nests naturally."""
        if not self.enabled():
            yield
            return
        start = time.perf_counter()
        try:
            yield
        finally:
            self.add_complete(
                name, cat, start, time.perf_counter() - start,
                args=args or None,
            )

    def stage(self, name: str, **args) -> Optional[StageTrace]:
        """A cross-thread request trace; None when disabled so call
        sites guard with `if trace is not None`."""
        if not self.enabled():
            return None
        return StageTrace(self, name, **args)

    def events(self) -> List[dict]:
        return list(self._events)

    def clear(self) -> None:
        self._events.clear()

    def export_chrome(self, path: str) -> int:
        """Write {"traceEvents": [...]} (chrome://tracing / Perfetto
        format). Returns the number of events written."""
        events = self.events()
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "wall_time_at_ts_zero": self._wall_at_zero,
                "open_spans_dropped": self.open_count(),
            },
        }
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(events)


# Process-wide tracer, gated with the metrics registry.
_GLOBAL = Tracer(gated=True)


def get_tracer() -> Tracer:
    return _GLOBAL
