"""Process-wide observability: metrics registry, pipeline span tracing,
and exporters (ISSUE 2 tentpole).

Three modules, stdlib-only (no jax/numpy — instrumentation inside the
acting hot path must never trigger a device sync or heavyweight import;
pinned by tests/test_telemetry.py):

- metrics: Counter/Gauge/Histogram with per-thread shards (no hot-path
  locks) and mergeable log-bucketed histograms (p50/p95/p99).
- trace:   duration spans + cross-thread StageTraces, exportable as
  Chrome trace-event JSON (chrome://tracing / Perfetto).
- export:  snapshot / delta / merge, the JSON-lines exporter FileWriter
  hosts (`{xpid}/telemetry.jsonl`), a Prometheus-text HTTP endpoint
  (--telemetry_port), and a `--selftest` CLI.

Typical call-site shape (instruments are resolved once, used forever):

    from torchbeast_tpu import telemetry
    _reg = telemetry.get_registry()
    _rtt = _reg.histogram("actor.request_rtt_s")
    ...
    _rtt.observe(dt)

`set_enabled(False)` (the drivers' --no_telemetry) turns every
global-registry instrument and the global tracer into no-ops; private
MetricsRegistry()/Tracer() instances ignore the gate.
"""

from torchbeast_tpu.telemetry.driver import (  # noqa: F401
    DriverTelemetry,
    add_arguments,
)
from torchbeast_tpu.telemetry.export import (  # noqa: F401
    JsonLinesExporter,
    PrometheusServer,
    SCHEMA_VERSION,
    delta,
    merge_snapshots,
    read_jsonl,
    render_prometheus,
    snapshot,
    telemetry_block,
    validate_snapshot,
)
from torchbeast_tpu.telemetry.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    is_enabled,
    set_enabled,
)
from torchbeast_tpu.telemetry.trace import (  # noqa: F401
    StageTrace,
    Tracer,
    get_tracer,
)
