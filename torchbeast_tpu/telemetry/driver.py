"""Shared telemetry lifecycle for the training drivers.

One implementation of the flags + setup/tick/shutdown sequence both
monobeast and polybeast run, so the two can't drift (and fixes land
once): `add_arguments` contributes the --telemetry/--no_telemetry/
--telemetry_port/--trace_path stanza to a driver parser;
`DriverTelemetry` owns the exporter, the optional Prometheus endpoint
(bind failures DEGRADE to a warning — an observability port conflict
must never abort a training run), and the guarded shutdown writes.
stdlib-only, like the rest of the package.
"""

import logging
from typing import Dict, Optional

from torchbeast_tpu.telemetry.export import (
    JsonLinesExporter,
    PrometheusServer,
)
from torchbeast_tpu.telemetry.metrics import (
    MetricsRegistry,
    get_registry,
    set_enabled,
)
from torchbeast_tpu.telemetry.trace import get_tracer

log = logging.getLogger(__name__)


def add_arguments(parser) -> None:
    """The telemetry flag stanza shared by every driver parser."""
    parser.add_argument("--telemetry", dest="telemetry",
                        action="store_true", default=True,
                        help="Process-wide metrics + span tracing "
                             "(default): queue depths, batch-size "
                             "distribution, stage latencies, wire "
                             "bytes; snapshots append to "
                             "{xpid}/telemetry.jsonl every monitor/"
                             "log tick. See README \"Telemetry\".")
    parser.add_argument("--no_telemetry", dest="telemetry",
                        action="store_false",
                        help="Disable all instrumentation (global "
                             "registry and tracer become no-ops).")
    parser.add_argument("--telemetry_port", type=int, default=0,
                        help="Serve a Prometheus-text /metrics HTTP "
                             "endpoint on this port (0 = off).")
    parser.add_argument("--telemetry_host", default="127.0.0.1",
                        help="Bind address for /metrics (default "
                             "loopback; the endpoint is "
                             "unauthenticated — pass 0.0.0.0 only to "
                             "deliberately expose it for remote "
                             "scraping).")
    parser.add_argument("--trace_path", default=None,
                        help="Write a Chrome trace-event JSON of the "
                             "run's recorded spans here at shutdown "
                             "(open in chrome://tracing or Perfetto).")


class DriverTelemetry:
    """Setup/tick/shutdown of a driver's telemetry surfaces.

    `enabled` mirrors the --telemetry flag; when off, every method is a
    cheap no-op and the global registry/tracer are gated off too.
    """

    def __init__(self, flags, jsonl_path: str, driver: str):
        self.enabled = bool(getattr(flags, "telemetry", True))
        set_enabled(self.enabled)
        self.registry: MetricsRegistry = get_registry()
        self.exporter: Optional[JsonLinesExporter] = None
        self.prometheus: Optional[PrometheusServer] = None
        self._trace_path = getattr(flags, "trace_path", None)
        self._tick_callbacks = []
        if not self.enabled:
            return
        self.exporter = JsonLinesExporter(
            jsonl_path, registry=self.registry, static={"driver": driver}
        )
        port = getattr(flags, "telemetry_port", 0)
        if port:
            try:
                self.prometheus = PrometheusServer(
                    self.registry, port=port,
                    host=getattr(flags, "telemetry_host", "127.0.0.1"),
                ).start()
                log.info(
                    "Telemetry: /metrics on port %d", self.prometheus.port
                )
            except OSError as e:
                # Observability must degrade, never abort training.
                self.prometheus = None
                log.warning(
                    "Telemetry: could not bind /metrics port %d (%s); "
                    "continuing without the endpoint", port, e,
                )

    def set_static(self, key: str, value) -> None:
        """Attach a static block to every exported line (e.g. the
        acting-path wire accounting)."""
        if self.exporter is not None:
            self.exporter.static[key] = value

    def add_tick_callback(self, fn) -> None:
        """Run `fn()` right before EVERY snapshot write — the periodic
        monitor ticks AND the final shutdown line. Sampled gauges
        (live-actor count, queue depths read off live objects) stay
        fresh on each exported line instead of freezing at whatever the
        last monitor tick saw."""
        self._tick_callbacks.append(fn)

    def write(self, extra: Optional[Dict] = None) -> None:
        """One snapshot line (monitor/log tick). Broad guard, not just
        OSError: json serialization of a bad static/extra value
        (TypeError/ValueError) must degrade too — observability can
        never abort the training loop it watches."""
        if self.exporter is None:
            return
        for cb in self._tick_callbacks:
            try:
                cb()
            except Exception:  # noqa: BLE001
                log.exception("Telemetry tick callback failed")
        try:
            self.exporter.write(extra=extra)
        except Exception:  # noqa: BLE001
            log.exception("Telemetry snapshot write failed")

    def shutdown(self, step: Optional[int] = None) -> None:
        """Final snapshot (short smoke runs may end before the first
        tick), Prometheus stop, optional Chrome-trace export. Every
        part guarded: teardown telemetry failures must not mask the
        run's own exit path."""
        if self.exporter is not None:
            extra = {"final": True}
            if step is not None:
                extra["step"] = step
            # Through write(): the tick callbacks refresh sampled
            # gauges on the final line too.
            self.write(extra=extra)
        if self.prometheus is not None:
            try:
                self.prometheus.stop()
            except Exception:  # noqa: BLE001
                log.exception("Prometheus endpoint stop failed")
        if self._trace_path:
            try:
                n = get_tracer().export_chrome(self._trace_path)
                log.info(
                    "Wrote %d trace events to %s", n, self._trace_path
                )
            except Exception:  # noqa: BLE001
                log.exception("Chrome trace export failed")
