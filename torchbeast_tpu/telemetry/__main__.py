"""`python -m torchbeast_tpu.telemetry --selftest` — exporter CLI
(avoids runpy's found-in-sys.modules warning that
`-m torchbeast_tpu.telemetry.export` triggers via the package init)."""

import sys

from torchbeast_tpu.telemetry.export import main

if __name__ == "__main__":
    sys.exit(main())
