"""Snapshot/delta/merge + exporters for the telemetry registry.

Snapshot schema (SCHEMA_VERSION bumps on any breaking change; the
bench artifacts and tests/test_telemetry.py validate against it):

    {
      "schema": 1,
      "time": <wall seconds>,
      "counters":   {name: float},
      "gauges":     {name: float},
      "histograms": {name: {count, total, total_sq, min, max, mean,
                            std, p50, p95, p99, buckets: {idx: n}}},
    }

Histogram entries carry their raw sparse log-buckets, so two snapshots
subtract (delta — "what happened during this interval") or add (merge —
"both intervals together") EXACTLY, with interval percentiles re-derived
from the differenced buckets. Exporters:

- JsonLinesExporter: one snapshot JSON object per line, appended to
  `{xpid}/telemetry.jsonl` next to FileWriter's logs.csv (open/append/
  close per write — crash-safe, no fd held).
- PrometheusServer: optional `GET /metrics` text endpoint
  (--telemetry_port) in a daemon thread; counters/gauges map directly,
  histograms render as summaries with quantile labels.

`python -m torchbeast_tpu.telemetry.export --selftest` exercises the
whole stack (instruments -> spans -> snapshot -> delta -> jsonl ->
validate -> prometheus render) and prints one machine-readable verdict
line — CI's cheap guard against exporter/schema drift.
"""

import argparse
import http.server
import json
import re
import socket
import sys
import threading
import time
from typing import Dict, Iterable, List, Optional

from torchbeast_tpu.telemetry.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    hist_stats,
)

SCHEMA_VERSION = 1

# Derived from the one stats constructor so the validator can never
# drift from the shape live histograms and deltas actually emit.
_HIST_KEYS = tuple(hist_stats({}, 0.0, 0.0).keys())


def snapshot(registry: Optional[MetricsRegistry] = None) -> Dict:
    """Cumulative snapshot of every instrument in the registry."""
    registry = registry if registry is not None else get_registry()
    counters, gauges, histograms = {}, {}, {}
    for name, inst in registry.instruments().items():
        if isinstance(inst, Counter):
            counters[name] = inst.value()
        elif isinstance(inst, Gauge):
            gauges[name] = inst.value()
        elif isinstance(inst, Histogram):
            histograms[name] = inst.stats()
    return {
        "schema": SCHEMA_VERSION,
        "time": time.time(),
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
    }


def _combine_hist(a: Dict, b: Dict, sign: int) -> Dict:
    buckets = {int(k): v for k, v in a.get("buckets", {}).items()}
    for k, v in b.get("buckets", {}).items():
        buckets[int(k)] = buckets.get(int(k), 0) + sign * v
    total = a["total"] + sign * b["total"]
    total_sq = a["total_sq"] + sign * b["total_sq"]
    if sign > 0:
        # Empty sides contribute no extremes: their 0.0/0.0
        # placeholders would otherwise corrupt the merged min (or max,
        # for negative-valued series) when a histogram exists in only
        # one of the two snapshots.
        mins = [h["min"] for h in (a, b) if h["count"]]
        maxs = [h["max"] for h in (a, b) if h["count"]]
        lo = min(mins) if mins else None
        hi = max(maxs) if maxs else None
    else:
        # Exact min/max don't subtract; hist_stats falls back to the
        # surviving buckets' bounds (delta percentiles stay
        # bounded-error).
        lo = hi = None
    return hist_stats(buckets, total, total_sq, lo, hi)


def _combine(cur: Dict, other: Dict, sign: int) -> Dict:
    out = {
        "schema": SCHEMA_VERSION,
        "time": cur.get("time", 0.0),
        "counters": {},
        "gauges": dict(cur.get("gauges", {})),
        "histograms": {},
    }
    if sign < 0:
        out["interval_s"] = cur.get("time", 0.0) - other.get("time", 0.0)
    else:
        # Merge is a UNION: gauges present only in the second snapshot
        # (e.g. another process's registry) must survive; on collision
        # the first argument wins (last-write-wins has no meaning
        # across snapshots, so the choice just needs to be stable).
        for name, value in other.get("gauges", {}).items():
            out["gauges"].setdefault(name, value)
    names = set(cur.get("counters", {})) | set(other.get("counters", {}))
    for name in names:
        out["counters"][name] = cur.get("counters", {}).get(
            name, 0.0
        ) + sign * other.get("counters", {}).get(name, 0.0)
    empty = hist_stats({}, 0.0, 0.0)
    names = set(cur.get("histograms", {})) | set(
        other.get("histograms", {})
    )
    for name in names:
        out["histograms"][name] = _combine_hist(
            cur.get("histograms", {}).get(name, empty),
            other.get("histograms", {}).get(name, empty),
            sign,
        )
    return out


def delta(cur: Dict, prev: Dict) -> Dict:
    """What happened between two cumulative snapshots: counters and
    histogram buckets/moments subtracted (interval percentiles
    re-derived), gauges taken from `cur`."""
    return _combine(cur, prev, -1)


def merge_snapshots(a: Dict, b: Dict) -> Dict:
    """Union of two disjoint intervals (bucket/moment sums)."""
    return _combine(a, b, +1)


def validate_snapshot(snap) -> List[str]:
    """Schema check; returns a list of problems (empty = valid)."""
    problems = []
    if not isinstance(snap, dict):
        return [f"snapshot is {type(snap).__name__}, not dict"]
    if snap.get("schema") != SCHEMA_VERSION:
        problems.append(
            f"schema {snap.get('schema')!r} != {SCHEMA_VERSION}"
        )
    for section in ("counters", "gauges", "histograms"):
        if not isinstance(snap.get(section), dict):
            problems.append(f"missing/invalid section {section!r}")
    if not isinstance(snap.get("time"), (int, float)):
        problems.append("missing/invalid 'time'")
    for name, value in snap.get("counters", {}).items():
        if not isinstance(value, (int, float)):
            problems.append(f"counter {name!r} value {value!r}")
    for name, value in snap.get("gauges", {}).items():
        if not isinstance(value, (int, float)):
            problems.append(f"gauge {name!r} value {value!r}")
    for name, h in snap.get("histograms", {}).items():
        if not isinstance(h, dict):
            problems.append(f"histogram {name!r} is not a dict")
            continue
        for key in _HIST_KEYS:
            if key not in h:
                problems.append(f"histogram {name!r} missing {key!r}")
        buckets = h.get("buckets", {})
        if isinstance(buckets, dict):
            bucket_total = sum(buckets.values())
            if bucket_total != h.get("count"):
                problems.append(
                    f"histogram {name!r}: bucket sum {bucket_total} != "
                    f"count {h.get('count')}"
                )
        else:
            problems.append(f"histogram {name!r} buckets not a dict")
    return problems


def telemetry_block(
    prev: Optional[Dict] = None,
    registry: Optional[MetricsRegistry] = None,
) -> Dict:
    """The `telemetry` block bench artifacts embed: the current
    snapshot (or the delta since `prev`) plus the enabled flag. ONE
    shared constructor so every artifact drifts together — and the
    tier-1 schema test validates this exact shape."""
    from torchbeast_tpu.telemetry.metrics import is_enabled

    snap = snapshot(registry)
    if prev is not None:
        snap = delta(snap, prev)
    return {
        "enabled": is_enabled(),
        "snapshot": snap,
    }


class JsonLinesExporter:
    """Append one snapshot JSON object per line to `path`.

    `static` entries ride along on every line (e.g. the acting-path
    wire accounting polybeast used to log as free text). `extra` merges
    per-write (step counters, SPS). Open/append/close per write: no fd
    leaks, and a crash never truncates prior lines.
    """

    def __init__(
        self,
        path: str,
        registry: Optional[MetricsRegistry] = None,
        static: Optional[Dict] = None,
    ):
        self.path = path
        self._registry = registry
        self.static = dict(static or {})
        self._lock = threading.Lock()
        self.lines_written = 0

    def write(self, extra: Optional[Dict] = None) -> Dict:
        snap = snapshot(self._registry)
        snap.update(self.static)
        if extra:
            snap.update(extra)
        line = json.dumps(snap, default=float)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")
            self.lines_written += 1
        return snap


def read_jsonl(path: str) -> List[Dict]:
    """All parseable snapshot lines of a telemetry.jsonl (skips
    torn/corrupt lines rather than dying on them)."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        return []
    return out


_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    out = _PROM_NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def render_prometheus(snap: Dict) -> str:
    """Prometheus text exposition (0.0.4) of a snapshot: counters and
    gauges directly, histograms as summaries."""
    lines = []
    for name, value in sorted(snap.get("counters", {}).items()):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} counter")
        lines.append(f"{pname} {value!r}")
    for name, value in sorted(snap.get("gauges", {}).items()):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {value!r}")
    for name, h in sorted(snap.get("histograms", {}).items()):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} summary")
        for q in ("0.5", "0.95", "0.99"):
            key = "p" + str(int(float(q) * 100))
            lines.append(
                f'{pname}{{quantile="{q}"}} {h.get(key, 0.0)!r}'
            )
        lines.append(f"{pname}_sum {h.get('total', 0.0)!r}")
        lines.append(f"{pname}_count {h.get('count', 0)}")
    return "\n".join(lines) + "\n"


class PrometheusServer:
    """Tiny /metrics HTTP endpoint in a daemon thread (stdlib
    http.server; port=0 binds an ephemeral port — read `.port` after
    start()). Binds loopback by default: the endpoint is unauthenticated
    and carries run metadata, so exposure beyond the host is an explicit
    opt-in (the drivers' --telemetry_host)."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        port: int = 0,
        host: str = "127.0.0.1",
    ):
        self._registry = registry
        self._host = host
        self._requested_port = port
        self._httpd = None
        self._thread = None
        self.port = None

    def start(self) -> "PrometheusServer":
        registry = self._registry

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_error(404)
                    return
                body = render_prometheus(snapshot(registry)).encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr
                pass

        class Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            address_family = socket.AF_INET

        self._httpd = Server((self._host, self._requested_port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            daemon=True,
            name="telemetry-prometheus",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def _selftest(out_path: Optional[str]) -> Dict:
    """Exercise the full stack on a private registry + tracer; returns
    the verdict dict (ok + per-check results)."""
    import os
    import tempfile

    from torchbeast_tpu.telemetry.trace import Tracer

    checks = {}
    registry = MetricsRegistry()
    registry.counter("selftest.count").inc(3)
    registry.gauge("selftest.depth").set(7)
    hist = registry.histogram("selftest.latency_s")
    for i in range(1, 101):
        hist.observe(i / 1000.0)
    p50 = hist.percentile(0.5)
    checks["histogram_p50_bounded"] = bool(0.040 <= p50 <= 0.060)

    tracer = Tracer()
    with tracer.span("selftest.outer"):
        with tracer.span("selftest.inner"):
            pass
    st = tracer.stage("selftest.request")
    st.stamp("queue")
    st.stamp("reply")
    st.finish()
    names = {e["name"] for e in tracer.events()}
    checks["spans_recorded"] = bool(
        {"selftest.outer", "selftest.inner",
         "selftest.request.queue", "selftest.request.reply"} <= names
    )

    snap0 = snapshot(registry)
    hist.observe(5.0)
    registry.counter("selftest.count").inc(2)
    snap1 = snapshot(registry)
    d = delta(snap1, snap0)
    checks["delta_counter"] = d["counters"]["selftest.count"] == 2.0
    checks["delta_histogram"] = (
        d["histograms"]["selftest.latency_s"]["count"] == 1
    )
    checks["validate_snapshot"] = validate_snapshot(snap1) == []
    checks["validate_delta"] = validate_snapshot(d) == []

    path = out_path
    tmpdir = None
    if path is None:
        tmpdir = tempfile.mkdtemp(prefix="telemetry_selftest_")
        path = os.path.join(tmpdir, "telemetry.jsonl")
    exporter = JsonLinesExporter(path, registry, static={"driver": "selftest"})
    exporter.write(extra={"step": 1})
    exporter.write(extra={"step": 2})
    lines = read_jsonl(path)
    checks["jsonl_roundtrip"] = (
        len(lines) == 2
        and all(validate_snapshot(ln) == [] for ln in lines)
        and lines[-1]["step"] == 2
        and lines[-1]["driver"] == "selftest"
    )
    text = render_prometheus(snap1)
    checks["prometheus_render"] = (
        "selftest_count 5.0" in text
        and 'selftest_latency_s{quantile="0.5"}' in text
    )
    block = telemetry_block(prev=snap0, registry=registry)
    checks["telemetry_block"] = (
        validate_snapshot(block["snapshot"]) == []
        and isinstance(block["enabled"], bool)
    )
    return {
        "selftest": "telemetry",
        "ok": all(checks.values()),
        "checks": checks,
        "jsonl": path,
    }


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--selftest", action="store_true",
        help="Exercise instruments/spans/snapshot/delta/exporters and "
             "print one JSON verdict line (rc 0 iff every check passed).",
    )
    parser.add_argument(
        "--out", default=None,
        help="Where --selftest writes its scratch telemetry.jsonl "
             "(default: a temp dir).",
    )
    args = parser.parse_args(argv)
    if not args.selftest:
        parser.error("nothing to do (did you mean --selftest?)")
    verdict = _selftest(args.out)
    print(json.dumps(verdict), flush=True)
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
