"""Process-wide metrics registry: Counter / Gauge / Histogram.

Design constraints (ISSUE 2 tentpole):

- No hot-path locks. Counters and histograms write to PER-THREAD shards
  (a thread-local cell registered once per thread under a creation
  lock); `inc`/`observe` are plain dict/float ops on the calling
  thread's shard. Readers merge a snapshot of the shard list — the
  `list()` copy is a single C call, atomic under the GIL, so a monitor
  thread can merge while writers keep appending.
- Mergeable log-bucketed histograms. Bucket i >= 1 covers
  (LO*G^(i-1), LO*G^i] with G = 2**0.25 (~19% wide, so any bucket
  representative is within ~9% of every value it absorbed — p50/p95/p99
  read from merged buckets carry that bounded relative error). Bucket 0
  absorbs v <= LO (including 0 and negatives). Sparse dicts of
  index -> count add and subtract term-wise, which is what makes
  cross-shard merge and snapshot delta exact.
- stdlib only. This module must stay importable (and its ops runnable)
  without jax or numpy: instrumentation inside the acting hot path may
  never trigger a device sync or a heavyweight import
  (tests/test_telemetry.py pins both).

The GLOBAL registry (telemetry.get_registry()) is gated by
set_enabled(): with telemetry off its instruments become no-ops, so a
--no_telemetry run pays one attribute check per call site. Private
registries (MetricsRegistry()) ignore the gate — utils/prof.Timings
uses one by default so driver log lines keep working with telemetry
disabled.
"""

import math
import threading
from typing import Dict, Iterable, Optional

# Log-bucket geometry, shared by observe-side indexing and read-side
# percentile reconstruction (and by export.delta, which re-derives
# percentiles from subtracted bucket counts).
BUCKET_LO = 1e-9
BUCKET_GROWTH = 2.0 ** 0.25
_LOG_GROWTH = math.log(BUCKET_GROWTH)

# Global on/off gate, honored only by gated (global-registry) instruments.
_ENABLED = [True]


def set_enabled(on: bool) -> None:
    """Flip the global-registry gate (--no_telemetry). Private
    registries are unaffected."""
    _ENABLED[0] = bool(on)


def is_enabled() -> bool:
    return _ENABLED[0]


def bucket_index(value: float) -> int:
    """Log-bucket index of a sample (0 = underflow bucket, v <= LO)."""
    if value <= BUCKET_LO:
        return 0
    return 1 + int(math.log(value / BUCKET_LO) / _LOG_GROWTH)


def bucket_bounds(index: int):
    """(lower, upper] bounds of a bucket (lower is -inf for bucket 0)."""
    if index <= 0:
        return (float("-inf"), BUCKET_LO)
    return (
        BUCKET_LO * BUCKET_GROWTH ** (index - 1),
        BUCKET_LO * BUCKET_GROWTH ** index,
    )


def bucket_representative(index: int) -> float:
    """The value a bucket's samples are reported as (geometric middle;
    0.0 for the underflow bucket)."""
    if index <= 0:
        return 0.0
    return BUCKET_LO * BUCKET_GROWTH ** (index - 0.5)


def percentiles_from_buckets(
    buckets: Dict[int, int],
    qs: Iterable[float],
    lo: Optional[float] = None,
    hi: Optional[float] = None,
):
    """Percentile estimates from a (possibly merged or delta'd) sparse
    bucket dict. `lo`/`hi` clamp the estimates to the exactly-tracked
    min/max when the caller has them."""
    total = sum(buckets.values())
    out = []
    if total <= 0:
        return [0.0 for _ in qs]
    items = sorted(buckets.items())
    for q in qs:
        rank = q * total
        cum = 0
        value = bucket_representative(items[-1][0])
        for index, count in items:
            cum += count
            if cum >= rank:
                value = bucket_representative(index)
                break
        if lo is not None:
            value = max(value, lo)
        if hi is not None:
            value = min(value, hi)
        out.append(value)
    return out


def hist_stats(
    buckets: Dict[int, int],
    total: float,
    total_sq: float,
    lo: Optional[float] = None,
    hi: Optional[float] = None,
) -> Dict:
    """THE constructor of the snapshot histogram-stats shape — live
    Histogram.stats(), export's delta/merge, and the schema validator
    all derive from this one function, so the schema cannot drift
    apart. Count derives from the bucket sums (keeps bucket-sum ==
    count true by construction). `lo`/`hi` are the exact min/max when
    the caller has them; otherwise the extreme buckets' representatives
    bound them within one bucket width."""
    buckets = {int(k): v for k, v in buckets.items() if v > 0}
    count = sum(buckets.values())
    if count <= 0:
        return {
            "count": 0, "total": 0.0, "total_sq": 0.0,
            "min": 0.0, "max": 0.0, "mean": 0.0, "std": 0.0,
            "p50": 0.0, "p95": 0.0, "p99": 0.0, "buckets": {},
        }
    if lo is None:
        lo = bucket_representative(min(buckets))
        hi = bucket_representative(max(buckets))
    mean = total / count
    std = max(total_sq / count - mean * mean, 0.0) ** 0.5
    p50, p95, p99 = percentiles_from_buckets(
        buckets, (0.5, 0.95, 0.99), lo=lo, hi=hi
    )
    return {
        "count": count,
        "total": total,
        "total_sq": total_sq,
        "min": lo,
        "max": hi,
        "mean": mean,
        "std": std,
        "p50": p50,
        "p95": p95,
        "p99": p99,
        "buckets": {str(k): v for k, v in sorted(buckets.items())},
    }


class Counter:
    """Monotonic float counter with per-thread shards.

    Shard lifecycle: registering a new shard (once per writer thread,
    under the creation lock) also FOLDS shards of dead threads into a
    retired total, so short-lived-thread churn (env-server connection
    threads, actor reconnects) can't grow the shard list unboundedly.
    The (shards, retired) pair is published as ONE tuple so readers
    never see a fold half-applied (which would double- or under-count).
    """

    def __init__(self, name: str, gated: bool = False):
        self.name = name
        self._gated = gated
        self._lock = threading.Lock()
        # (list of (thread, cell), retired_total) — replaced atomically.
        self._state = ([], 0.0)
        self._local = threading.local()

    def _cell(self):
        cell = getattr(self._local, "cell", None)
        if cell is None:
            cell = [0.0]
            with self._lock:
                shards, retired = self._state
                alive = []
                for thread, old in shards:
                    if thread.is_alive():
                        alive.append((thread, old))
                    else:
                        retired += old[0]
                alive.append((threading.current_thread(), cell))
                self._state = (alive, retired)
            self._local.cell = cell
        return cell

    def inc(self, n: float = 1.0) -> None:
        if self._gated and not _ENABLED[0]:
            return
        self._cell()[0] += n

    def value(self) -> float:
        shards, retired = self._state
        return retired + sum(cell[0] for _, cell in shards)

    def num_shards(self) -> int:
        return len(self._state[0])


class Gauge:
    """Last-write-wins instantaneous value (one float; the assignment
    is atomic under the GIL, so no shards are needed)."""

    def __init__(self, name: str, gated: bool = False):
        self.name = name
        self._gated = gated
        self._value = 0.0

    def set(self, value: float) -> None:
        if self._gated and not _ENABLED[0]:
            return
        self._value = float(value)

    def value(self) -> float:
        return self._value


class _HistShard:
    __slots__ = ("buckets", "count", "total", "total_sq", "min", "max")

    def __init__(self):
        self.buckets: Dict[int, int] = {}
        # `count` is only maintained on AGGREGATES (derived from bucket
        # sums in _fold_into); live per-thread shards leave it 0 so a
        # racing reader can never observe bucket-sum != count.
        self.count = 0
        self.total = 0.0
        self.total_sq = 0.0
        self.min = float("inf")
        self.max = float("-inf")


def _fold_into(out: _HistShard, shard: _HistShard) -> None:
    """Accumulate `shard` into aggregate `out`. The bucket dict is
    copied first: the owning thread may be mid-increment, and a dict
    copy is atomic enough (counts may lag by the in-flight sample,
    never corrupt)."""
    for index, count in dict(shard.buckets).items():
        out.buckets[index] = out.buckets.get(index, 0) + count
    out.total += shard.total
    out.total_sq += shard.total_sq
    if shard.min < out.min:
        out.min = shard.min
    if shard.max > out.max:
        out.max = shard.max
    out.count = sum(out.buckets.values())


class Histogram:
    """Log-bucketed histogram with exact moments (count/sum/sumsq/
    min/max) and bounded-error percentiles, sharded per thread.

    Same shard lifecycle as Counter: new-shard registration folds
    dead threads' shards into a retired aggregate (published atomically
    with the live list), bounding memory and merge cost by the LIVE
    thread count. The merged count is derived from the bucket sums, so
    a snapshot racing an in-flight observe() can never report
    bucket-sum != count (the moments may lag by the one in-flight
    sample — a transient one-sample mean skew, never an inconsistent
    schema)."""

    def __init__(self, name: str, gated: bool = False):
        self.name = name
        self._gated = gated
        self._lock = threading.Lock()
        # (list of (thread, shard), retired _HistShard) — the retired
        # aggregate is never mutated after publication (folds build a
        # fresh one), so readers holding an old tuple stay consistent.
        self._state = ([], _HistShard())
        self._local = threading.local()

    def _shard(self) -> _HistShard:
        shard = getattr(self._local, "shard", None)
        if shard is None:
            shard = _HistShard()
            with self._lock:
                shards, retired = self._state
                dead = [s for t, s in shards if not t.is_alive()]
                if dead:
                    folded = _HistShard()
                    _fold_into(folded, retired)
                    for s in dead:
                        _fold_into(folded, s)
                    retired = folded
                    shards = [
                        (t, s) for t, s in shards if t.is_alive()
                    ]
                self._state = (
                    shards + [(threading.current_thread(), shard)],
                    retired,
                )
            self._local.shard = shard
        return shard

    def observe(self, value: float) -> None:
        if self._gated and not _ENABLED[0]:
            return
        value = float(value)
        shard = self._shard()
        shard.total += value
        shard.total_sq += value * value
        if value < shard.min:
            shard.min = value
        if value > shard.max:
            shard.max = value
        index = bucket_index(value)
        shard.buckets[index] = shard.buckets.get(index, 0) + 1

    def observe_aggregate(self, buckets: Dict[int, int], total: float,
                          total_sq: float, lo: float, hi: float) -> None:
        """Credit a pre-aggregated batch of observations — the native-
        runtime fold path (runtime/native.py): the C++ core accumulates
        per-request stage stamps into the SAME log-bucket geometry
        (csrc/queues.h telemetry_bucket_index) and the driver folds each
        monitor tick's interval here. Exact in buckets and moments;
        `lo`/`hi` are the interval's true min/max. No-op on an empty
        interval."""
        if self._gated and not _ENABLED[0]:
            return
        counts = {int(k): int(v) for k, v in buckets.items() if v > 0}
        if not counts:
            return
        shard = self._shard()
        for index, count in counts.items():
            shard.buckets[index] = shard.buckets.get(index, 0) + count
        shard.total += float(total)
        shard.total_sq += float(total_sq)
        if lo < shard.min:
            shard.min = float(lo)
        if hi > shard.max:
            shard.max = float(hi)

    def merged(self) -> _HistShard:
        """One shard-shaped aggregate over every thread's shard (plus
        the retired fold); count is derived from the bucket sums."""
        shards, retired = self._state
        out = _HistShard()
        _fold_into(out, retired)
        for _, shard in shards:
            _fold_into(out, shard)
        return out

    def num_shards(self) -> int:
        return len(self._state[0])

    @property
    def count(self) -> int:
        return self.merged().count

    @property
    def mean(self) -> float:
        m = self.merged()
        return m.total / m.count if m.count else 0.0

    @property
    def std(self) -> float:
        m = self.merged()
        if not m.count:
            return 0.0
        mean = m.total / m.count
        # Clamped: float cancellation can dip epsilon-negative.
        return max(m.total_sq / m.count - mean * mean, 0.0) ** 0.5

    def percentile(self, q: float) -> float:
        m = self.merged()
        if not m.count:
            return 0.0
        return percentiles_from_buckets(
            m.buckets, [q], lo=m.min, hi=m.max
        )[0]

    def stats(self) -> Dict:
        """Snapshot dict for the exporter: exact moments, estimated
        percentiles, and the raw sparse buckets (str-keyed for JSON)
        so snapshots stay mergeable/delta-able downstream."""
        m = self.merged()
        return hist_stats(
            m.buckets, m.total, m.total_sq,
            lo=m.min if m.count else None,
            hi=m.max if m.count else None,
        )


class MetricsRegistry:
    """Name -> instrument table with idempotent get-or-create (the
    creation lock is off the hot path; call sites keep the returned
    instrument)."""

    def __init__(self, gated: bool = False):
        self._gated = gated
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}  # guarded-by: self._lock

    def _get_or_create(self, name: str, cls):
        # Double-checked locking: the lock-free first read is re-checked
        # under the lock before any mutation; dict reads are atomic
        # under the GIL, and instruments are never removed or replaced.
        # beastlint: disable=LOCK-DISCIPLINE  racy fast-path read is re-validated under self._lock below
        inst = self._instruments.get(name)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(name)
                if inst is None:
                    inst = cls(name, gated=self._gated)
                    self._instruments[name] = inst
        if not isinstance(inst, cls):
            raise ValueError(
                f"Instrument {name!r} already registered as "
                f"{type(inst).__name__}, not {cls.__name__}"
            )
        return inst

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get_or_create(name, Histogram)

    def instruments(self) -> Dict[str, object]:
        # Snapshot copy; same GIL-atomic read as the fast path above.
        # beastlint: disable=LOCK-DISCIPLINE  read-only snapshot of a grow-only dict; GIL-atomic
        return dict(self._instruments)


# The process-wide registry all runtime instrumentation writes to.
_GLOBAL = MetricsRegistry(gated=True)


def get_registry() -> MetricsRegistry:
    return _GLOBAL
