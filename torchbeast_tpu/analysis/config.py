"""beastlint repo configuration: which contracts bind which paths.

This file is the declarative half of the analyzer — rules read it, the
repo edits it. Everything here is data, so adding a package to a purity
contract or a flag to the parity exemptions is a one-line diff reviewed
like any other contract change.
"""

# Per-package banned top-level imports (IMPORT-PURITY). Keys are
# repo-relative directory prefixes; values are module roots that must
# never be imported anywhere under that prefix.
#
# telemetry/: stdlib-only so instrumentation can never introduce a device
# sync (replaces the PR 2 source-pin test as the single source of truth).
# analysis/: the linter itself must run in a bare-CI image and must never
# import the runtime it analyzes.
_HEAVY = (
    "jax",
    "jaxlib",
    "numpy",
    "np",
    "torch",
    "optax",
    "ml_dtypes",
    "chex",
    "flax",
    "tensorflow",
)
PURITY = {
    "torchbeast_tpu/telemetry": _HEAVY,
    "torchbeast_tpu/analysis": _HEAVY + ("torchbeast_tpu",),
}

# EXCEPT-SWALLOW scope: path prefixes where a broad `except:` /
# `except Exception:` / `except BaseException:` body must re-raise,
# log, or count the failure. These are the pipeline's failure-handling
# layers — a silent swallow here is exactly how a DEGRADED run hides
# (ISSUE 6). telemetry/ and analysis/ joined in ISSUE 10: a swallowed
# exporter failure silently drops observability, and a swallowed
# analyzer failure silently stops checking a contract. Other packages
# stay out of scope: broad-but-silent guards in benches/tests are
# noise, not hidden outages.
EXCEPT_SWALLOW_PATHS = (
    "torchbeast_tpu/runtime",
    "torchbeast_tpu/resilience",
    "torchbeast_tpu/telemetry",
    "torchbeast_tpu/analysis",
)

# WIRE-PARITY anchors: the Python codec and its C++ mirrors.
WIRE_PY = "torchbeast_tpu/runtime/wire.py"
WIRE_H = "csrc/wire.h"
ARRAY_H = "csrc/array.h"
CLIENT_H = "csrc/client.h"
POLYBEAST_PY = "torchbeast_tpu/polybeast.py"
# The shm ring layout contract (ISSUE 9): a Python env server and a C++
# actor attach the SAME segments, so the header word layout, in-ring
# markers, doorbell bytes, and the ring-eligibility cap must agree.
TRANSPORT_PY = "torchbeast_tpu/runtime/transport.py"
SHM_H = "csrc/shm.h"

# C++ DType enumerator -> numpy dtype name (the dtype table's rosetta
# stone; WIRE-PARITY fails if either side has a code the other lacks).
CPP_DTYPE_TO_NUMPY = {
    "kU8": "uint8",
    "kI8": "int8",
    "kI32": "int32",
    "kI64": "int64",
    "kF32": "float32",
    "kF64": "float64",
    "kBool": "bool",
    "kU16": "uint16",
    "kI16": "int16",
    "kU32": "uint32",
    "kU64": "uint64",
    "kF16": "float16",
    "kBF16": "bfloat16",
}

# Ground-truth itemsizes (bytes) per wire dtype: both languages' tables
# are checked against this, so a wrong size on either side is a finding
# even when the two sides agree with each other.
DTYPE_ITEMSIZE = {
    "uint8": 1,
    "int8": 1,
    "bool": 1,
    "uint16": 2,
    "int16": 2,
    "float16": 2,
    "bfloat16": 2,
    "int32": 4,
    "uint32": 4,
    "float32": 4,
    "int64": 8,
    "uint64": 8,
    "float64": 8,
}

# ROUTE-PARITY anchors (ISSUE 16): the static slot->slice hash runs in
# BOTH languages — runtime/placement.py `_mix64` for the Python pool and
# csrc/routing.h `splitmix64` for the native one. The same slot MUST
# land on the same slice either way (slot tables never migrate between
# devices), so the splitmix64 finalizer constants are pinned against
# the ground-truth spec below on both sides. The per-slice telemetry
# namespace ("inference.slice.<i>.*") is part of the same contract:
# dashboards and the capacity bench read one schema regardless of
# which language routed the request.
PLACEMENT_PY = "torchbeast_tpu/runtime/placement.py"
ROUTING_H = "csrc/routing.h"
# Python emitters of the per-slice series (both must build names under
# SLICE_SERIES_PREFIX): the Python serving plane and the native
# telemetry folder.
SLICE_SERIES_FILES = (
    "torchbeast_tpu/parallel/sebulba.py",
    "torchbeast_tpu/runtime/native.py",
)

# splitmix64 finalizer ground truth (Vigna's constants): both languages
# are checked against THIS, so a wrong constant on either side is a
# finding even when the two sides agree with each other.
SPLITMIX64_SPEC = {
    "gamma": 0x9E3779B97F4A7C15,
    "mul1": 0xBF58476D1CE4E5B9,
    "mul2": 0x94D049BB133111EB,
    "shift1": 30,
    "shift2": 27,
    "shift3": 31,
}

# The per-slice telemetry namespace: csrc/routing.h kSliceSeriesPrefix
# and every Python series builder must use exactly this prefix.
SLICE_SERIES_PREFIX = "inference.slice."

# FLAG-PARITY anchors: drivers whose shared flags must agree on type and
# default. Intentional divergences carry inline suppressions at the
# add_argument site (with the reason), not entries here — the exemption
# should live next to the flag it exempts. Each pair is checked
# independently; findings anchor in the SECOND file of the pair.
FLAG_PARITY_FILES = (
    "torchbeast_tpu/monobeast.py",
    "torchbeast_tpu/polybeast.py",
)
FLAG_PARITY_GROUPS = (
    FLAG_PARITY_FILES,
    # The env-server group driver shares its address/supervision flags
    # with the learner driver (polybeast spawns ServerSupervisor from
    # the same knobs).
    ("torchbeast_tpu/polybeast.py", "torchbeast_tpu/polybeast_env.py"),
    # The chaos harness builds polybeast flag lists programmatically;
    # the flags it re-declares for itself must not silently drift from
    # the driver's meaning (its deliberately scaled-down defaults carry
    # inline suppressions).
    ("torchbeast_tpu/polybeast.py", "scripts/chaos_run.py"),
    # The capacity bench re-declares the driver flags its subprocess
    # rows forward (ISSUE 16); its deliberately scaled-down / armed-by-
    # default values carry inline suppressions at the add_argument
    # sites.
    ("torchbeast_tpu/polybeast.py", "benchmarks/capacity_bench.py"),
)

# Whole-program concurrency analysis scope (RACE / LOCK-ORDER /
# HOTPATH-SYNC-XPROC, analysis/graph.py): the module/call/thread-root
# graphs are built from — and findings restricted to — these prefixes.
# tests/ and benchmarks/ stay out: their ad-hoc threads would add roots
# that exist only for one test's lifetime.
CONCURRENCY_PATHS = (
    "torchbeast_tpu",
    "scripts",
)

# Module-level functions treated as driver main-thread roots wherever
# they appear inside CONCURRENCY_PATHS (the driver main loops of
# polybeast/monobeast/anakin/polybeast_env/chaos_run).
THREAD_ROOT_FUNCTIONS = ("main", "train", "cli")

# ---------------------------------------------------------------------
# C++ analysis scope (ISSUE 10, analysis/cxx.py + cxxrules.py).

# GIL-DISCIPLINE: files whose CPython API calls must be dominated by a
# GIL acquire (in-function or via the call summary) and whose GIL-held
# regions must not make blocking calls (waits, socket recvs, queue
# dequeues). pymodule.cc is the binding layer; actor_pool.h hosts the
# slot hooks' call sites (its threads run GIL-free by design, so a
# CPython call appearing there without an acquire is a bug by
# construction); chaos.h hosts the FaultHooks entry points the Python
# chaos thread drives through pymodule (ISSUE 12) — same contract: any
# CPython call landing there without an acquire is a bug.
GIL_FILES = (
    "csrc/pymodule.cc",
    "csrc/actor_pool.h",
    "csrc/chaos.h",
)

# CXX-LOCK-DISCIPLINE / cross-root conflict scope: every C++ source the
# frontend lexes. Classes are in conflict scope only when they own a
# mutex or one of their methods is a thread-spawn target — same
# "you lock because you share" heuristic as the Python RACE rule.
CXX_PATHS = ("csrc",)

# ATOMIC-ORDER: the required memory order at the KEY publish/Dekker
# sites of csrc/shm.h, keyed by (function, word, op). Sites not listed
# only need an EXPLICIT order through the designated accessor; listed
# sites must use exactly this one (weakening the publish to relaxed is
# a lost-wakeup, not a style choice).
ATOMIC_ORDER_REQUIRED = {
    ("write_frame", "head", "store"): "release",
    ("write_inline_marker", "head", "store"): "release",
    ("release", "tail", "store"): "release",
    ("set_waiting", "waiting", "store"): "seq_cst",
    ("has_frame", "head", "load"): "acquire",
    ("reader_waiting", "waiting", "load"): "acquire",
    ("read_frame", "head", "load"): "acquire",
    ("wait_free", "tail", "load"): "acquire",
}

# Shared by HOTPATH-SYNC (intraprocedural) and HOTPATH-SYNC-XPROC
# (summary-based): jax.* namespaces that do HOST work (rooted there does
# not make a value device-resident), and calls whose RESULT is host data
# regardless of their arguments (`jax.device_get` is the explicit fetch
# the findings recommend, so its result must never re-taint).
HOST_JAX_NAMESPACES = ("tree_util", "tree", "dtypes", "typing")
HOST_RETURNING_CALLS = ("jax.device_get",)

# ---------------------------------------------------------------------
# Distributed-systems analysis tier (ISSUE 20, analysis/fleetrules.py +
# fleetproto.py).

# FLEET-MSG-PARITY anchor: the one file that speaks the fleet
# control-plane dict protocol. The rule extracts every send site
# (dict literals with a "type" key flowing into _send/_broadcast) and
# every handler arm, then cross-checks types and field sets per role.
FLEET_COORDINATOR = "torchbeast_tpu/fleet/coordinator.py"
# The payload-carrying senders the extractor follows. `_send`'s first
# argument is the destination rank (a literal 0 means "to the lead");
# `_broadcast` fans out lead -> remotes.
FLEET_SEND_FUNCS = ("_send", "_broadcast")
# Role assignment for handler arms found OUTSIDE the shared `_handle` /
# `_reader` dispatch (which both roles run): the lead-only accept loop
# handles "hello"; anything in the remote-only dial path is remote.
FLEET_LEAD_FUNCS = ("_start_lead",)
FLEET_REMOTE_FUNCS = ("_start_remote",)
# Fields every control-plane message may carry without a reader: "type"
# is consumed by the dispatch itself, and "rank" is the sender identity
# (verified once at hello, implied by the connection thereafter).
FLEET_MSG_STANDARD_FIELDS = ("type", "rank")

# FLEET-TIMEOUT-DISCIPLINE scope: path prefixes where every blocking
# control-plane operation (accept, recv, dial, condition/event wait,
# join) must be under a deadline or carry an explicit
# `# unbounded-by-design: <why>` annotation.
FLEET_TIMEOUT_PATHS = ("torchbeast_tpu/fleet",)
# Dial helpers that bound their own retry loop ONLY when a deadline is
# passed; calling them without one is an unbounded dial.
FLEET_DIAL_FUNCS = ("dial_transport", "connect_transport")

# TELEMETRY-SCHEMA scope: where series registrations
# (reg.counter/gauge/histogram with a literal or f-string name) are
# collected from. tests/ stays out: fixture registries use throwaway
# names by design.
TELEMETRY_SCAN_PATHS = ("torchbeast_tpu", "scripts", "benchmarks")
# The `host<r>.` fold prefix is reserved to the lead's telemetry folder
# (NativeTelemetryFolder): any other emitter would collide with the
# folded remote series and corrupt fleet dashboards.
TELEMETRY_FOLD_FILES = ("torchbeast_tpu/runtime/native.py",)
# Files whose series READS are schema commitments: the chaos harness'
# verdict counters and the telemetry test suite's snapshot assertions.
# A name consumed here that no scanned code emits is drift (a rename
# that silently turned the verdict/assert into a no-op).
TELEMETRY_CONSUMER_FILES = (
    "scripts/chaos_run.py",
    "tests/test_telemetry.py",
)
# The consumed-but-never-emitted check only runs when the scan plainly
# covers the whole tree (partial scans would see a truncated emitter
# set and flag everything): this sentinel file must be in scope.
TELEMETRY_SENTINEL_FILE = "torchbeast_tpu/telemetry/metrics.py"
