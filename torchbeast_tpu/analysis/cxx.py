"""beastlint C++ frontend (ISSUE 10): a stdlib-only lexer + extractor
over `csrc/*.h` / `*.cc`.

No libclang, no compiler — the same purity contract as the rest of the
package (enforced by its own IMPORT-PURITY entry). The frontend is NOT a
C++ parser: it is a tokenizer plus a small set of shape-matchers scoped
to the declaration idioms this repo actually uses (trailing-underscore
members one per line, `std::lock_guard`/`unique_lock` RAII locking,
brace-balanced function bodies, `PyMethodDef` tables). Rules built on it
(analysis/cxxrules.py) stay conservative: anything the matchers cannot
resolve is silence, not a guess — except where a contract says an
unparseable side must itself be a finding (WIRE-PARITY precedent).

What it extracts per file (`CxxFileContext`):

- comments (line -> text) and the beastlint annotation grammar in its
  `//` spelling: `// beastlint: disable=RULE  reason` (trailing or
  standalone-covering-next-line), `// beastlint: holds mu_`,
  `// guarded-by: mu_` — same semantics as the Python engine, so one
  suppression mechanism covers both languages.
- classes with their member declarations (name, type text, line,
  atomic/mutex/const classification, guarded-by annotations).
- functions (free + methods) with token spans, a name-based call graph,
  lexical lock-held scopes, `std::thread`/`emplace_back(lambda)` spawn
  sites, and per-token GIL state (PyGILState_Ensure/Release,
  Py_BEGIN/END_ALLOW_THREADS, the `call_nogil(...)` idiom, RAII
  GILGuard).
- shm ring header accesses: every use of the kRing*Word constants with
  its accessor shape and explicit memory order (ATOMIC-ORDER's raw
  material), plus data-region accesses for the protocol conformance
  sequences.
"""

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import FileContext, Finding, Suppression

# ---------------------------------------------------------------------------
# Lexer

_TOKEN_RE = re.compile(
    r"""
    (?P<id>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<num>0[xX][0-9a-fA-F']+|\d[\d.']*(?:[eE][+-]?\d+)?[uUlLfF]*)
  | (?P<str>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<punct>->|\+\+|--|<<=|>>=|<=|>=|==|!=|&&|\|\||\+=|-=|\*=|/=|\|=|&=|\^=|::|[{}()\[\];,<>=+\-*/!&|^~%?:.\#])
    """,
    re.VERBOSE,
)

_LINE_COMMENT_RE = re.compile(r"//[^\n]*")
_BLOCK_COMMENT_RE = re.compile(r"/\*.*?\*/", re.DOTALL)

_DISABLE_RE = re.compile(r"//\s*beastlint:\s*disable=([A-Za-z0-9_,\-]+)\s*(.*)$")
_HOLDS_RE = re.compile(r"//\s*beastlint:\s*holds\s+(\S+)")
_GUARDED_RE = re.compile(r"//\s*guarded-by:\s*(\S+)")

_KEYWORDS = {
    "if", "else", "for", "while", "do", "switch", "case", "return",
    "throw", "try", "catch", "new", "delete", "sizeof", "static_cast",
    "reinterpret_cast", "const_cast", "dynamic_cast", "using",
    "namespace", "class", "struct", "enum", "template", "typename",
    "public", "private", "protected", "const", "constexpr", "static",
    "inline", "virtual", "override", "final", "noexcept", "mutable",
    "default", "break", "continue", "auto", "void", "bool", "int",
    "char", "float", "double", "unsigned", "signed", "long", "short",
    "true", "false", "nullptr", "this", "operator", "friend", "explicit",
    "typedef", "extern", "goto", "alignas", "alignof", "decltype",
}


@dataclasses.dataclass(frozen=True)
class Token:
    kind: str  # 'id' | 'num' | 'str' | 'punct'
    text: str
    line: int


def lex(source: str) -> Tuple[List[Token], Dict[int, str], Dict[int, bool]]:
    """(tokens, comments{line: text}, comment_only{line: bool}).

    Comments and string literals are stripped before tokenizing (a `{`
    in a string must not unbalance brace matching); comments are kept
    aside for the annotation grammar.
    """
    comments: Dict[int, str] = {}
    code_lines: Set[int] = set()

    def _blank(match: "re.Match[str]") -> str:
        # Replace with same-shape whitespace so line numbers survive.
        return re.sub(r"[^\n]", " ", match.group(0))

    # Block comments first (a // inside /* */ is not a line comment).
    stripped = _BLOCK_COMMENT_RE.sub(_blank, source)
    lines = stripped.split("\n")
    out_lines = []
    for i, line in enumerate(lines, start=1):
        m = _LINE_COMMENT_RE.search(line)
        if m is not None:
            comments[i] = m.group(0)
            line = line[: m.start()]
        out_lines.append(line)
    stripped = "\n".join(out_lines)

    tokens: List[Token] = []
    line = 1
    pos = 0
    for m in _TOKEN_RE.finditer(stripped):
        line += stripped.count("\n", pos, m.start())
        pos = m.start()
        kind = m.lastgroup or "punct"
        if kind != "str":
            tokens.append(Token(kind, m.group(0), line))
        else:
            tokens.append(Token("str", "<str>", line))
        code_lines.add(line)

    comment_only = {
        ln: ln not in code_lines for ln in comments
    }
    return tokens, comments, comment_only


# ---------------------------------------------------------------------------
# Declarations

@dataclasses.dataclass
class CxxMember:
    name: str
    line: int
    type_text: str
    is_atomic: bool
    is_mutex: bool
    is_const: bool


@dataclasses.dataclass
class CxxClass:
    name: str
    start_line: int
    end_line: int
    members: Dict[str, CxxMember]
    guarded: Dict[str, str]  # member -> lock member (guarded-by)
    methods: Dict[str, "CxxFunction"]

    @property
    def lock_attrs(self) -> Set[str]:
        return {m.name for m in self.members.values() if m.is_mutex}


@dataclasses.dataclass
class CxxFunction:
    name: str
    qual: str  # Class::name or ::name
    class_name: Optional[str]
    start_line: int
    end_line: int
    # Token span: signature start .. closing brace (inclusive), so
    # mem-initializer lists are part of the searchable region.
    tokens: List[Token]
    body_start: int  # index into `tokens` of the opening '{'


_MEMBER_LINE_RE = re.compile(
    r"^\s*(?:mutable\s+)?(?P<type>[A-Za-z_][\w:<>,*&\s.()]*?)\s+"
    r"(?P<name>[A-Za-z_]\w*)\s*(?:\{[^{}]*\}\s*|=\s*[^;]*)?;\s*$"
)


class CxxFileContext:
    """One lexed C++ source file plus its beastlint annotations.

    Mirrors the engine FileContext interface the suppression machinery
    needs (`path`, `suppressions`, `suppression_for`, `comment_only`) so
    `run_rules` applies inline suppressions to C++ findings exactly as
    it does to Python ones. `is_cxx` keeps the Python file rules away.
    """

    is_cxx = True

    def __init__(self, path: str, source: str, abspath: str = ""):
        import os

        self.path = path.replace(os.sep, "/")
        self.abspath = abspath or path
        self.source = source
        self.tokens, self.comments, self._comment_only = lex(source)
        self.suppressions: List[Suppression] = []
        self._holds: Dict[int, str] = {}
        self.guarded_annotations: Dict[int, str] = {}
        self._parse_annotations()
        self.functions: List[CxxFunction] = []
        self.classes: Dict[str, CxxClass] = {}
        self._fn_end_index: Dict[int, int] = {}
        self._extract()

    # -- annotations (same grammar as engine.FileContext, // spelling) ------

    def _parse_annotations(self) -> None:
        for line, text in self.comments.items():
            m = _DISABLE_RE.search(text)
            if m:
                rules_text, reason = m.group(1), m.group(2).strip()
                names = {r.strip() for r in rules_text.split(",") if r.strip()}
                self.suppressions.append(
                    Suppression(
                        line=line,
                        rules=None if "all" in names else names,
                        reason=reason,
                        standalone=self._comment_only.get(line, False),
                    )
                )
                continue
            m = _HOLDS_RE.search(text)
            if m:
                self._holds[line] = m.group(1)
            m = _GUARDED_RE.search(text)
            if m:
                self.guarded_annotations[line] = m.group(1)

    def comment_only(self, line: int) -> bool:
        return self._comment_only.get(line, False)

    def holds_annotation_for_line(self, line: int) -> Optional[str]:
        for ln in (line - 1, line):
            if ln in self._holds:
                return self._holds[ln]
        return None

    # The engine's window semantics, literally shared (one suppression
    # mechanism for both languages — a change to the coverage rules in
    # engine.py applies here by construction).
    suppression_for = FileContext.suppression_for

    # -- structure extraction ----------------------------------------------

    def _extract(self) -> None:
        toks = self.tokens
        n = len(toks)
        i = 0
        # Scope stack entries: (kind, name, close_depth) where kind in
        # {"namespace", "class"}; depth = brace depth the scope closes at.
        depth = 0
        scope: List[Tuple[str, str, int]] = []
        class_spans: List[Tuple[str, int, int]] = []  # (name, start_i, end_i)

        def current_class() -> Optional[str]:
            for kind, name, _ in reversed(scope):
                if kind == "class":
                    return name
            return None

        while i < n:
            tok = toks[i]
            if tok.kind == "punct" and tok.text == "{":
                depth += 1
                i += 1
                continue
            if tok.kind == "punct" and tok.text == "}":
                depth -= 1
                while scope and scope[-1][2] > depth:
                    kind, name, _ = scope.pop()
                i += 1
                continue
            if tok.kind == "id" and tok.text in ("namespace",):
                # namespace X { ... }
                j = i + 1
                name = ""
                if j < n and toks[j].kind == "id":
                    name = toks[j].text
                    j += 1
                if j < n and toks[j].text == "{":
                    scope.append(("namespace", name, depth + 1))
                    depth += 1
                    i = j + 1
                    continue
                i = j
                continue
            if tok.kind == "id" and tok.text in ("class", "struct") and (
                i + 1 < n and toks[i + 1].kind == "id"
            ):
                # class NAME [: bases] { ... }   (skip `class X;` decls and
                # `enum class`).
                if i > 0 and toks[i - 1].kind == "id" and (
                    toks[i - 1].text == "enum"
                ):
                    i += 1
                    continue
                name = toks[i + 1].text
                j = i + 2
                while j < n and toks[j].text not in ("{", ";"):
                    j += 1
                if j < n and toks[j].text == "{":
                    scope.append(("class", name, depth + 1))
                    start_i = j + 1
                    # record span lazily: find matching close
                    d = 1
                    k = start_i
                    while k < n and d > 0:
                        if toks[k].text == "{":
                            d += 1
                        elif toks[k].text == "}":
                            d -= 1
                        k += 1
                    class_spans.append((name, start_i, k - 1))
                    depth += 1
                    i = j + 1
                    continue
                i = j
                continue
            # Function definition candidate: ID '(' ... ')' ...opt... '{'
            if tok.kind == "id" and tok.text not in _KEYWORDS and (
                i + 1 < n and toks[i + 1].text == "("
            ):
                fn = self._try_function(i, depth, current_class())
                if fn is not None:
                    self.functions.append(fn)
                    # Skip past the body to avoid nested re-extraction
                    # (lambdas stay part of this function).
                    i = self._fn_end_index[id(fn)]
                    continue
            # operator overloads: `operator` punct... '('
            if tok.kind == "id" and tok.text == "operator":
                j = i + 1
                name = "operator"
                while j < n and toks[j].kind == "punct" and toks[j].text != "(":
                    name += toks[j].text
                    j += 1
                if j < n and toks[j].text == "(":
                    fn = self._try_function(i, depth, current_class(),
                                            name_override=name,
                                            paren_index=j)
                    if fn is not None:
                        self.functions.append(fn)
                        i = self._fn_end_index[id(fn)]
                        continue
            i += 1

        # Attach methods to classes + parse member declarations.
        fn_ranges = [(f.start_line, f.end_line) for f in self.functions]
        src_lines = self.source.split("\n")
        line_spans = [
            (name, toks[s].line if s < n else 0,
             toks[e].line if e < n else 0)
            for name, s, e in class_spans
        ]
        for name, start_line, end_line in line_spans:
            # Lines belonging to a class NESTED inside this one must not
            # contribute members here (struct Frame inside ShmRing).
            nested = [
                (a, b) for other, a, b in line_spans
                if other != name and a > start_line and b <= end_line
            ]
            members: Dict[str, CxxMember] = {}
            guarded: Dict[str, str] = {}
            methods = {
                f.name: f for f in self.functions
                if f.class_name == name
            }
            for ln in range(start_line, end_line + 1):
                if any(a <= ln <= b for a, b in fn_ranges):
                    continue  # inside a method body
                if any(a - 1 <= ln <= b for a, b in nested):
                    continue  # a nested class's declaration lines
                raw = src_lines[ln - 1] if ln - 1 < len(src_lines) else ""
                code = _LINE_COMMENT_RE.sub("", raw)
                if "= delete" in code or "= default" in code:
                    continue  # deleted/defaulted special members
                m = _MEMBER_LINE_RE.match(code)
                if not m:
                    continue
                type_text = m.group("type").strip()
                mname = m.group("name")
                if mname == "operator" or "operator" in type_text.split():
                    continue
                if type_text in ("return", "delete", "case", "goto"):
                    continue
                if "using" in type_text.split() or type_text.startswith(
                    ("typedef", "friend")
                ):
                    continue
                members[mname] = CxxMember(
                    name=mname,
                    line=ln,
                    type_text=type_text,
                    is_atomic="atomic" in type_text,
                    is_mutex=bool(re.search(r"\bmutex\b", type_text)),
                    is_const=bool(
                        re.match(r"\s*(static\s+)?(constexpr|const)\b",
                                 type_text)
                    ),
                )
                annotation = self.guarded_annotations.get(ln)
                if annotation is None and self._comment_only.get(ln - 1):
                    annotation = self.guarded_annotations.get(ln - 1)
                if annotation is not None:
                    guarded[mname] = annotation.split(".")[-1]
            self.classes[name] = CxxClass(
                name=name, start_line=start_line, end_line=end_line,
                members=members, guarded=guarded, methods=methods,
            )

    def _try_function(self, name_i: int, depth: int,
                      class_name: Optional[str],
                      name_override: Optional[str] = None,
                      paren_index: Optional[int] = None
                      ) -> Optional[CxxFunction]:
        """Match ID '(' params ')' [qualifiers / mem-inits] '{' body '}'.

        Returns None when the shape is a call / declaration / macro use
        rather than a definition with a body.
        """
        toks = self.tokens
        n = len(toks)
        name = name_override or toks[name_i].text
        # Heuristic: a definition is preceded by a type/qualifier token,
        # '}'/';'/'{'/access-specifier ':' — NOT by '.', '->', '=', '(',
        # ',', 'return' etc. (those are calls).
        prev = toks[name_i - 1] if name_i > 0 else None
        if prev is not None:
            if prev.kind == "punct" and prev.text not in (
                "}", ";", "{", ":", "&", "*", ">",
            ):
                return None
            if prev.kind == "id" and prev.text in (
                "return", "throw", "new", "case", "else", "do",
            ):
                return None
            # `Foo::name(` — a qualified out-of-line definition; take the
            # class from the qualifier.
            if prev.text == "::" and name_i >= 2 and toks[name_i - 2].kind == "id":
                class_name = toks[name_i - 2].text
        j = paren_index if paren_index is not None else name_i + 1
        # matching ')'
        d = 0
        while j < n:
            if toks[j].text == "(":
                d += 1
            elif toks[j].text == ")":
                d -= 1
                if d == 0:
                    break
            j += 1
        if j >= n:
            return None
        # After ')': allow qualifiers, mem-initializer lists, ->type,
        # until '{' (definition) or ';'/'='/',' (declaration / something
        # else). Track paren depth for mem-inits.
        k = j + 1
        d_paren = 0
        while k < n:
            t = toks[k]
            if d_paren == 0 and t.text == "{":
                break
            if d_paren == 0 and t.text in (";", "=", ","):
                return None
            if t.text == "(":
                d_paren += 1
            elif t.text == ")":
                d_paren -= 1
            k += 1
        if k >= n:
            return None
        body_open = k
        d = 0
        end = body_open
        while end < n:
            if toks[end].text == "{":
                d += 1
            elif toks[end].text == "}":
                d -= 1
                if d == 0:
                    break
            end += 1
        if end >= n:
            return None
        span = toks[name_i : end + 1]
        fn = CxxFunction(
            name=name,
            qual=f"{class_name}::{name}" if class_name else name,
            class_name=class_name,
            start_line=toks[name_i].line,
            end_line=toks[end].line,
            tokens=span,
            body_start=body_open - name_i,
        )
        self._fn_end_index[id(fn)] = end + 1
        return fn

    # -- queries ------------------------------------------------------------

    def function_named(self, name: str,
                       class_name: Optional[str] = None
                       ) -> Optional[CxxFunction]:
        for fn in self.functions:
            if fn.name == name and (
                class_name is None or fn.class_name == class_name
            ):
                return fn
        return None

    def address_taken_names(self) -> Set[str]:
        """Function names referenced somewhere WITHOUT a following '('
        — address taken (PyMethodDef tables, slot assignments). Those
        are CPython entry points: called with the GIL held."""
        defined = {f.name for f in self.functions}
        spans = []
        for f in self.functions:
            spans.append((f.start_line, f.end_line))
        out: Set[str] = set()
        toks = self.tokens
        for i, tok in enumerate(toks):
            if tok.kind != "id" or tok.text not in defined:
                continue
            nxt = toks[i + 1] if i + 1 < len(toks) else None
            if nxt is not None and nxt.text == "(":
                continue
            # Skip the definition site itself (name followed by '(' is
            # already excluded; qualified definition `Class :: name` is
            # followed by '(' too).
            out.add(tok.text)
        return out


# ---------------------------------------------------------------------------
# Lexical lock scopes

@dataclasses.dataclass
class LockScope:
    lock: str  # member/variable name of the mutex
    start_index: int  # token index (within fn.tokens) where held begins
    end_index: int  # exclusive


_GUARD_TYPES = {"lock_guard", "unique_lock", "scoped_lock"}


def lock_scopes(fn: CxxFunction) -> List[LockScope]:
    """Lexical spans of fn.tokens where a named mutex is held via an
    RAII guard. Handles `std::lock_guard<std::mutex> l(mu_);` (held to
    the end of the enclosing brace block) and `l.unlock()` (releases a
    unique_lock early). A `cv.wait(l)` keeps the lock held (it is
    reacquired before returning)."""
    toks = fn.tokens
    n = len(toks)
    scopes: List[LockScope] = []
    open_guards: List[Tuple[str, str, int, int]] = []  # (var, lock, start, depth)
    depth = 0
    i = 0
    while i < n:
        t = toks[i]
        if t.text == "{":
            depth += 1
        elif t.text == "}":
            # close guards opened at this depth
            for var, lock, start, d in list(open_guards):
                if d >= depth:
                    scopes.append(LockScope(lock, start, i))
                    open_guards.remove((var, lock, start, d))
            depth -= 1
        elif t.kind == "id" and t.text in _GUARD_TYPES:
            # ... lock_guard < ... > VAR ( LOCKEXPR ) ;
            j = i + 1
            angle = 0
            while j < n:
                if toks[j].text == "<":
                    angle += 1
                elif toks[j].text == ">":
                    angle -= 1
                elif angle == 0 and toks[j].kind == "id":
                    break
                j += 1
            if j < n and j + 1 < n and toks[j + 1].text == "(":
                var = toks[j].text
                k = j + 2
                d2 = 1
                lock_name = ""
                while k < n and d2 > 0:
                    if toks[k].text == "(":
                        d2 += 1
                    elif toks[k].text == ")":
                        d2 -= 1
                    elif toks[k].kind == "id":
                        lock_name = toks[k].text
                    k += 1
                if lock_name:
                    open_guards.append((var, lock_name, k, depth))
                i = k
                continue
        elif t.kind == "id":
            # var.unlock() ends the hold early.
            if (
                i + 3 < n
                and toks[i + 1].text == "."
                and toks[i + 2].text == "unlock"
                and toks[i + 3].text == "("
            ):
                for g in list(open_guards):
                    if g[0] == t.text:
                        scopes.append(LockScope(g[1], g[2], i))
                        open_guards.remove(g)
        i += 1
    for var, lock, start, d in open_guards:
        scopes.append(LockScope(lock, start, n))
    return scopes


def held_locks_at(scopes: Sequence[LockScope], index: int) -> Set[str]:
    return {s.lock for s in scopes if s.start_index <= index < s.end_index}


# ---------------------------------------------------------------------------
# Member accesses

@dataclasses.dataclass
class CxxAccess:
    owner: str  # class name
    attr: str
    kind: str  # 'read' | 'write'
    func: str  # qualified function name
    path: str
    line: int
    held: frozenset
    in_init: bool
    rmw: bool = False


_MUTATORS = {
    "push_back", "emplace_back", "emplace", "pop_front", "pop_back",
    "clear", "erase", "insert", "swap", "push", "pop", "resize",
}

_WRITE_NEXT = {"=", "+=", "-=", "*=", "/=", "|=", "&=", "^=", "++", "--"}


def member_accesses(ctx: CxxFileContext, cls: CxxClass,
                    fn: CxxFunction) -> List[CxxAccess]:
    """Occurrences of `cls` members inside `fn`, with lock context.

    Constructors, the destructor, and move/copy assignment are marked
    in_init (no concurrent observers during construction / ownership
    transfer — same exemption as the Python rules' __init__)."""
    in_init = (
        fn.name == cls.name
        or fn.name == f"~{cls.name}"
        or fn.name.startswith("operator=")
        or fn.name == "operator="
    )
    scopes = lock_scopes(fn)
    holds = ctx.holds_annotation_for_line(fn.start_line)
    extra_held: Set[str] = set()
    if holds:
        extra_held.add(holds.split(".")[-1])
    # `// Caller holds mu_.` style doc comments are NOT annotations; only
    # the formal grammar counts.
    out: List[CxxAccess] = []
    toks = fn.tokens
    n = len(toks)
    for i, t in enumerate(toks):
        if t.kind != "id" or t.text not in cls.members:
            continue
        member = cls.members[t.text]
        if member.is_mutex:
            continue  # touching the lock IS acquiring it
        prev = toks[i - 1] if i > 0 else None
        nxt = toks[i + 1] if i + 1 < n else None
        # `other.base_` in move ops: still this class's member; keep.
        kind = "read"
        rmw = False
        if nxt is not None and nxt.kind == "punct":
            if nxt.text in _WRITE_NEXT and nxt.text != "==":
                kind = "write"
                rmw = nxt.text in ("+=", "-=", "*=", "/=", "|=", "&=",
                                   "^=", "++", "--")
            elif nxt.text == "." and i + 2 < n and (
                toks[i + 2].text in _MUTATORS
            ):
                kind = "write"
                rmw = True
        if prev is not None and prev.text in ("++", "--"):
            kind = "write"
            rmw = True
        held = frozenset(
            f"{cls.name}.{lk}" for lk in (held_locks_at(scopes, i) | extra_held)
        )
        out.append(
            CxxAccess(
                owner=f"cxx::{cls.name}",
                attr=t.text,
                kind=kind,
                func=f"cxx::{fn.qual}",
                path=ctx.path,
                line=t.line,
                held=held,
                in_init=in_init,
                rmw=rmw,
            )
        )
    return out


# ---------------------------------------------------------------------------
# Thread spawns + call graph

@dataclasses.dataclass
class SpawnSite:
    line: int
    callees: Set[str]
    multi: bool  # spawn site lexically inside a loop
    func: str  # spawning function qual


def thread_spawns(ctx: CxxFileContext) -> List[SpawnSite]:
    """`std::thread(...)` constructions and `*.emplace_back([..]{...})`
    on a vector<std::thread> (recognized lexically: emplace_back whose
    argument starts with a lambda). Callees = identifiers called inside
    the thread body/lambda."""
    out: List[SpawnSite] = []
    for fn in ctx.functions:
        toks = fn.tokens
        n = len(toks)
        loop_depths: List[int] = []
        depth = 0
        i = 0
        while i < n:
            t = toks[i]
            if t.text == "{":
                depth += 1
            elif t.text == "}":
                depth -= 1
                loop_depths = [d for d in loop_depths if d <= depth]
            elif t.kind == "id" and t.text in ("for", "while"):
                loop_depths.append(depth + 1)
            lam_start = None
            if (
                t.kind == "id"
                and t.text == "thread"
                and i + 1 < n
                and toks[i + 1].text in ("(", "{")
            ):
                lam_start = i + 1
            elif (
                t.kind == "id"
                and t.text == "emplace_back"
                and i + 1 < n
                and toks[i + 1].text == "("
                and i + 2 < n
                and toks[i + 2].text == "["
            ):
                lam_start = i + 1
            if lam_start is not None:
                d = 0
                j = lam_start
                callees: Set[str] = set()
                while j < n:
                    if toks[j].text == "(":
                        d += 1
                    elif toks[j].text == ")":
                        d -= 1
                        if d == 0:
                            break
                    elif toks[j].kind == "id" and j + 1 < n and (
                        toks[j + 1].text == "("
                    ) and toks[j].text not in _KEYWORDS:
                        if j != lam_start:
                            callees.add(toks[j].text)
                    j += 1
                out.append(
                    SpawnSite(
                        line=t.line,
                        callees=callees,
                        multi=bool(loop_depths),
                        func=f"cxx::{fn.qual}",
                    )
                )
                i = j
                continue
            i += 1
    return out


def call_edges(ctx: CxxFileContext) -> Dict[str, Set[str]]:
    """Name-based call graph: fn qual -> set of callee NAMES (resolved
    by the caller against known functions; method calls `x->f(` and
    `x.f(` contribute `f`)."""
    edges: Dict[str, Set[str]] = {}
    for fn in ctx.functions:
        callees: Set[str] = set()
        toks = fn.tokens
        n = len(toks)
        for i, t in enumerate(toks[fn.body_start:], start=fn.body_start):
            if t.kind != "id" or t.text in _KEYWORDS:
                continue
            if i + 1 < n and toks[i + 1].text == "(":
                callees.add(t.text)
        edges[fn.qual] = callees
    return edges


def resolve_callees(ctxs: Sequence[CxxFileContext],
                    names: Set[str]) -> Dict[str, List[CxxFunction]]:
    """Callee name -> candidate function definitions across files."""
    out: Dict[str, List[CxxFunction]] = {}
    for ctx in ctxs:
        for fn in ctx.functions:
            out.setdefault(fn.name, []).append(fn)
    return {name: out.get(name, []) for name in names}


# ---------------------------------------------------------------------------
# GIL events

@dataclasses.dataclass
class GilEvent:
    index: int  # token index within fn.tokens
    line: int
    kind: str  # ensure | release | begin_allow | end_allow | nogil_start |
    #            nogil_end | guard (RAII) | api_call | blocking_call
    name: str = ""


_GIL_EXEMPT = {
    "PyGILState_Ensure", "PyGILState_Release", "PyGILState_STATE",
    "Py_BEGIN_ALLOW_THREADS", "Py_END_ALLOW_THREADS", "PyObject_HEAD",
    "PyVarObject_HEAD_INIT", "PyModuleDef_HEAD_INIT", "Py_ssize_t",
}

# Direct blocking primitives: condition/future waits, socket syscalls,
# sleeps. Matched as called names; the interprocedural summary lifts
# them through helpers (a GIL-held call to BatchingQueue::enqueue is a
# finding because enqueue can wait on can_enqueue_).
BLOCKING_PRIMITIVES = {
    "wait", "wait_for", "wait_until", "sleep_for", "sleep_until",
    "recv", "recvmsg", "accept", "poll", "select", "connect",
    "recv_exact", "recv_sized", "sendall", "sendmsg",
}

# Method names shared with the standard containers/strings. The
# may-block summary is NAME-based (no type resolution), so these never
# participate in it: `list.reserve(n)` must not inherit may-block-ness
# from ShmRing::reserve. The cost is a missed finding if binding code
# ever calls such a same-named repo function directly while holding the
# GIL — silence over a guess, the frontend's standing contract.
STL_METHOD_NAMES = {
    "reserve", "resize", "insert", "erase", "clear", "swap", "count",
    "find", "at", "map", "get", "front", "back", "begin", "end",
    "emplace", "emplace_back", "push_back", "pop_back", "push_front",
    "pop_front", "data", "size", "empty", "str", "c_str", "reset",
    "release", "substr", "append",
}


def gil_events(fn: CxxFunction) -> List[GilEvent]:
    """Lexical GIL-relevant events in order: acquire/release ops, the
    call_nogil(...) released region, CPython API calls (`Py*`/`_Py*`/
    `PyArray_*` identifiers followed by '('), and potentially-blocking
    calls. The scan is lexical (no CFG): adequate for the straight-line
    acquire..release shapes this repo uses; anything cleverer needs an
    inline suppression with the reasoning.

    The signature (everything before the body's '{') is skipped: the
    function's own name token would otherwise read as a recursive call
    to itself, poisoning the may-block fixpoint."""
    toks = fn.tokens
    n = len(toks)
    events: List[GilEvent] = []
    nogil_until: List[int] = []  # stack of close indices for call_nogil spans
    i = fn.body_start
    while i < n:
        t = toks[i]
        if t.kind == "id":
            nxt = toks[i + 1] if i + 1 < n else None
            called = nxt is not None and nxt.text == "("
            if t.text == "PyGILState_Ensure" and called:
                events.append(GilEvent(i, t.line, "ensure"))
            elif t.text == "PyGILState_Release" and called:
                events.append(GilEvent(i, t.line, "release"))
            elif t.text == "Py_BEGIN_ALLOW_THREADS":
                events.append(GilEvent(i, t.line, "begin_allow"))
            elif t.text == "Py_END_ALLOW_THREADS":
                events.append(GilEvent(i, t.line, "end_allow"))
            elif t.text == "GILGuard" and nxt is not None and (
                nxt.kind == "id" or nxt.text in ("(", "{")
            ):
                events.append(GilEvent(i, t.line, "guard"))
            elif t.text == "call_nogil" and called:
                # The lambda argument runs between Py_BEGIN/END inside
                # call_nogil: mark the span released.
                d = 0
                j = i + 1
                while j < n:
                    if toks[j].text == "(":
                        d += 1
                    elif toks[j].text == ")":
                        d -= 1
                        if d == 0:
                            break
                    j += 1
                events.append(GilEvent(i, t.line, "nogil_start"))
                events.append(GilEvent(j, toks[min(j, n - 1)].line,
                                       "nogil_end"))
            elif called and re.match(r"^(_?Py[A-Z]|Py_[A-Z]|PyArray)", t.text) \
                    and t.text not in _GIL_EXEMPT:
                # Py_RETURN_* are statement macros without parens; the
                # paren requirement keeps casts/types out.
                events.append(GilEvent(i, t.line, "api_call", t.text))
            elif called and t.text in BLOCKING_PRIMITIVES:
                events.append(GilEvent(i, t.line, "blocking_call", t.text))
            elif called and t.text not in _KEYWORDS:
                events.append(GilEvent(i, t.line, "call", t.text))
        i += 1
    events.sort(key=lambda e: e.index)
    return events


# ---------------------------------------------------------------------------
# shm ring header accesses (ATOMIC-ORDER raw material)

HEADER_WORDS = {
    "kRingHeadWord": "head",
    "kRingTailWord": "tail",
    "kRingCapacityWord": "capacity",
    "kRingWaitingWord": "waiting",
}


@dataclasses.dataclass
class HeaderAccess:
    word: str  # head | tail | capacity | waiting
    op: str  # load | store | raw
    order: str  # memory_order suffix ('' when missing/raw)
    func: str  # enclosing function name
    line: int


def ring_header_accesses(ctx: CxxFileContext) -> List[HeaderAccess]:
    """Every use of a kRing*Word constant, classified by accessor shape:
    `word(kX)->load/store(..., std::memory_order_Y)` is the designated
    pattern; anything else is op='raw' (a finding for ATOMIC-ORDER)."""
    out: List[HeaderAccess] = []
    for fn in ctx.functions:
        toks = fn.tokens
        n = len(toks)
        for i, t in enumerate(toks):
            if t.kind != "id" or t.text not in HEADER_WORDS:
                continue
            word = HEADER_WORDS[t.text]
            # Expect: word ( kX ) -> load|store ( ... memory_order_Y ... )
            prev_ok = (
                i >= 2
                and toks[i - 1].text == "("
                and toks[i - 2].kind == "id"
                and toks[i - 2].text == "word"
            )
            op = "raw"
            order = ""
            if prev_ok and i + 2 < n and toks[i + 1].text == ")" and (
                toks[i + 2].text == "->"
            ) and i + 3 < n and toks[i + 3].text in ("load", "store"):
                op = toks[i + 3].text
                # scan the call parens for a memory_order token
                j = i + 4
                d = 0
                while j < n:
                    if toks[j].text == "(":
                        d += 1
                    elif toks[j].text == ")":
                        d -= 1
                        if d == 0:
                            break
                    elif toks[j].kind == "id" and toks[j].text.startswith(
                        "memory_order"
                    ):
                        order = toks[j].text.replace("memory_order_", "")
                    j += 1
            out.append(HeaderAccess(word, op, order, fn.name, t.line))
    return out


def raw_u64_casts(ctx: CxxFileContext) -> List[Tuple[str, int]]:
    """reinterpret_cast<...uint64_t*>(...) sites NOT casting to
    std::atomic — a raw header-word deref candidate. Returns
    (enclosing function, line)."""
    out: List[Tuple[str, int]] = []
    for fn in ctx.functions:
        toks = fn.tokens
        n = len(toks)
        for i, t in enumerate(toks):
            if t.kind != "id" or t.text != "reinterpret_cast":
                continue
            j = i + 1
            angle = 0
            saw_u64 = False
            saw_atomic = False
            while j < n:
                if toks[j].text == "<":
                    angle += 1
                elif toks[j].text == ">":
                    angle -= 1
                    if angle == 0:
                        break
                elif toks[j].kind == "id":
                    if toks[j].text == "uint64_t":
                        saw_u64 = True
                    elif toks[j].text == "atomic":
                        saw_atomic = True
                j += 1
            if saw_u64 and not saw_atomic:
                out.append((fn.name, t.line))
    return out


# ---------------------------------------------------------------------------
# Data-region + header access SEQUENCES (protocol conformance)

def access_sequence(ctx: CxxFileContext, class_name: str, fn_name: str,
                    _depth: int = 0) -> List[str]:
    """Ordered header/data ops for one ShmRing method, with same-class
    helper calls spliced in (depth 2): 'R:head', 'W:head', 'R:tail',
    'W:tail', 'R:waiting', 'W:waiting', 'R:data', 'W:data'."""
    fn = ctx.function_named(fn_name, class_name) or ctx.function_named(fn_name)
    if fn is None:
        return []
    cls = ctx.classes.get(class_name)
    method_names = set(cls.methods) if cls is not None else set()
    toks = fn.tokens
    n = len(toks)
    seq: List[str] = []
    for i, t in enumerate(toks):
        if t.kind != "id":
            continue
        if t.text in HEADER_WORDS:
            word = HEADER_WORDS[t.text]
            op = "R"
            if i + 3 < n and toks[i + 1].text == ")" and (
                toks[i + 2].text == "->"
            ) and toks[i + 3].text == "store":
                op = "W"
            seq.append(f"{op}:{word}")
        elif t.text in ("memcpy", "load_u32le") and i + 1 < n and (
            toks[i + 1].text == "("
        ):
            # memcpy(data() + ..., src, n) writes the data region;
            # load_u32le(data() + pos) reads it. Only count calls whose
            # argument window mentions data().
            j = i + 1
            d = 0
            mentions_data = False
            first_arg_data = False
            arg_index = 0
            while j < n:
                if toks[j].text == "(":
                    d += 1
                elif toks[j].text == ")":
                    d -= 1
                    if d == 0:
                        break
                elif d == 1 and toks[j].text == ",":
                    arg_index += 1
                elif toks[j].kind == "id" and toks[j].text == "data":
                    mentions_data = True
                    if arg_index == 0:
                        first_arg_data = True
                j += 1
            if mentions_data:
                seq.append(
                    "W:data" if t.text == "memcpy" and first_arg_data
                    else "R:data"
                )
        elif t.text in method_names and t.text != fn_name and _depth < 2 and (
            i + 1 < n and toks[i + 1].text == "("
        ):
            seq.extend(access_sequence(ctx, class_name, t.text,
                                       _depth + 1))
    return seq


def collapse(seq: Sequence[str]) -> List[str]:
    """Adjacent-duplicate collapse ('W:data W:data' -> 'W:data')."""
    out: List[str] = []
    for op in seq:
        if not out or out[-1] != op:
            out.append(op)
    return out
