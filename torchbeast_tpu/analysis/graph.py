"""beastlint whole-program layer: module graph -> call graph -> thread roots.

This module turns the per-file ASTs the engine already parses into ONE
program model the concurrency rules (RACE / LOCK-ORDER /
HOTPATH-SYNC-XPROC, rules.py) can query:

- **Module index**: repo-relative path <-> dotted module name, per-module
  import tables (``from x import y`` / ``import x as z``), module-level
  functions and classes. Re-exports (``runtime/__init__`` re-exporting
  ``BatchingQueue``) are followed through import tables.
- **Class facts**: methods, resolved program-internal bases, lock
  attributes (``self._lock = threading.Lock()``; a ``Condition`` built
  FROM a lock aliases to it, exactly like LOCK-DISCIPLINE), attribute
  types (``self._queue = BatchingQueue(...)``), and callable/type
  bindings flowed through constructors (``InferenceSupervisor(serve_loop,
  state_table=table)`` binds ``self._loop_fn -> serve_loop`` and
  ``self._table -> DeviceStateTable`` when ``__init__`` stores the
  parameter on ``self``).
- **Call graph** with class-method resolution: ``self.m()``, typed-local
  ``obj.m()``, stored-callable ``self._loop_fn()``, property loads on
  typed receivers, ``getattr(obj, "name", default)``, and plain/module
  calls. Resolution is deliberately partial — an unresolvable call is a
  missing edge, never a guess — so every downstream rule errs toward
  silence, not noise.
- **Thread-root graph**: every ``Thread(target=...)`` /
  ``Process(target=...)`` spawn site (loop/comprehension spawns are
  marked multi-instance: N threads run the same body against shared
  ``self``), plus the configured driver entrypoints
  (config.THREAD_ROOT_FUNCTIONS in config.CONCURRENCY_PATHS). Each
  root's transitive callees come from a BFS over the call graph.
- **Access + lock facts**: every ``self.attr`` / typed-local attr
  read/write with the lexically-held lock set at that statement
  (``with self._lock:`` blocks, ``# beastlint: holds`` annotations,
  bare ``.acquire()`` within its statement list), every lock-acquisition
  edge (acquire Y while holding X), and per-function lexical lock sets
  for the interprocedural LOCK-ORDER closure.

Everything here is stdlib-`ast` only (the analysis package's own
IMPORT-PURITY contract) and built ONCE per run: rules share the Program
via `get_program(contexts)`'s single-entry cache.
"""

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from . import config
from .engine import FileContext

# Mutating container methods: calling one on `self.attr` writes the
# shared object even though the attribute access itself is a Load.
_MUTATORS = {
    "append", "extend", "insert", "pop", "popleft", "appendleft",
    "remove", "clear", "update", "setdefault", "add", "discard",
    "sort", "reverse",
}

# Spawn constructors matched by name suffix so both `threading.Thread`
# and a spawn-context's `ctx.Process` register without type inference.
_THREAD_SUFFIXES = ("Thread",)
_PROCESS_SUFFIXES = ("Process",)


def module_name(path: str) -> str:
    p = path[:-3] if path.endswith(".py") else path
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


def _attr_chain(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


@dataclasses.dataclass
class SpawnSite:
    path: str
    line: int
    kind: str  # "thread" | "process"
    target_text: str  # source text of the target= expression
    target: Optional[str]  # resolved function qual, or None
    func: Optional[str]  # enclosing function qual, or None (module level)
    multi: bool  # spawned inside a loop/comprehension: N instances


@dataclasses.dataclass
class RootInfo:
    root_id: str
    func: str  # the root's entry function qual
    kind: str  # "thread" | "process" | "driver"
    spawn_func: Optional[str]  # function containing the spawn site
    multi: bool


@dataclasses.dataclass
class AttrAccess:
    owner: str  # class qual (or "<module>::path" for globals)
    attr: str
    kind: str  # "read" | "write"
    path: str
    line: int
    func: str  # enclosing function qual
    held: FrozenSet[str]
    in_init: bool
    rmw: bool = False  # read-modify-write (`+=`, mutator, item store)


@dataclasses.dataclass
class LockEdge:
    held: str  # lock id already held
    acquired: str  # lock id acquired under it
    path: str
    line: int
    func: str
    via: str  # "" for a lexical nesting, else the callee qual


class FuncInfo:
    def __init__(self, qual, path, node, ctx, cls=None, parent=None):
        self.qual = qual
        self.path = path
        self.node = node
        self.ctx = ctx
        self.cls = cls  # owning class qual, or None
        self.parent = parent  # enclosing function qual for nested defs
        args = node.args
        self.params = [a.arg for a in args.posonlyargs + args.args]
        self.is_property = any(
            isinstance(d, ast.Name) and d.id == "property"
            for d in node.decorator_list
        )


class ClassInfo:
    def __init__(self, qual, path, node, ctx):
        self.qual = qual
        self.path = path
        self.node = node
        self.ctx = ctx
        self.name = node.name
        self.base_names = [_attr_chain(b) for b in node.bases]
        self.bases: List[str] = []  # resolved program-internal quals
        self.methods: Dict[str, FuncInfo] = {}
        # attr -> frozenset of lock ids held once acquired (a Condition
        # built from self._lock yields {cond_id, lock_id}).
        self.lock_attrs: Dict[str, FrozenSet[str]] = {}
        self.reentrant: Set[str] = set()  # RLock attr ids
        self.attr_types: Dict[str, str] = {}  # attr -> class qual
        self.attr_funcs: Dict[str, Set[str]] = {}  # attr -> func quals
        self.init_param_attr: Dict[str, str] = {}  # param -> stored attr
        self.guarded: Dict[str, str] = {}  # attr -> annotated lock attr

    def lock_id(self, attr: str) -> str:
        return f"{self.qual}.{attr}"


class Program:
    """The resolved whole-program model (build with `build_program`)."""

    def __init__(self, contexts: Sequence[FileContext]):
        self.contexts = list(contexts)
        self.by_path: Dict[str, FileContext] = {
            c.path: c for c in contexts
        }
        self.mod_to_path: Dict[str, str] = {
            module_name(c.path): c.path for c in contexts
        }
        # path -> local name -> ("mod", modname) | ("from", modname, attr)
        self.imports: Dict[str, Dict[str, Tuple]] = {}
        # path -> name -> ("func", qual) | ("class", qual)
        self.module_defs: Dict[str, Dict[str, Tuple[str, str]]] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.call_edges: Dict[str, Set[str]] = {}
        # (caller, callee, path, line, held)
        self.call_sites: List[Tuple[str, str, str, int, FrozenSet[str]]] = []
        self.accesses: List[AttrAccess] = []
        self.lock_edges: List[LockEdge] = []
        self.func_acquires: Dict[str, Set[str]] = {}
        self.reentrant_ids: Set[str] = set()
        self.spawn_sites: List[SpawnSite] = []
        # func qual -> first line of a `.start()` call inside it (for
        # the spawn-site ordering exemption: writes before the first
        # start() happen-before the spawned thread).
        self.start_lines: Dict[str, int] = {}
        self.roots: Dict[str, RootInfo] = {}
        self.func_roots: Dict[str, Set[str]] = {}
        # (func_qual, param) -> bound function quals / class quals
        self.param_funcs: Dict[Tuple[str, str], Set[str]] = {}
        self.param_types: Dict[Tuple[str, str], Set[str]] = {}
        # Caches: per-function resolved env (cleared between binding
        # passes, stable afterwards) and flattened own-node lists.
        self.env_cache: Dict[str, "_Env"] = {}
        self.node_cache: Dict[str, list] = {}

    def own_nodes(self, info: "FuncInfo") -> list:
        nodes = self.node_cache.get(info.qual)
        if nodes is None:
            nodes = list(_own_nodes(info.node))
            self.node_cache[info.qual] = nodes
        return nodes

    # -- name resolution ---------------------------------------------------

    def resolve_module_attr(
        self, path: str, name: str, _seen: Optional[Set] = None
    ) -> Optional[Tuple[str, str]]:
        """('func'|'class', qual) for `name` in module `path`, following
        one re-export chain through import tables (cycle-guarded)."""
        _seen = _seen or set()
        if (path, name) in _seen:
            return None
        _seen.add((path, name))
        defs = self.module_defs.get(path, {})
        if name in defs:
            return defs[name]
        imp = self.imports.get(path, {}).get(name)
        if imp is None:
            return None
        if imp[0] == "from":
            target_path = self.mod_to_path.get(imp[1])
            if target_path is None:
                return None  # module outside the scanned program
            return self.resolve_module_attr(target_path, imp[2], _seen)
        return None

    def _imported_module_path(self, path: str, name: str) -> Optional[str]:
        imp = self.imports.get(path, {}).get(name)
        if imp is None:
            return None
        if imp[0] == "mod":
            return self.mod_to_path.get(imp[1])
        if imp[0] == "from":
            return self.mod_to_path.get(f"{imp[1]}.{imp[2]}")
        return None

    def class_method(self, cls_qual: str, name: str,
                     _seen=None) -> Optional[FuncInfo]:
        _seen = _seen or set()
        if cls_qual in _seen:
            return None
        _seen.add(cls_qual)
        cls = self.classes.get(cls_qual)
        if cls is None:
            return None
        if name in cls.methods:
            return cls.methods[name]
        for base in cls.bases:
            found = self.class_method(base, name, _seen)
            if found is not None:
                return found
        return None

    def class_lock_ids(self, cls_qual: str, attr: str) -> FrozenSet[str]:
        """Lock ids acquired by entering `with <obj-of-cls>.attr:` —
        empty when the attribute is not a known lock."""
        cls = self.classes.get(cls_qual)
        while cls is not None:
            if attr in cls.lock_attrs:
                return cls.lock_attrs[attr]
            cls = self.classes.get(cls.bases[0]) if cls.bases else None
        return frozenset()

    def is_lock_attr(self, cls_qual: str, attr: str) -> bool:
        return bool(self.class_lock_ids(cls_qual, attr))


# ---------------------------------------------------------------------------
# Builder


def build_program(contexts: Sequence[FileContext]) -> Program:
    prog = Program(contexts)
    _index_modules(prog)
    _index_classes(prog)
    # Constructor/call-site bindings feed attribute types, which feed
    # better bindings: two passes reach the repo's patterns (a typed
    # local passed into a constructor whose __init__ stores it). Envs
    # are cached per pass (bindings change between passes) and stay
    # cached from the final walk on for the rules/summaries layer.
    for _ in range(2):
        prog.env_cache.clear()
        _bind_call_sites(prog)
    prog.env_cache.clear()
    _final_walk(prog)
    _seed_roots(prog)
    return prog


_CACHE: List[Tuple[tuple, Program]] = []


def get_program(contexts: Sequence[FileContext]) -> Program:
    """Single-entry cache: the three concurrency rules in one run share
    one Program instead of rebuilding it per rule."""
    key = tuple(id(c) for c in contexts)
    if _CACHE and _CACHE[0][0] == key:
        return _CACHE[0][1]
    prog = build_program(contexts)
    _CACHE[:] = [(key, prog)]
    return prog


def _index_modules(prog: Program) -> None:
    for ctx in prog.contexts:
        imports: Dict[str, Tuple] = {}
        defs: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imports[alias.asname or alias.name.split(".")[0]] = (
                        "mod", alias.name,
                    )
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if node.level:
                    pkg = module_name(ctx.path).split(".")
                    # level 1 inside pkg/mod.py -> pkg; __init__ paths
                    # already dropped their own name in module_name.
                    base = pkg[: len(pkg) - node.level] if not ctx.path.endswith(
                        "__init__.py"
                    ) else pkg[: len(pkg) - node.level + 1]
                    mod = ".".join(base + ([mod] if mod else []))
                for alias in node.names:
                    imports[alias.asname or alias.name] = (
                        "from", mod, alias.name,
                    )
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = ("func", f"{ctx.path}::{node.name}")
            elif isinstance(node, ast.ClassDef):
                defs[node.name] = ("class", f"{ctx.path}::{node.name}")
        prog.imports[ctx.path] = imports
        prog.module_defs[ctx.path] = defs
        # Index every def at every nesting depth as a function.
        _index_defs(prog, ctx, ctx.tree.body, cls=None, parent=None)


def _index_defs(prog, ctx, body, cls, parent) -> None:
    for node in body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if cls is not None:
                qual = f"{cls}.{node.name}"
            elif parent is not None:
                qual = f"{parent}.{node.name}"
            else:
                qual = f"{ctx.path}::{node.name}"
            info = FuncInfo(qual, ctx.path, node, ctx, cls=cls,
                            parent=parent)
            prog.functions[qual] = info
            if cls is not None:
                prog.classes[cls].methods.setdefault(node.name, info)
            _index_defs(prog, ctx, node.body, cls=None, parent=qual)
        elif isinstance(node, ast.ClassDef):
            qual = f"{ctx.path}::{node.name}"
            if parent is not None or cls is not None:
                continue  # nested classes: out of model
            prog.classes[qual] = ClassInfo(qual, ctx.path, node, ctx)
            _index_defs(prog, ctx, node.body, cls=qual, parent=None)


def _lock_ctor(prog, ctx, value) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' when `value` constructs one."""
    if not isinstance(value, ast.Call):
        return None
    base = _attr_chain(value.func).split(".")[-1]
    return base if base in ("Lock", "RLock", "Condition") else None


def _index_classes(prog: Program) -> None:
    for cls in prog.classes.values():
        for name in cls.base_names:
            root = name.split(".")[0]
            resolved = prog.resolve_module_attr(cls.path, root)
            if resolved and resolved[0] == "class":
                cls.bases.append(resolved[1])
        for node in ast.walk(cls.node):
            if isinstance(node, ast.Assign):
                target = node.targets[0] if node.targets else None
                value = node.value
            elif isinstance(node, ast.AnnAssign):
                target, value = node.target, node.value
            else:
                continue
            if not (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                continue
            attr = target.attr
            ctor = _lock_ctor(prog, cls.ctx, value)
            if ctor in ("Lock", "RLock"):
                cls.lock_attrs[attr] = frozenset({cls.lock_id(attr)})
                if ctor == "RLock":
                    cls.reentrant.add(cls.lock_id(attr))
                continue
            if ctor == "Condition":
                held = {cls.lock_id(attr)}
                if value.args:
                    inner = value.args[0]
                    if (
                        isinstance(inner, ast.Attribute)
                        and isinstance(inner.value, ast.Name)
                        and inner.value.id == "self"
                    ):
                        held.add(cls.lock_id(inner.attr))
                cls.lock_attrs[attr] = frozenset(held)
                continue
            if isinstance(value, ast.Call):
                resolved = _resolve_value_class(prog, cls.path, value.func)
                if resolved is not None:
                    cls.attr_types.setdefault(attr, resolved)
            # guarded-by annotations on the attr assignment line.
            annotation = cls.ctx.guarded_annotations.get(node.lineno)
            if annotation is not None:
                cls.guarded.setdefault(attr, annotation.split(".")[-1])
        init = cls.methods.get("__init__")
        if init is not None:
            params = set(init.params)
            for node in ast.walk(init.node):
                if not isinstance(node, ast.Assign):
                    continue
                target = node.targets[0] if node.targets else None
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                    and isinstance(node.value, ast.Name)
                    and node.value.id in params
                ):
                    cls.init_param_attr[node.value.id] = target.attr
    for cls in prog.classes.values():
        prog.reentrant_ids |= cls.reentrant


def _resolve_value_class(prog, path, func_node) -> Optional[str]:
    """Class qual when `func_node` (a call's func) names a program class."""
    if isinstance(func_node, ast.Name):
        resolved = prog.resolve_module_attr(path, func_node.id)
        if resolved and resolved[0] == "class":
            return resolved[1]
        return None
    chain = _attr_chain(func_node)
    if not chain or "." not in chain:
        return None
    root, rest = chain.split(".", 1)
    mod_path = prog._imported_module_path(path, root)
    if mod_path is not None and "." not in rest:
        resolved = prog.resolve_module_attr(mod_path, rest)
        if resolved and resolved[0] == "class":
            return resolved[1]
    return None


class _Env:
    """Per-function local bindings: name -> class qual / function quals."""

    def __init__(self, parent: Optional["_Env"] = None):
        self.types: Dict[str, str] = dict(parent.types) if parent else {}
        self.funcs: Dict[str, Set[str]] = (
            {k: set(v) for k, v in parent.funcs.items()} if parent else {}
        )
        self.local_locks: Dict[str, FrozenSet[str]] = (
            dict(parent.local_locks) if parent else {}
        )
        # name -> class qual for CLASS aliases (`pool_cls = ActorPool`,
        # incl. through a conditional expression) — calling the alias
        # constructs that class.
        self.class_aliases: Dict[str, str] = (
            dict(parent.class_aliases) if parent else {}
        )


def _function_env(prog, info: FuncInfo, parent: Optional[_Env]) -> _Env:
    """Local type/callable/lock bindings visible inside `info` (straight
    scan of its body assignments; enclosing-scope bindings inherit)."""
    env = _Env(parent)
    cls = prog.classes.get(info.cls) if info.cls else None
    if cls is not None and info.params:
        env.types.setdefault(info.params[0], cls.qual)  # self
    for pname in info.params:
        for t in prog.param_types.get((info.qual, pname), ()):
            env.types.setdefault(pname, t)
        bound = prog.param_funcs.get((info.qual, pname))
        if bound:
            env.funcs.setdefault(pname, set()).update(bound)
    top = _top_function(prog, info)
    for node in prog.own_nodes(info):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested defs are their own scopes (walked separately) but
            # their NAMES are local callables here.
            env.funcs.setdefault(node.name, set()).add(
                f"{info.qual}.{node.name}"
            )
    for node in prog.own_nodes(info):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target, value = node.targets[0], node.value
        if not isinstance(target, ast.Name):
            continue
        ctor = _lock_ctor(prog, info.ctx, value)
        if ctor in ("Lock", "RLock"):
            lid = f"{top}.{target.id}"
            env.local_locks[target.id] = frozenset({lid})
            if ctor == "RLock":
                prog.reentrant_ids.add(lid)
            continue
        if ctor == "Condition":
            lid = f"{top}.{target.id}"
            held = {lid}
            if value.args and isinstance(value.args[0], ast.Name):
                inner = env.local_locks.get(value.args[0].id)
                if inner:
                    held |= set(inner)
            env.local_locks[target.id] = frozenset(held)
            continue
        if isinstance(value, ast.IfExp):
            # `pool_cls = NativePool if flags.native else ActorPool`:
            # a class alias through a conditional — take the first
            # branch that resolves to a program class.
            for branch in (value.body, value.orelse):
                cls_qual = _class_ref(prog, info.path, branch)
                if cls_qual is not None:
                    env.class_aliases[target.id] = cls_qual
                    break
            continue
        if isinstance(value, ast.Call):
            ctor = value.func
            resolved = _resolve_value_class(prog, info.path, ctor)
            if resolved is None and isinstance(ctor, ast.Name):
                resolved = env.class_aliases.get(ctor.id)
            if resolved is not None:
                env.types[target.id] = resolved
                continue
            # v = getattr(obj, "name", default) -> bound method/property
            if (
                isinstance(value.func, ast.Name)
                and value.func.id == "getattr"
                and len(value.args) >= 2
                and isinstance(value.args[0], ast.Name)
                and isinstance(value.args[1], ast.Constant)
                and isinstance(value.args[1].value, str)
            ):
                recv = env.types.get(value.args[0].id)
                if recv:
                    m = prog.class_method(recv, value.args[1].value)
                    if m is not None:
                        env.funcs.setdefault(target.id, set()).add(m.qual)
        elif isinstance(value, ast.Attribute) and isinstance(
            value.value, ast.Name
        ):
            recv = env.types.get(value.value.id)
            if recv:
                t = prog.classes.get(recv)
                if t is not None and value.attr in t.attr_types:
                    env.types[target.id] = t.attr_types[value.attr]
                elif t is not None and value.attr in t.methods:
                    env.funcs.setdefault(target.id, set()).add(
                        t.methods[value.attr].qual
                    )
        elif isinstance(value, ast.Name):
            # Aliasing: v = some_function / v = SomeClass / v = typed_local.
            resolved = prog.resolve_module_attr(info.path, value.id)
            if resolved and resolved[0] == "func":
                env.funcs.setdefault(target.id, set()).add(resolved[1])
            elif resolved and resolved[0] == "class":
                env.class_aliases[target.id] = resolved[1]
            elif value.id in env.types:
                env.types[target.id] = env.types[value.id]
    return env


def _class_ref(prog, path: str, node) -> Optional[str]:
    """Class qual when `node` REFERENCES (not constructs) a class."""
    if isinstance(node, ast.Name):
        resolved = prog.resolve_module_attr(path, node.id)
        if resolved and resolved[0] == "class":
            return resolved[1]
        return None
    if isinstance(node, ast.Attribute):
        return _resolve_value_class(
            prog, path, node
        )
    return None


def _own_nodes(fn_node):
    """Nodes of a function EXCLUDING nested function/class bodies."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _top_function(prog, info: FuncInfo) -> str:
    while info.parent is not None and info.parent in prog.functions:
        info = prog.functions[info.parent]
    return info.qual


def _expr_type(prog, env: _Env, node) -> Optional[str]:
    """Class qual of a Name or single-level typed-attribute expression."""
    if isinstance(node, ast.Name):
        return env.types.get(node.id)
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        outer = env.types.get(node.value.id)
        if outer is not None:
            cls = prog.classes.get(outer)
            if cls is not None:
                return cls.attr_types.get(node.attr)
    return None


def _resolve_call_targets(prog, info: FuncInfo, env: _Env,
                          call: ast.Call) -> Set[str]:
    """Function quals a call may dispatch to (empty when unresolvable)."""
    out: Set[str] = set()
    func = call.func
    if isinstance(func, ast.Name):
        name = func.id
        if name in env.funcs:
            out |= env.funcs[name]
        resolved = prog.resolve_module_attr(info.path, name)
        if resolved is None and name in env.class_aliases:
            resolved = ("class", env.class_aliases[name])
        if resolved is not None:
            if resolved[0] == "func":
                out.add(resolved[1])
            else:  # class construction -> __init__
                m = prog.class_method(resolved[1], "__init__")
                if m is not None:
                    out.add(m.qual)
        return out
    if not isinstance(func, ast.Attribute):
        return out
    # super().__init__() and friends.
    if (
        isinstance(func.value, ast.Call)
        and isinstance(func.value.func, ast.Name)
        and func.value.func.id == "super"
        and info.cls is not None
    ):
        cls = prog.classes.get(info.cls)
        for base in cls.bases if cls else ():
            m = prog.class_method(base, func.attr)
            if m is not None:
                out.add(m.qual)
        return out
    if isinstance(func.value, ast.Name):
        recv_name = func.value.id
        recv_type = env.types.get(recv_name)
        if recv_type is not None:
            t = prog.classes.get(recv_type)
            m = prog.class_method(recv_type, func.attr)
            if m is not None:
                out.add(m.qual)
            elif t is not None and func.attr in t.attr_funcs:
                out |= t.attr_funcs[func.attr]
            return out
        mod_path = prog._imported_module_path(info.path, recv_name)
        if mod_path is not None:
            resolved = prog.resolve_module_attr(mod_path, func.attr)
            if resolved is not None:
                if resolved[0] == "func":
                    out.add(resolved[1])
                else:
                    m = prog.class_method(resolved[1], "__init__")
                    if m is not None:
                        out.add(m.qual)
        return out
    # self._attr(...) has receiver Name 'self' (handled above);
    # obj.attr.m(...) one level deep: typed receiver attribute.
    if isinstance(func.value, ast.Attribute) and isinstance(
        func.value.value, ast.Name
    ):
        base_type = env.types.get(func.value.value.id)
        if base_type is not None:
            t = prog.classes.get(base_type)
            if t is not None:
                inner = t.attr_types.get(func.value.attr)
                if inner is not None:
                    m = prog.class_method(inner, func.attr)
                    if m is not None:
                        out.add(m.qual)
    return out


def _resolve_constructed_class(prog, info, env, call) -> Optional[str]:
    func = call.func
    if isinstance(func, ast.Name):
        resolved = prog.resolve_module_attr(info.path, func.id)
        if resolved and resolved[0] == "class":
            return resolved[1]
        return env.class_aliases.get(func.id)
    return _resolve_value_class(prog, info.path, func)


def _callable_descriptor(prog, info, env, node) -> Set[str]:
    """Function quals an ARGUMENT expression denotes (for bindings)."""
    out: Set[str] = set()
    if isinstance(node, ast.Name):
        if node.id in env.funcs:
            out |= env.funcs[node.id]
        resolved = prog.resolve_module_attr(info.path, node.id)
        if resolved and resolved[0] == "func":
            out.add(resolved[1])
    elif isinstance(node, ast.Attribute) and isinstance(
        node.value, ast.Name
    ):
        recv = env.types.get(node.value.id)
        if recv:
            m = prog.class_method(recv, node.attr)
            if m is not None:
                out.add(m.qual)
    return out


def _type_descriptor(prog, info, env, node) -> Optional[str]:
    if isinstance(node, ast.Name):
        return env.types.get(node.id)
    if isinstance(node, ast.Call):
        return _resolve_constructed_class(prog, info, env, node)
    return None


def _bind_call_sites(prog: Program) -> None:
    """Flow callables/types through constructors and plain calls:
    `C(f, table=t)` + `__init__(self, fn, table): self._fn = fn` binds
    `(C, _fn) -> f` and `(C, _table) -> type(t)`; calls to plain
    functions bind `(callee, param) -> type/callable` the same way."""
    for info in list(prog.functions.values()):
        env = _build_env_chain(prog, info)
        for node in prog.own_nodes(info):
            if not isinstance(node, ast.Call):
                continue
            cls_qual = _resolve_constructed_class(prog, info, env, node)
            targets: List[Tuple[FuncInfo, Optional[ClassInfo]]] = []
            if cls_qual is not None:
                init = prog.class_method(cls_qual, "__init__")
                if init is not None:
                    targets.append((init, prog.classes.get(cls_qual)))
            else:
                for qual in _resolve_call_targets(prog, info, env, node):
                    callee = prog.functions.get(qual)
                    if callee is not None and callee.cls is None:
                        targets.append((callee, None))
            for callee, cls in targets:
                params = callee.params[1:] if cls is not None else (
                    callee.params
                )
                bound: List[Tuple[str, ast.AST]] = list(
                    zip(params, node.args)
                )
                by_name = set(params)
                for kw in node.keywords:
                    if kw.arg in by_name:
                        bound.append((kw.arg, kw.value))
                for pname, arg in bound:
                    funcs = _callable_descriptor(prog, info, env, arg)
                    a_type = _type_descriptor(prog, info, env, arg)
                    if cls is not None:
                        attr = cls.init_param_attr.get(pname)
                        if attr is None:
                            continue
                        if funcs:
                            cls.attr_funcs.setdefault(attr, set()).update(
                                funcs
                            )
                        if a_type is not None:
                            cls.attr_types.setdefault(attr, a_type)
                    else:
                        key = (callee.qual, pname)
                        if funcs:
                            prog.param_funcs.setdefault(key, set()).update(
                                funcs
                            )
                        if a_type is not None:
                            prog.param_types.setdefault(key, set()).add(
                                a_type
                            )


def _build_env_chain(prog, info: FuncInfo) -> _Env:
    env = prog.env_cache.get(info.qual)
    if env is not None:
        return env
    parent_env = None
    if info.parent and info.parent in prog.functions:
        parent_env = _build_env_chain(prog, prog.functions[info.parent])
    env = _function_env(prog, info, parent_env)
    prog.env_cache[info.qual] = env
    return env


# ---------------------------------------------------------------------------
# Final walk: accesses, call edges, lock edges, spawns


class _FuncWalker:
    def __init__(self, prog: Program, info: FuncInfo, env: _Env):
        self.prog = prog
        self.info = info
        self.env = env
        self.cls = prog.classes.get(info.cls) if info.cls else None
        self.in_init = info.cls is not None and (
            info.node.name == "__init__"
        )
        held: Set[str] = set()
        holds = info.ctx.holds_annotation(info.node)
        if holds and self.cls is not None:
            attr = holds.split(".")[-1]
            held |= self.prog.class_lock_ids(self.cls.qual, attr) or {
                self.cls.lock_id(attr)
            }
        self.entry_held = frozenset(held)
        self.loop_depth = 0
        self.globals: Set[str] = set()
        self.acquires: Set[str] = set()

    # -- lock id resolution ------------------------------------------------

    def _lock_ids_of(self, expr) -> FrozenSet[str]:
        if isinstance(expr, ast.Name):
            return self.env.local_locks.get(expr.id, frozenset())
        if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ):
            recv = self.env.types.get(expr.value.id)
            if recv is not None:
                return self.prog.class_lock_ids(recv, expr.attr)
        return frozenset()

    def _record_acquire(self, ids: FrozenSet[str], held: FrozenSet[str],
                        line: int, via: str = "") -> None:
        self.acquires |= set(ids)
        for acq in ids:
            for h in held:
                # h == acq is a SELF-edge: lexically re-acquiring a lock
                # already held on this path (a Condition aliasing an
                # outer-held lock included). Recorded like any edge —
                # LOCK-ORDER turns non-reentrant self-edges into
                # self-deadlock findings.
                self.prog.lock_edges.append(
                    LockEdge(h, acq, self.info.path, line,
                             self.info.qual, via)
                )

    # -- statement walk ----------------------------------------------------

    def walk(self) -> None:
        self._stmts(self.info.node.body, set(self.entry_held))

    def _stmts(self, stmts, held: Set[str]) -> None:
        i = 0
        while i < len(stmts):
            stmt = stmts[i]
            if isinstance(stmt, ast.With):
                new_held = set(held)
                for item in stmt.items:
                    ids = self._lock_ids_of(item.context_expr)
                    self._expr(item.context_expr, frozenset(new_held))
                    if ids:
                        self._record_acquire(
                            ids, frozenset(new_held), stmt.lineno
                        )
                        new_held |= ids
                    if item.optional_vars is not None:
                        self._expr(item.optional_vars, frozenset(new_held))
                self._stmts(stmt.body, new_held)
            elif isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                pass  # separate scopes, walked via their own FuncInfo
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self.loop_depth += 1
                for field in ("target", "iter", "test"):
                    sub = getattr(stmt, field, None)
                    if sub is not None:
                        self._expr(sub, frozenset(held))
                self._stmts(stmt.body, held)
                self._stmts(stmt.orelse, held)
                self.loop_depth -= 1
            elif isinstance(stmt, ast.Global):
                self.globals |= set(stmt.names)
            else:
                # Bare `x.acquire()` statement: held for the remainder of
                # this statement list (LOCK-DISCIPLINE already enforces
                # the try/finally release shape).
                acquired = self._bare_acquire_ids(stmt)
                if acquired:
                    self._record_acquire(
                        acquired, frozenset(held), stmt.lineno
                    )
                    held = set(held) | acquired
                if isinstance(stmt, ast.AugAssign) and isinstance(
                    stmt.target, ast.Attribute
                ):
                    # `self._x += 1` is a read-modify-write.
                    self._attr_access(stmt.target, frozenset(held),
                                      force_kind="write", rmw=True)
                for _, value in ast.iter_fields(stmt):
                    if isinstance(value, list):
                        if value and isinstance(value[0], ast.stmt):
                            self._stmts(value, set(held))
                        elif value and isinstance(
                            value[0], ast.excepthandler
                        ):
                            for handler in value:
                                if handler.type is not None:
                                    self._expr(
                                        handler.type, frozenset(held)
                                    )
                                self._stmts(handler.body, set(held))
                        else:
                            for v in value:
                                if isinstance(v, ast.expr):
                                    self._expr(v, frozenset(held))
                    elif isinstance(value, ast.expr):
                        self._expr(value, frozenset(held))
            i += 1

    def _bare_acquire_ids(self, stmt) -> FrozenSet[str]:
        if not isinstance(stmt, ast.Expr):
            return frozenset()
        call = stmt.value
        if (
            isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr == "acquire"
        ):
            return self._lock_ids_of(call.func.value)
        return frozenset()

    # -- expression walk ---------------------------------------------------

    def _expr(self, expr, held: FrozenSet[str]) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._call(node, held)
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ) and isinstance(node.value, ast.Attribute):
                # `self._x[k] = v` / `del self._x[k]`: the attribute node
                # itself carries Load ctx — upgrade to a write here.
                self._attr_access(node.value, held, force_kind="write",
                                  rmw=True)
            elif isinstance(node, ast.Attribute):
                self._attr_access(node, held)
            elif isinstance(node, ast.Name) and node.id in self.globals:
                kind = (
                    "write" if isinstance(node.ctx, (ast.Store, ast.Del))
                    else "read"
                )
                self._global_access(node, kind, held)

    def _global_access(self, node, kind, held) -> None:
        self.prog.accesses.append(
            AttrAccess(
                f"<module>::{self.info.path}", node.id, kind,
                self.info.path, node.lineno, self.info.qual, held,
                self.in_init,
            )
        )

    def _receiver_class(self, node: ast.Attribute) -> Optional[str]:
        if not isinstance(node.value, ast.Name):
            return None
        return self.env.types.get(node.value.id)

    def _attr_access(self, node: ast.Attribute, held: FrozenSet[str],
                     force_kind: Optional[str] = None,
                     rmw: bool = False) -> None:
        owner = self._receiver_class(node)
        if owner is None:
            return
        if self.prog.is_lock_attr(owner, node.attr):
            return  # touching a lock IS how you acquire it
        if self.prog.class_method(owner, node.attr) is not None:
            return  # bound-method/property reference, not instance data
        kind = force_kind or (
            "write" if isinstance(node.ctx, (ast.Store, ast.Del))
            else "read"
        )
        self.prog.accesses.append(
            AttrAccess(owner, node.attr, kind, self.info.path,
                       node.lineno, self.info.qual, held, self.in_init,
                       rmw=rmw)
        )

    def _call(self, node: ast.Call, held: FrozenSet[str]) -> None:
        func = node.func
        # Mutator methods on a typed attribute: self._x.append(...) and
        # subscript stores walk through as Attribute loads; upgrade the
        # access kind here.
        if (
            isinstance(func, ast.Attribute)
            and func.attr in _MUTATORS
            and isinstance(func.value, ast.Attribute)
        ):
            self._attr_access(func.value, held, force_kind="write",
                              rmw=True)
        if isinstance(func, ast.Attribute) and func.attr == "start":
            prev = self.prog.start_lines.get(self.info.qual)
            if prev is None or node.lineno < prev:
                self.prog.start_lines[self.info.qual] = node.lineno
        # Spawn sites. The constructor name comes from the last
        # attribute segment even when the chain is rooted in a call
        # (`mp.get_context("spawn").Process(...)`).
        if isinstance(func, ast.Attribute):
            base = func.attr
        elif isinstance(func, ast.Name):
            base = func.id
        else:
            base = ""
        target_kw = next(
            (kw.value for kw in node.keywords if kw.arg == "target"), None
        )
        if target_kw is not None and (
            base.endswith(_THREAD_SUFFIXES)
            or base.endswith(_PROCESS_SUFFIXES)
        ):
            kind = (
                "thread" if base.endswith(_THREAD_SUFFIXES) else "process"
            )
            targets = _callable_descriptor(
                self.prog, self.info, self.env, target_kw
            )
            self.prog.spawn_sites.append(
                SpawnSite(
                    self.info.path, node.lineno, kind,
                    _attr_chain(target_kw) or type(target_kw).__name__,
                    sorted(targets)[0] if targets else None,
                    self.info.qual,
                    multi=self.loop_depth > 0,
                )
            )
        # Call edges + call sites.
        targets = _resolve_call_targets(self.prog, self.info, self.env,
                                        node)
        # Property loads on typed receivers dispatch like calls — but a
        # plain `obj.prop` read is an Attribute, handled in _attr_access
        # only as data. Register the property edge here for the args.
        for qual in targets:
            self.prog.call_edges.setdefault(self.info.qual, set()).add(
                qual
            )
            self.prog.call_sites.append(
                (self.info.qual, qual, self.info.path, node.lineno, held)
            )

def _final_walk(prog: Program) -> None:
    for info in list(prog.functions.values()):
        env = _build_env_chain(prog, info)
        walker = _FuncWalker(prog, info, env)
        walker.walk()
        prog.func_acquires[info.qual] = walker.acquires
    _mark_comprehension_spawns(prog)
    _property_edges(prog)
    _inherit_call_site_locks(prog)


def _inherit_call_site_locks(prog: Program) -> None:
    """A helper called ONLY with lock L held runs with L held: its
    accesses inherit the intersection of every call site's held set
    (one level — the `_require_alive`-under-`self._lock` pattern).
    Functions that are thread-spawn targets are exempt: their real
    entry is the bare thread, not a locked call site."""
    spawn_targets = {s.target for s in prog.spawn_sites if s.target}
    by_callee: Dict[str, List[FrozenSet[str]]] = {}
    for _, callee, _, _, held in prog.call_sites:
        by_callee.setdefault(callee, []).append(held)
    inherited: Dict[str, FrozenSet[str]] = {}
    for callee, helds in by_callee.items():
        if callee in spawn_targets:
            continue
        common = frozenset.intersection(*helds)
        if common:
            inherited[callee] = common
    if not inherited:
        return
    for acc in prog.accesses:
        extra = inherited.get(acc.func)
        if extra:
            acc.held = acc.held | extra


def _mark_comprehension_spawns(prog: Program) -> None:
    """Spawns inside list/set/generator comprehensions are multi-instance
    (one walker pass can't see comprehension nesting cheaply: fix up by
    locating each spawn call's comprehension ancestors per file)."""
    by_path: Dict[str, List[SpawnSite]] = {}
    for site in prog.spawn_sites:
        by_path.setdefault(site.path, []).append(site)
    for path, sites in by_path.items():
        ctx = prog.by_path.get(path)
        if ctx is None:
            continue
        comp_lines: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.ListComp, ast.SetComp,
                                 ast.GeneratorExp, ast.DictComp)):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        comp_lines.add(sub.lineno)
        for site in sites:
            if site.line in comp_lines:
                site.multi = True


def _property_edges(prog: Program) -> None:
    """`obj.prop` loads on typed receivers dispatch to the property body:
    add call edges so RACE sees reads inside properties from the caller's
    roots. (Second pass: needs every function's env; reuses the binding
    machinery rather than the full walker.)"""
    prop_names: Dict[str, Dict[str, str]] = {}
    for cls in prog.classes.values():
        props = {
            name: m.qual for name, m in cls.methods.items()
            if m.is_property
        }
        if props:
            prop_names[cls.qual] = props
    if not prop_names:
        return
    for info in list(prog.functions.values()):
        env = _build_env_chain(prog, info)
        for node in prog.own_nodes(info):
            recv = attr = line = None
            if isinstance(node, ast.Attribute):
                recv = _expr_type(prog, env, node.value)
                attr, line = node.attr, node.lineno
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "getattr"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
            ):
                recv = _expr_type(prog, env, node.args[0])
                attr, line = node.args[1].value, node.lineno
            if recv is None or attr is None:
                continue
            # Walk the MRO for the property table.
            cls = prog.classes.get(recv)
            qual = None
            while cls is not None:
                qual = prop_names.get(cls.qual, {}).get(attr)
                if qual:
                    break
                cls = prog.classes.get(cls.bases[0]) if cls.bases else None
            if qual:
                prog.call_edges.setdefault(info.qual, set()).add(qual)
                prog.call_sites.append(
                    (info.qual, qual, info.path, line, frozenset())
                )


DRIVER_ROOT = "driver-main"


def _seed_roots(prog: Program) -> None:
    for site in prog.spawn_sites:
        if site.target is None:
            continue
        short = site.target.split("::")[-1]
        root_id = f"{short}@{site.path}:{site.line}"
        prog.roots[root_id] = RootInfo(
            root_id, site.target, site.kind, site.func, site.multi
        )
    # Every configured driver entrypoint is ONE root: a process has one
    # main thread, and two drivers never run concurrently in the same
    # process — treating main/train/cli (or two drivers) as distinct
    # roots would conjure conflicts between code that is all executed
    # by the same thread.
    driver_entries: List[str] = []
    for ctx in prog.contexts:
        defs = prog.module_defs.get(ctx.path, {})
        for name in config.THREAD_ROOT_FUNCTIONS:
            entry = defs.get(name)
            if entry and entry[0] == "func":
                driver_entries.append(entry[1])
    reach: Dict[str, Set[str]] = {}
    for root in prog.roots.values():
        reach[root.root_id] = _reachable(prog, root.func)
    driver_reach: Set[str] = set()
    for entry in driver_entries:
        driver_reach |= _reachable(prog, entry)
    if driver_entries:
        prog.roots[DRIVER_ROOT] = RootInfo(
            DRIVER_ROOT, driver_entries[0], "driver", None, False
        )
        reach[DRIVER_ROOT] = driver_reach
    for root_id, quals in reach.items():
        for qual in quals:
            prog.func_roots.setdefault(qual, set()).add(root_id)


def _reachable(prog: Program, start: str) -> Set[str]:
    seen = {start}
    stack = [start]
    while stack:
        cur = stack.pop()
        for nxt in prog.call_edges.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return seen


def transitive_acquires(prog: Program) -> Dict[str, Set[str]]:
    """func qual -> every lock id it may acquire, directly or through
    calls (bounded fixpoint over the call graph)."""
    acq = {q: set(s) for q, s in prog.func_acquires.items()}
    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for caller, callees in prog.call_edges.items():
            mine = acq.setdefault(caller, set())
            before = len(mine)
            for callee in callees:
                mine |= acq.get(callee, set())
            if len(mine) != before:
                changed = True
    return acq
