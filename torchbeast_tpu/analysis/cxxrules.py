"""beastlint C++ rules (ISSUE 10): the concurrency contracts PR 9's
native core lives by, checked statically across the language boundary.

All three are REPO rules (they need the full context set: the C++
frontend contexts from csrc/, and for ATOMIC-ORDER the Python
transport.py AST as well):

    GIL-DISCIPLINE       every CPython API call in csrc/pymodule.cc /
                         actor_pool.h is dominated by a GIL acquire
                         (PyGILState_Ensure, RAII GILGuard, or entry
                         from a Python-registered callable), acquire/
                         release pairing is balanced, and NO potentially
                         blocking call (condition waits, socket recvs,
                         queue dequeues — direct or via the per-function
                         may-block summary) happens while the GIL is
                         held outside a call_nogil region.
    ATOMIC-ORDER         every load/store of the shm ring header words
                         goes through the designated accessors with an
                         explicit (and, at the publish/Dekker sites, the
                         exact documented) memory order; raw u64 casts
                         of the header are findings; the Python side's
                         memoryview header accesses must name their
                         offsets (`self._u64[self._HEAD]`, never a bare
                         index); and BOTH implementations' access
                         sequences must conform to the protocol spec
                         (analysis/protocol.py SPEC_ACCESS) — WIRE-
                         PARITY extended from layout to access
                         discipline.
    CXX-LOCK-DISCIPLINE  `// guarded-by: mu_` members only touched
                         under an RAII guard on that mutex (ctor/dtor/
                         move exempt; `// beastlint: holds mu_` for
                         helpers called locked) — the C++ twin of the
                         Python LOCK-DISCIPLINE rule — plus cross-root
                         conflict detection: std::thread spawn sites
                         join the thread-root graph (each Python-facing
                         entry method is its own root, mirroring PR 7's
                         driver roots), and an unguarded non-atomic
                         member written from one root and touched from
                         another is a finding.
"""

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import config, cxx, protocol
from .engine import Finding


def _cxx_contexts(contexts) -> List["cxx.CxxFileContext"]:
    return [
        ctx for ctx in contexts
        if getattr(ctx, "is_cxx", False) and any(
            ctx.path.startswith(prefix + "/") or ctx.path == prefix
            for prefix in config.CXX_PATHS
        )
    ]


# ---------------------------------------------------------------------------
# GIL-DISCIPLINE


class GilDisciplineRule:
    """GIL-DISCIPLINE: CPython API calls only with the GIL; no blocking
    calls while holding it.

    The binding layer's two invariants (csrc/pymodule.cc):

    - a `Py*` call without the GIL corrupts the interpreter. Entry
      points registered with Python (PyMethodDef tables, type slots —
      recognized by their address being taken) START with the GIL held;
      everything else must acquire it (PyGILState_Ensure / RAII
      GILGuard) before the first API call, in-function or via the call
      summary (a helper only ever called from GIL-held sites inherits
      held-ness).
    - a blocking call (condition wait, socket recv, queue dequeue —
      direct, or via the per-function may-block summary over the csrc
      call graph) while the GIL is held starves every Python thread;
      the `call_nogil([&]{...})` idiom releases it for exactly the
      lambda's span, and Py_BEGIN/END_ALLOW_THREADS pairs must balance.

    The scan is lexical per function (no CFG): right for the
    straight-line acquire..release shapes this repo uses. A cleverer
    control flow needs an inline `// beastlint: disable=GIL-DISCIPLINE
    <why>` with the path reasoning.
    """

    name = "GIL-DISCIPLINE"

    def check_repo(self, root: str, contexts) -> List[Finding]:
        ctxs = [
            ctx for ctx in _cxx_contexts(contexts)
            if ctx.path in config.GIL_FILES
        ]
        if not ctxs:
            return []
        all_cxx = _cxx_contexts(contexts)
        may_block = self._may_block_summary(all_cxx)
        findings: List[Finding] = []
        for ctx in ctxs:
            entry = ctx.address_taken_names()
            entry |= {
                f.name for f in ctx.functions
                if f.name.startswith("PyInit")
            }
            held_entry = self._entry_states(ctx, entry)
            for fn in ctx.functions:
                findings.extend(
                    self._check_function(
                        ctx, fn, held_entry.get(fn.qual, False),
                        may_block,
                    )
                )
        return findings

    # -- interprocedural summaries -------------------------------------

    @staticmethod
    def _may_block_summary(ctxs) -> Set[str]:
        """Function NAMES that may block WITHOUT releasing the GIL
        first: contain a blocking primitive, or call a may-block
        function, OUTSIDE any call_nogil/Py_BEGIN_ALLOW_THREADS span
        (name-resolved fixpoint).

        The nogil exclusion is the point: BatchingQueue::enqueue can
        wait, so `queue->enqueue(...)` bare under the GIL is a finding —
        but pymodule's queue_enqueue wraps it in call_nogil, so CALLING
        queue_enqueue with the GIL held is fine and must not flag.
        STL-collision-prone names (cxx.STL_METHOD_NAMES) never enter
        the propagation: `list.reserve(n)` is not ShmRing::reserve."""
        primitives = set(cxx.BLOCKING_PRIMITIVES) | {"join"}
        edges: Dict[str, Set[str]] = {}
        blocking: Set[str] = set()
        for ctx in ctxs:
            for fn in ctx.functions:
                callees: Set[str] = set()
                nogil_depth = 0
                for ev in cxx.gil_events(fn):
                    if ev.kind in ("nogil_start", "begin_allow"):
                        nogil_depth += 1
                        continue
                    if ev.kind in ("nogil_end", "end_allow"):
                        nogil_depth = max(0, nogil_depth - 1)
                        continue
                    if nogil_depth:
                        continue  # released span: blocking here is fine
                    if ev.kind == "blocking_call":
                        blocking.add(fn.name)
                    elif ev.kind == "call" and ev.name in primitives:
                        blocking.add(fn.name)
                    elif ev.kind == "call" and (
                        ev.name not in cxx.STL_METHOD_NAMES
                    ):
                        callees.add(ev.name)
                edges.setdefault(fn.name, set()).update(callees)
        changed = True
        while changed:
            changed = False
            for name, callees in edges.items():
                if name not in blocking and callees & blocking:
                    blocking.add(name)
                    changed = True
        return blocking

    def _entry_states(self, ctx, entry: Set[str]) -> Dict[str, bool]:
        """fn qual -> GIL held at entry. Python-registered callables
        start held; others inherit from their call sites (any caller
        that calls them at a held point makes them held — conservative
        in the direction that CHECKS the API calls inside). Functions
        never called in-file default to the file's nature: held in the
        binding layer (a helper for entry code), unheld elsewhere."""
        default_held = ctx.path.endswith(".cc")
        states: Dict[str, bool] = {}
        for fn in ctx.functions:
            states[fn.qual] = fn.name in entry
        by_name: Dict[str, List] = {}
        for fn in ctx.functions:
            by_name.setdefault(fn.name, []).append(fn)
        called: Set[str] = set()
        for _ in range(3):
            changed = False
            for fn in ctx.functions:
                held = states[fn.qual] or fn.name in entry
                for ev, held_at in self._walk_held(fn, held):
                    # STL-collision names never resolve name-based
                    # (same contract as the may-block summary).
                    if ev.kind == "call" and ev.name in by_name and (
                        ev.name not in cxx.STL_METHOD_NAMES
                    ):
                        for callee in by_name[ev.name]:
                            called.add(callee.qual)
                            if held_at and not states[callee.qual]:
                                states[callee.qual] = True
                                changed = True
            if not changed:
                break
        for fn in ctx.functions:
            if fn.qual not in called and fn.name not in entry:
                states[fn.qual] = default_held
        for name in entry:
            for fn in by_name.get(name, []):
                states[fn.qual] = True
        return states

    @staticmethod
    def _walk_held(fn, entry_held: bool):
        """Yield (event, gil_held_at_event) lexically."""
        held = entry_held
        nogil_depth = 0
        nogil_ends: List[int] = []
        saved: List[bool] = []
        for ev in cxx.gil_events(fn):
            while nogil_ends and ev.index >= nogil_ends[-1]:
                nogil_ends.pop()
                held = saved.pop()
            if ev.kind == "ensure" or ev.kind == "guard":
                yield ev, held
                held = True
            elif ev.kind == "release":
                yield ev, held
                held = False
            elif ev.kind == "begin_allow":
                yield ev, held
                saved.append(held)
                nogil_ends.append(1 << 60)  # until end_allow
                held = False
            elif ev.kind == "end_allow":
                if nogil_ends:
                    nogil_ends.pop()
                    held = saved.pop()
                yield ev, held
            elif ev.kind == "nogil_start":
                yield ev, held
                saved.append(held)
                held = False
            elif ev.kind == "nogil_end":
                if saved:
                    held = saved.pop()
                yield ev, held
            else:
                yield ev, held

    def _check_function(self, ctx, fn, entry_held: bool,
                        may_block: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        ensures = releases = begins = ends = 0
        is_raii = fn.class_name is not None and (
            fn.name == fn.class_name or fn.name == f"~{fn.class_name}"
        )
        for ev, held in self._walk_held(fn, entry_held):
            if ev.kind == "ensure":
                ensures += 1
            elif ev.kind == "release":
                releases += 1
            elif ev.kind == "begin_allow":
                begins += 1
            elif ev.kind == "end_allow":
                ends += 1
            elif ev.kind == "api_call" and not held:
                findings.append(Finding(
                    self.name, ctx.path, ev.line,
                    f"CPython API call `{ev.name}` on a path without "
                    "the GIL (acquire via PyGILState_Ensure/GILGuard, "
                    "or keep the call out of the released region)",
                ))
            elif held and (
                ev.kind == "blocking_call"
                or (ev.kind == "call" and ev.name in may_block
                    and ev.name not in cxx.STL_METHOD_NAMES)
            ):
                via = (
                    "" if ev.kind == "blocking_call"
                    else f" (it can wait: see `{ev.name}`'s body)"
                )
                findings.append(Finding(
                    self.name, ctx.path, ev.line,
                    f"potentially blocking call `{ev.name}` while the "
                    f"GIL is held{via} — wrap it in "
                    "call_nogil/Py_BEGIN_ALLOW_THREADS",
                ))
        if ensures and not releases and not is_raii:
            findings.append(Finding(
                self.name, ctx.path, fn.start_line,
                f"{fn.name}: PyGILState_Ensure with no matching "
                "PyGILState_Release on any path (RAII ctor/dtor pairs "
                "are exempt)",
            ))
        if releases and not ensures and not is_raii and not entry_held:
            findings.append(Finding(
                self.name, ctx.path, fn.start_line,
                f"{fn.name}: PyGILState_Release with no matching "
                "PyGILState_Ensure",
            ))
        if begins != ends:
            findings.append(Finding(
                self.name, ctx.path, fn.start_line,
                f"{fn.name}: Py_BEGIN_ALLOW_THREADS/"
                f"Py_END_ALLOW_THREADS unbalanced ({begins} vs {ends})",
            ))
        return findings


# ---------------------------------------------------------------------------
# ATOMIC-ORDER (incl. cross-language access-discipline conformance)


_PY_WORD_NAMES = {
    "_HEAD": "head", "_TAIL": "tail", "_CAP": "capacity",
    "_WAITING": "waiting",
}


def _py_access_sequence(cls_node: ast.ClassDef, fn_name: str,
                        _depth: int = 0) -> List[str]:
    """Ordered header/data ops for one transport.py ShmRing method,
    same vocabulary as cxx.access_sequence, with self._method calls
    spliced (depth 2) and locals aliased from self._data tracked."""
    fn = next(
        (n for n in cls_node.body
         if isinstance(n, ast.FunctionDef) and n.name == fn_name),
        None,
    )
    if fn is None:
        return []
    seq: List[str] = []
    data_aliases: Set[str] = set()

    def is_u64(node: ast.AST) -> bool:
        return isinstance(node, ast.Attribute) and node.attr == "_u64"

    def is_data(node: ast.AST) -> bool:
        if isinstance(node, ast.Attribute) and node.attr == "_data":
            return True
        return isinstance(node, ast.Name) and node.id in data_aliases

    def word_of(index: ast.AST) -> str:
        if isinstance(index, ast.Attribute) and (
            index.attr in _PY_WORD_NAMES
        ):
            return _PY_WORD_NAMES[index.attr]
        return "?"

    def emit_expr(node: ast.AST, store: bool = False) -> None:
        if node is None:
            return
        if isinstance(node, ast.Subscript):
            emit_expr(node.value)
            if not is_u64(node.value):
                emit_expr(node.slice)
            if is_u64(node.value):
                seq.append(("W:" if store else "R:") + word_of(node.slice))
                return
            if is_data(node.value):
                seq.append("W:data" if store else "R:data")
                return
            return
        if isinstance(node, ast.Call):
            chain = _attr_text(node.func)
            if chain in ("struct.pack_into", "struct.unpack_from") and (
                len(node.args) >= 2
            ):
                for arg in node.args:
                    emit_expr(arg)
                if is_data(node.args[1]) or (
                    isinstance(node.args[1], ast.Name)
                    and node.args[1].id in data_aliases
                ):
                    seq.append(
                        "W:data" if chain == "struct.pack_into"
                        else "R:data"
                    )
                return
            # self._method(...) splice
            if (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
                and _depth < 2
            ):
                for arg in node.args:
                    emit_expr(arg)
                seq.extend(
                    _py_access_sequence(cls_node, node.func.attr,
                                        _depth + 1)
                )
                return
            for child in ast.iter_child_nodes(node):
                emit_expr(child)
            return
        for child in ast.iter_child_nodes(node):
            emit_expr(child)

    def emit_stmt(stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign):
            emit_expr(stmt.value)
            for target in stmt.targets:
                if isinstance(target, ast.Name) and isinstance(
                    stmt.value, ast.Attribute
                ) and stmt.value.attr == "_data":
                    data_aliases.add(target.id)
                    continue
                if isinstance(target, (ast.Subscript,)):
                    emit_expr(target, store=True)
        elif isinstance(stmt, ast.AugAssign):
            emit_expr(stmt.value)
            if isinstance(stmt.target, ast.Subscript):
                emit_expr(stmt.target)  # read half
                emit_expr(stmt.target, store=True)
        elif isinstance(stmt, (ast.If,)):
            emit_expr(stmt.test)
            for s in stmt.body:
                emit_stmt(s)
            for s in stmt.orelse:
                emit_stmt(s)
        elif isinstance(stmt, (ast.While,)):
            emit_expr(stmt.test)
            for s in stmt.body:
                emit_stmt(s)
            for s in stmt.orelse:
                emit_stmt(s)
        elif isinstance(stmt, (ast.For,)):
            emit_expr(stmt.iter)
            for s in stmt.body:
                emit_stmt(s)
        elif isinstance(stmt, ast.Return):
            emit_expr(stmt.value)
        elif isinstance(stmt, ast.Expr):
            emit_expr(stmt.value)
        elif isinstance(stmt, (ast.Try,)):
            for s in stmt.body:
                emit_stmt(s)
            for h in stmt.handlers:
                for s in h.body:
                    emit_stmt(s)
            for s in stmt.orelse:
                emit_stmt(s)
            for s in stmt.finalbody:
                emit_stmt(s)
        elif isinstance(stmt, ast.Raise):
            emit_expr(stmt.exc)
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.stmt):
                    emit_stmt(child)
                elif isinstance(child, ast.expr):
                    emit_expr(child)

    for stmt in fn.body:
        emit_stmt(stmt)
    return seq


def _attr_text(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _check_sequence(fn_name: str, lang: str, seq: List[str],
                    path: str, line: int) -> List[Finding]:
    """One implementation's collapsed sequence against the spec."""
    findings: List[Finding] = []
    spec = protocol.SPEC_ACCESS.get(fn_name)
    if spec is None:
        return findings
    collapsed = tuple(cxx.collapse(seq))
    if not collapsed:
        findings.append(Finding(
            "ATOMIC-ORDER", path, line,
            f"{fn_name} ({lang}): could not extract any header/data "
            "accesses — the conformance pin against the protocol spec "
            "is broken",
        ))
        return findings
    if collapsed != spec:
        findings.append(Finding(
            "ATOMIC-ORDER", path, line,
            f"{fn_name} ({lang}): header access sequence "
            f"{list(collapsed)} does not conform to the protocol "
            f"spec {list(spec)} (analysis/protocol.py SPEC_ACCESS — "
            "reordering header accesses changes the publish contract "
            "the model checker verified)",
        ))
    final = protocol.SPEC_FINAL_OP.get(fn_name)
    if final is not None and collapsed and collapsed[-1] != final:
        findings.append(Finding(
            "ATOMIC-ORDER", path, line,
            f"{fn_name} ({lang}): the final header op must be {final} "
            f"(publish/release last), got {collapsed[-1]}",
        ))
    return findings


class AtomicOrderRule:
    """ATOMIC-ORDER: shm ring header access discipline, both languages.

    C++ (csrc/shm.h): every kRing*Word use must be
    `word(kX)->load/store(.., std::memory_order_Y)` — the designated
    accessor with an EXPLICIT order; the publish/Dekker sites must use
    exactly the documented order (config.ATOMIC_ORDER_REQUIRED: head
    publish = release, waiting store = seq_cst, consumer head load =
    acquire...). A reinterpret_cast to a non-atomic u64 pointer is a
    raw header deref and flags.

    Python (runtime/transport.py): header words go through the cast
    memoryview with NAMED indices (`self._u64[self._HEAD]`); a bare
    numeric index is an access-discipline finding even though it
    reads/writes the same bytes — the named offset is what WIRE-PARITY
    cross-checks against the C++ word constants.

    Cross-language: both implementations' per-method access sequences
    must conform to analysis/protocol.py SPEC_ACCESS (the spec the
    model checker exhaustively verified), the header-word coverage sets
    must agree, and the bounded recheck must be protocol.RECHECK_MS in
    both (transport.py _WAKE_RECHECK_S, shm.h kWakeRecheckMs).
    """

    name = "ATOMIC-ORDER"

    def check_repo(self, root: str, contexts) -> List[Finding]:
        by_path = {ctx.path: ctx for ctx in contexts}
        shm_ctx = by_path.get(config.SHM_H)
        transport_ctx = by_path.get(config.TRANSPORT_PY)
        if shm_ctx is None and transport_ctx is None:
            return []  # partial scan: the ring is not in scope
        findings: List[Finding] = []

        cpp_words: Set[str] = set()
        if shm_ctx is not None and getattr(shm_ctx, "is_cxx", False):
            findings.extend(self._check_cpp(shm_ctx, cpp_words))
        elif shm_ctx is None and transport_ctx is not None:
            findings.append(Finding(
                self.name, config.TRANSPORT_PY, 1,
                "csrc/shm.h missing from the scan — the C++ side of "
                "the ring access discipline is unchecked",
            ))

        py_words: Set[str] = set()
        if transport_ctx is not None and not getattr(
            transport_ctx, "is_cxx", False
        ):
            findings.extend(self._check_py(transport_ctx, py_words))

        # Cross-language coverage + conformance + recheck pin need both.
        if shm_ctx is None or transport_ctx is None:
            return findings
        if cpp_words and py_words and cpp_words != py_words:
            findings.append(Finding(
                self.name, config.TRANSPORT_PY, 1,
                "header-word coverage differs across languages: "
                f"Python touches {sorted(py_words)}, C++ touches "
                f"{sorted(cpp_words)} — both sides must drive the "
                "same protocol words",
            ))
        findings.extend(self._check_conformance(transport_ctx, shm_ctx))
        findings.extend(self._check_recheck(transport_ctx, shm_ctx))
        return findings

    # -- C++ side -------------------------------------------------------

    def _check_cpp(self, ctx, words_out: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        for acc in cxx.ring_header_accesses(ctx):
            words_out.add(acc.word)
            if acc.op == "raw":
                findings.append(Finding(
                    self.name, ctx.path, acc.line,
                    f"ring header word `{acc.word}` used outside the "
                    "designated `word(k...)->load/store(memory_order)` "
                    "accessor pattern (raw header access in "
                    f"{acc.func})",
                ))
                continue
            if not acc.order:
                findings.append(Finding(
                    self.name, ctx.path, acc.line,
                    f"{acc.func}: {acc.op} of header word "
                    f"`{acc.word}` without an explicit memory order "
                    "(implicit seq_cst hides the documented publish "
                    "contract)",
                ))
                continue
            required = config.ATOMIC_ORDER_REQUIRED.get(
                (acc.func, acc.word, acc.op)
            )
            if required is not None and acc.order != required:
                findings.append(Finding(
                    self.name, ctx.path, acc.line,
                    f"{acc.func}: {acc.op} of `{acc.word}` uses "
                    f"memory_order_{acc.order}, the protocol requires "
                    f"memory_order_{required} here (weakening this is "
                    "a lost wakeup, not a style choice)",
                ))
        for fn_name, line in cxx.raw_u64_casts(ctx):
            if fn_name == "word":
                continue  # the designated accessor's own atomic cast
            findings.append(Finding(
                self.name, ctx.path, line,
                f"{fn_name}: reinterpret_cast to a non-atomic u64 "
                "pointer — ring header words may only be touched "
                "through the std::atomic accessor",
            ))
        return findings

    # -- Python side ----------------------------------------------------

    def _check_py(self, ctx, words_out: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        ring_cls = next(
            (n for n in ast.walk(ctx.tree)
             if isinstance(n, ast.ClassDef) and n.name == "ShmRing"),
            None,
        )
        if ring_cls is None:
            findings.append(Finding(
                self.name, ctx.path, 1,
                "ShmRing class not found in transport.py — the Python "
                "side of the ring access discipline is unparseable",
            ))
            return findings
        for node in ast.walk(ring_cls):
            if not isinstance(node, ast.Subscript):
                continue
            base = node.value
            if not (
                isinstance(base, ast.Attribute) and base.attr == "_u64"
            ):
                continue
            index = node.slice
            if isinstance(index, ast.Attribute) and (
                index.attr in _PY_WORD_NAMES
            ):
                words_out.add(_PY_WORD_NAMES[index.attr])
                continue
            findings.append(Finding(
                self.name, ctx.path, node.lineno,
                "header word accessed with a raw index — name the "
                "offset (`self._u64[self._HEAD]`): the named constant "
                "is what WIRE-PARITY cross-checks against csrc/shm.h",
            ))
        return findings

    # -- cross-language -------------------------------------------------

    def _check_conformance(self, transport_ctx, shm_ctx) -> List[Finding]:
        findings: List[Finding] = []
        ring_cls = next(
            (n for n in ast.walk(transport_ctx.tree)
             if isinstance(n, ast.ClassDef) and n.name == "ShmRing"),
            None,
        )
        for fn_name in protocol.SPEC_ACCESS:
            if ring_cls is not None:
                py_seq = _py_access_sequence(ring_cls, fn_name)
                py_line = next(
                    (n.lineno for n in ring_cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == fn_name),
                    1,
                )
                findings.extend(_check_sequence(
                    fn_name, "transport.py", py_seq,
                    transport_ctx.path, py_line,
                ))
            cpp_seq = cxx.access_sequence(shm_ctx, "ShmRing", fn_name)
            cpp_fn = shm_ctx.function_named(fn_name, "ShmRing")
            findings.extend(_check_sequence(
                fn_name, "csrc/shm.h", cpp_seq, shm_ctx.path,
                cpp_fn.start_line if cpp_fn is not None else 1,
            ))
        return findings

    # The adaptive-recheck policy constants (ISSUE 12), pinned in both
    # implementations against the spec values in analysis/protocol.py:
    # (python module constant, C++ constexpr, protocol attribute).
    _ADAPTIVE_PINS = (
        ("_RECHECK_MIN_MS", "kRecheckMinMs", "RECHECK_MIN_MS"),
        ("_RECHECK_MAX_MS", "kRecheckMaxMs", "RECHECK_MAX_MS"),
        ("_RECHECK_WINDOW", "kRecheckWindow", "RECHECK_WINDOW"),
        ("_RECHECK_TIGHTEN", "kRecheckTighten", "RECHECK_TIGHTEN"),
        ("_RECHECK_RELAX", "kRecheckRelax", "RECHECK_RELAX"),
    )

    def _check_recheck(self, transport_ctx, shm_ctx) -> List[Finding]:
        findings: List[Finding] = []
        py_consts: Dict[str, float] = {}
        for node in ast.walk(transport_ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if isinstance(target, ast.Name) and isinstance(
                    node.value, ast.Constant
                ) and isinstance(node.value.value, (int, float)):
                    py_consts[target.id] = float(node.value.value)
        cpp_consts: Dict[str, float] = {
            m.group(1): float(m.group(2))
            for m in re.finditer(
                r"constexpr\s+int\s+(k\w+)\s*=\s*(\d+)", shm_ctx.source
            )
        }
        py_ms = py_consts.get("_WAKE_RECHECK_S")
        if py_ms is not None:
            py_ms *= 1000.0
        pins = (
            ("_WAKE_RECHECK_S", "kWakeRecheckMs", "RECHECK_MS"),
        ) + self._ADAPTIVE_PINS
        for py_name, cpp_name, spec_attr in pins:
            spec_value = getattr(protocol, spec_attr)
            py_value = (
                py_ms if py_name == "_WAKE_RECHECK_S"
                else py_consts.get(py_name)
            )
            for label, value, path in (
                (py_name, py_value, transport_ctx.path),
                (cpp_name, cpp_consts.get(cpp_name), shm_ctx.path),
            ):
                if value is None:
                    findings.append(Finding(
                        self.name, path, 1,
                        f"could not parse {label} — the recheck-policy "
                        "pin against the protocol spec is broken",
                    ))
                elif abs(value - spec_value) > 1e-9:
                    findings.append(Finding(
                        self.name, path, 1,
                        f"{label} is {value:g}, the verified protocol "
                        f"spec says {spec_value:g} "
                        f"(analysis/protocol.py {spec_attr}) — change "
                        "both together or re-verify",
                    ))
        # The adaptive walk must stay inside what the no-wedge proof
        # covers (the timeout transition needs a finite positive bound).
        if not protocol.adaptive_recheck_covered():
            findings.append(Finding(
                self.name, shm_ctx.path, 1,
                "adaptive recheck range is not covered by the model "
                "checker's timeout transition (protocol."
                "adaptive_recheck_covered): the bound must stay finite "
                "and positive",
            ))
        return findings


# ---------------------------------------------------------------------------
# CXX-LOCK-DISCIPLINE (guarded-by + cross-root conflicts)


class CxxLockDisciplineRule:
    """CXX-LOCK-DISCIPLINE: the Python LOCK-DISCIPLINE/RACE contracts,
    applied to the C++ core via the frontend.

    Guarded members: `type member_;  // guarded-by: mu_` may only be
    touched inside a lexical scope holding an RAII guard
    (`std::lock_guard`/`unique_lock`/`scoped_lock`) on `mu_`.
    Constructors, the destructor, and move/copy assignment are exempt
    (no concurrent observers); `// beastlint: holds mu_` above a method
    documents callers hold the lock. An early `l.unlock()` ends the
    held region (csrc/queues.h dequeue_item's shape).

    Cross-root conflicts: thread roots are std::thread /
    emplace_back(lambda) spawn sites (multi-instance when spawned in a
    loop) PLUS one root per Python-facing entry method (a method of a
    csrc class invoked from pymodule.cc runs on whatever Python thread
    calls it — the cross-language half of PR 7's thread-root graph).
    Within classes that own a mutex or a spawned method, a non-atomic
    non-const member with no guarded-by annotation that is WRITTEN from
    one root and touched from another (or written twice from a
    multi-instance root) with no common lock is a finding. Benign
    orderings (atomic-handoff publication, write-before-spawn) are
    suppressed inline with the interleaving described, same as RACE.
    """

    name = "CXX-LOCK-DISCIPLINE"

    def check_repo(self, root: str, contexts) -> List[Finding]:
        ctxs = _cxx_contexts(contexts)
        if not ctxs:
            return []
        findings: List[Finding] = []
        for ctx in ctxs:
            findings.extend(self._check_guarded(ctx))
        findings.extend(self._check_conflicts(ctxs))
        return findings

    # -- guarded-by assertions ------------------------------------------

    def _check_guarded(self, ctx) -> List[Finding]:
        findings: List[Finding] = []
        for cls in ctx.classes.values():
            if not cls.guarded:
                continue
            for fn in cls.methods.values():
                for acc in cxx.member_accesses(ctx, cls, fn):
                    lock = cls.guarded.get(acc.attr)
                    if lock is None or acc.in_init:
                        continue
                    if f"{cls.name}.{lock}" not in acc.held:
                        findings.append(Finding(
                            self.name, ctx.path, acc.line,
                            f"`{acc.attr}` ({cls.name}) is guarded-by "
                            f"`{lock}` but accessed in "
                            f"{fn.name} without holding it",
                        ))
        return findings

    # -- cross-root conflicts -------------------------------------------

    def _check_conflicts(self, ctxs) -> List[Finding]:
        # Name-based call graph over ALL csrc contexts.
        edges: Dict[str, Set[str]] = {}
        fn_by_name: Dict[str, List[Tuple[object, object]]] = {}
        for ctx in ctxs:
            for fn in ctx.functions:
                fn_by_name.setdefault(fn.name, []).append((ctx, fn))
            for qual, callees in cxx.call_edges(ctx).items():
                edges.setdefault(qual, set()).update(callees)

        def reachable(seed_names: Set[str]) -> Set[str]:
            """Function QUALS reachable from callee names."""
            out: Set[str] = set()
            stack = list(seed_names)
            while stack:
                name = stack.pop()
                for ctx, fn in fn_by_name.get(name, []):
                    if fn.qual in out:
                        continue
                    out.add(fn.qual)
                    stack.extend(edges.get(fn.qual, ()))
            return out

        # Roots: spawn sites + Python-facing entry methods.
        roots: Dict[str, Tuple[Set[str], bool]] = {}  # id -> (quals, multi)
        for ctx in ctxs:
            for site in cxx.thread_spawns(ctx):
                callees = {
                    name for name in site.callees if name in fn_by_name
                }
                if not callees:
                    continue
                rid = f"cxx-thread:{site.func}:{site.line}"
                roots[rid] = (reachable(callees), site.multi)
            if ctx.path.endswith("pymodule.cc"):
                # `obj->method(` / `obj.method(` sites in the binding
                # layer: each bound method is its own Python-side root
                # (different Python threads drive different entries).
                for name in self._bound_methods(ctx, fn_by_name):
                    roots[f"py-entry:{name}"] = (reachable({name}), False)

        # Conflict scan per shared-owner class.
        findings: List[Finding] = []
        for ctx in ctxs:
            spawned_methods = {
                callee for site in cxx.thread_spawns(ctx)
                for callee in site.callees
            }
            for cls in ctx.classes.values():
                in_scope = bool(cls.lock_attrs) or bool(
                    spawned_methods & set(cls.methods)
                )
                if not in_scope:
                    continue
                accesses: List[cxx.CxxAccess] = []
                for fn in cls.methods.values():
                    accesses.extend(cxx.member_accesses(ctx, cls, fn))
                findings.extend(
                    self._conflicts_for_class(ctx, cls, accesses, roots)
                )
        return findings

    @staticmethod
    def _bound_methods(ctx, fn_by_name) -> Set[str]:
        out: Set[str] = set()
        for fn in ctx.functions:
            toks = fn.tokens
            n = len(toks)
            for i, t in enumerate(toks):
                if t.kind == "punct" and t.text in ("->", ".") and (
                    i + 2 < n
                    and toks[i + 1].kind == "id"
                    and toks[i + 2].text == "("
                    and toks[i + 1].text in fn_by_name
                ):
                    out.add(toks[i + 1].text)
        return out

    def _conflicts_for_class(self, ctx, cls, accesses, roots
                             ) -> List[Finding]:
        findings: List[Finding] = []
        by_attr: Dict[str, List] = {}
        for acc in accesses:
            member = cls.members.get(acc.attr)
            if member is None or member.is_atomic or member.is_const:
                continue
            if acc.attr in cls.guarded:
                continue  # the guarded-by assertion covers these
            if acc.in_init:
                continue
            by_attr.setdefault(acc.attr, []).append(acc)
        for attr, accs in sorted(by_attr.items()):
            writes = [a for a in accs if a.kind == "write"]
            if not writes:
                continue  # immutable after construction
            # Map accesses to roots.
            per_root: Dict[str, List] = {}
            multi_roots: Set[str] = set()
            for acc in accs:
                qual = acc.func.replace("cxx::", "")
                for rid, (quals, multi) in roots.items():
                    if qual in quals:
                        per_root.setdefault(rid, []).append(acc)
                        if multi:
                            multi_roots.add(rid)
            conflict: List = []
            root_ids: Set[str] = set()
            for ra, a_accs in per_root.items():
                a_writes = [a for a in a_accs if a.kind == "write"]
                for rb, b_accs in per_root.items():
                    if rb == ra or not a_writes:
                        continue
                    conflict.extend(a_writes + b_accs)
                    root_ids |= {ra, rb}
                if ra in multi_roots and a_writes and (
                    len(a_accs) > len(a_writes) or len(a_writes) > 1
                    or any(a.rmw for a in a_writes)
                ):
                    conflict.extend(a_accs)
                    root_ids.add(ra)
            if not conflict:
                continue
            common = frozenset.intersection(
                *[a.held for a in conflict]
            )
            if common:
                continue
            anchor = min(
                (a for a in conflict if a.kind == "write"),
                key=lambda a: (a.path, a.line),
            )
            other = next(
                (a for a in sorted(conflict,
                                   key=lambda x: (x.path, x.line))
                 if (a.path, a.line) != (anchor.path, anchor.line)),
                anchor,
            )
            roots_text = ", ".join(sorted(root_ids)[:3])
            findings.append(Finding(
                self.name, anchor.path, anchor.line,
                f"`{attr}` ({cls.name}) is written from roots "
                f"{roots_text} with no common lock and no guarded-by "
                f"annotation (counterpart at {other.path}:"
                f"{other.line}) — guard it, make it atomic, or "
                "suppress with the safe interleaving described",
            ))
        return findings


CXX_RULES = [
    GilDisciplineRule(),
    AtomicOrderRule(),
    CxxLockDisciplineRule(),
]
