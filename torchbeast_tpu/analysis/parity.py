"""beastlint repo-level rules: cross-language wire parity and cross-driver
flag parity.

Both rules are TEXTUAL: the C++ headers are parsed with regexes scoped to
the specific declaration shapes this repo uses (constexpr tag constants,
the DType enum, the itemsize switch), and the Python side is parsed from
the AST without importing it. That keeps the analyzer runnable in an image
with no compiler and no jax/numpy — and means a parity break fails lint in
the same run that would have shipped it, instead of waiting for the
cross-language fuzz tests to execute both stacks.
"""

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from . import config
from .engine import FileContext, Finding

# ---------------------------------------------------------------------------
# C++ parsing helpers


def _fold_cpp_int(expr: str) -> Optional[int]:
    """Evaluate `256ull * 1024 * 1024`-style constant expressions."""
    cleaned = re.sub(r"(?i)(?<=\d)(ull|ll|ul|u|l)\b", "", expr)
    cleaned = cleaned.replace("'", "")  # C++14 digit separators
    if not re.fullmatch(r"[0-9xXa-fA-F\s*+\-()<>]+", cleaned):
        return None
    try:
        return int(eval(cleaned, {"__builtins__": {}}, {}))  # noqa: S307
    except (SyntaxError, NameError, ValueError, TypeError,
            ArithmeticError):
        # Unparseable constant expression -> None; callers treat an
        # unresolved anchor as its own parity finding, so nothing is
        # silently swallowed here.
        return None


def _norm_tag(name: str) -> str:
    """Case/underscore-insensitive tag identity: TAG_NP_SCALAR (py) and
    kTagNpScalar (C++) both normalize to NPSCALAR."""
    return name.upper().replace("_", "")


def parse_cpp_tags(wire_h: str) -> Dict[str, int]:
    """kTagArray = 0x01 -> {'ARRAY': 1}."""
    out: Dict[str, int] = {}
    for m in re.finditer(
        r"constexpr\s+uint8_t\s+kTag(\w+)\s*=\s*(0[xX][0-9a-fA-F]+|\d+)\s*;",
        wire_h,
    ):
        out[_norm_tag(m.group(1))] = int(m.group(2), 0)
    return out


def parse_cpp_max_frame(src: str) -> Optional[int]:
    m = re.search(
        r"constexpr\s+size_t\s+kMaxFrameBytes\s*=\s*([^;]+);", src
    )
    return _fold_cpp_int(m.group(1)) if m else None


def parse_cpp_dtype_enum(array_h: str) -> Dict[str, int]:
    """enum class DType entries -> {'kU8': 0, ...}."""
    m = re.search(
        r"enum\s+class\s+DType\s*:\s*uint8_t\s*\{(.*?)\};", array_h,
        re.DOTALL,
    )
    if not m:
        return {}
    out: Dict[str, int] = {}
    for entry in re.finditer(r"(k\w+)\s*=\s*(\d+)", m.group(1)):
        out[entry.group(1)] = int(entry.group(2))
    return out


def parse_cpp_ring(shm_h: str) -> Dict[str, Optional[int]]:
    """csrc/shm.h ring-layout constants -> canonical names. Missing
    pieces parse to None (the checker turns that into a finding)."""
    out: Dict[str, Optional[int]] = {}

    def const(cpp_name: str):
        m = re.search(
            r"constexpr\s+(?:size_t|uint32_t|uint8_t)\s+" + cpp_name +
            r"\s*=\s*(0[xX][0-9a-fA-F]+|\d+)",
            shm_h,
        )
        return int(m.group(1), 0) if m else None

    out["header_bytes"] = const("kRingHeaderBytes")
    out["head_word"] = const("kRingHeadWord")
    out["tail_word"] = const("kRingTailWord")
    out["capacity_word"] = const("kRingCapacityWord")
    out["waiting_word"] = const("kRingWaitingWord")
    out["wrap_marker"] = const("kRingWrapMarker")
    out["inline_marker"] = const("kRingInlineMarker")
    out["doorbell_wake"] = const("kDoorbellWake")
    out["doorbell_inline"] = const("kDoorbellInline")
    # Ring-eligibility cap: `max_frame_bytes() ... return capacity_ / D - S`.
    m = re.search(
        r"max_frame_bytes\s*\(\s*\)\s*const\s*\{\s*return\s+capacity_\s*/"
        r"\s*(\d+)\s*-\s*(\d+)\s*;",
        shm_h,
    )
    out["eligibility_divisor"] = int(m.group(1)) if m else None
    out["eligibility_slack"] = int(m.group(2)) if m else None
    return out


def parse_py_ring(tree: ast.Module) -> Dict[str, Optional[int]]:
    """runtime/transport.py ring-layout facts -> the same canonical
    names as parse_cpp_ring (ShmRing class attributes, the module-level
    doorbell bytes, and max_frame_bytes' capacity//D - S expression)."""
    out: Dict[str, Optional[int]] = {key: None for key in (
        "header_bytes", "head_word", "tail_word", "capacity_word",
        "waiting_word", "wrap_marker", "inline_marker", "doorbell_wake",
        "doorbell_inline", "eligibility_divisor", "eligibility_slack",
    )}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if isinstance(target, ast.Name) and isinstance(
                node.value, ast.Constant
            ) and isinstance(node.value.value, bytes) and len(
                node.value.value
            ) == 1:
                if target.id == "_DOORBELL_WAKE":
                    out["doorbell_wake"] = node.value.value[0]
                elif target.id == "_DOORBELL_INLINE":
                    out["doorbell_inline"] = node.value.value[0]
    ring_cls = next(
        (n for n in ast.walk(tree)
         if isinstance(n, ast.ClassDef) and n.name == "ShmRing"),
        None,
    )
    if ring_cls is None:
        return out
    for node in ring_cls.body:
        if isinstance(node, ast.Assign):
            targets = node.targets[0]
            if isinstance(targets, ast.Name):
                value = _fold_py_int(node.value)
                name = {
                    "HEADER_BYTES": "header_bytes",
                    "_WRAP": "wrap_marker",
                    "_INLINE": "inline_marker",
                }.get(targets.id)
                if name is not None and value is not None:
                    out[name] = value
            elif isinstance(targets, ast.Tuple) and isinstance(
                node.value, ast.Tuple
            ):
                # `_HEAD, _TAIL, _CAP, _WAITING = 0, 1, 2, 3`
                names = {
                    "_HEAD": "head_word", "_TAIL": "tail_word",
                    "_CAP": "capacity_word", "_WAITING": "waiting_word",
                }
                for elt, val in zip(targets.elts, node.value.elts):
                    if isinstance(elt, ast.Name) and elt.id in names:
                        folded = _fold_py_int(val)
                        if folded is not None:
                            out[names[elt.id]] = folded
        elif isinstance(node, ast.FunctionDef) and (
            node.name == "max_frame_bytes"
        ):
            # `return self._capacity // D - S`
            for ret in ast.walk(node):
                if not isinstance(ret, ast.Return):
                    continue
                expr = ret.value
                if (
                    isinstance(expr, ast.BinOp)
                    and isinstance(expr.op, ast.Sub)
                    and isinstance(expr.left, ast.BinOp)
                    and isinstance(expr.left.op, ast.FloorDiv)
                ):
                    out["eligibility_divisor"] = _fold_py_int(
                        expr.left.right
                    )
                    out["eligibility_slack"] = _fold_py_int(expr.right)
    return out


def parse_cpp_itemsizes(array_h: str) -> Dict[str, int]:
    """The itemsize() switch -> {'kU8': 1, ...}."""
    m = re.search(
        r"inline\s+size_t\s+itemsize\s*\(.*?\)\s*\{(.*?)\n\}", array_h,
        re.DOTALL,
    )
    if not m:
        return {}
    out: Dict[str, int] = {}
    pending: List[str] = []
    for line in m.group(1).splitlines():
        case = re.search(r"case\s+DType::(k\w+)\s*:", line)
        if case:
            pending.append(case.group(1))
        ret = re.search(r"return\s+(\d+)\s*;", line)
        if ret and pending:
            for name in pending:
                out[name] = int(ret.group(1))
            pending = []
    return out


# ---------------------------------------------------------------------------
# Python (AST) parsing helpers


def _fold_py_int(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.BinOp):
        left = _fold_py_int(node.left)
        right = _fold_py_int(node.right)
        if left is None or right is None:
            return None
        if isinstance(node.op, ast.Mult):
            return left * right
        if isinstance(node.op, ast.Add):
            return left + right
        if isinstance(node.op, ast.Sub):
            return left - right
        if isinstance(node.op, ast.Pow):
            return left ** right
        if isinstance(node.op, ast.LShift):
            return left << right
    return None


def _np_dtype_name(call: ast.AST) -> Optional[str]:
    """np.dtype(np.uint8) / np.dtype(_bfloat16) -> numpy dtype name."""
    if not (
        isinstance(call, ast.Call)
        and isinstance(call.func, ast.Attribute)
        and call.func.attr == "dtype"
        and call.args
    ):
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Attribute):
        name = arg.attr
    elif isinstance(arg, ast.Name):
        name = arg.id
    else:
        return None
    name = name.lstrip("_")
    return {"bool_": "bool"}.get(name, name)


def parse_py_wire(tree: ast.Module) -> Tuple[
    Dict[str, int], Optional[int], Dict[str, int]
]:
    """(TAG_* map, DEFAULT_MAX_FRAME_BYTES, dtype-name -> code)."""
    tags: Dict[str, int] = {}
    max_frame: Optional[int] = None
    codes: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if isinstance(target, ast.Name):
            if target.id.startswith("TAG_"):
                value = _fold_py_int(node.value)
                if value is not None:
                    tags[_norm_tag(target.id[4:])] = value
            elif target.id == "DEFAULT_MAX_FRAME_BYTES":
                max_frame = _fold_py_int(node.value)
            elif target.id == "_DTYPE_CODES" and isinstance(
                node.value, ast.Dict
            ):
                for k, v in zip(node.value.keys, node.value.values):
                    name = _np_dtype_name(k)
                    code = _fold_py_int(v)
                    if name is not None and code is not None:
                        codes[name] = code
        elif isinstance(target, ast.Subscript):
            # _DTYPE_CODES[np.dtype(_bfloat16)] = 12 (the guarded
            # ml_dtypes registration).
            base = target.value
            if isinstance(base, ast.Name) and base.id == "_DTYPE_CODES":
                key = target.slice
                name = _np_dtype_name(key)
                code = _fold_py_int(node.value)
                if name is not None and code is not None:
                    codes[name] = code
    return tags, max_frame, codes


def _find_add_argument_default(
    tree: ast.Module, flag: str
) -> Tuple[Optional[ast.AST], Optional[int]]:
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == flag
        ):
            for kw in node.keywords:
                if kw.arg == "default":
                    return kw.value, node.lineno
            return None, node.lineno
    return None, None


# ---------------------------------------------------------------------------
# WIRE-PARITY


def check_wire_parity(
    py_ctx: FileContext,
    wire_h: str,
    array_h: str,
    client_h: str,
    poly_ctx: Optional[FileContext],
) -> List[Finding]:
    findings: List[Finding] = []
    path = py_ctx.path

    def finding(line: int, msg: str, at: str = ""):
        findings.append(Finding("WIRE-PARITY", at or path, line, msg))

    tags_py, max_frame_py, codes_py = parse_py_wire(py_ctx.tree)
    tags_cpp = parse_cpp_tags(wire_h)
    max_frame_cpp = parse_cpp_max_frame(wire_h)
    enum_cpp = parse_cpp_dtype_enum(array_h)
    sizes_cpp = parse_cpp_itemsizes(array_h)

    # Parse failures are findings, not silence: an unparseable header
    # means the contract is no longer being checked.
    if not tags_py or not codes_py or max_frame_py is None:
        finding(1, "could not parse TAG_*/_DTYPE_CODES/"
                   "DEFAULT_MAX_FRAME_BYTES from runtime/wire.py — "
                   "WIRE-PARITY cannot verify the codec")
        return findings
    if not tags_cpp or not enum_cpp or not sizes_cpp:
        finding(1, "could not parse kTag*/DType/itemsize from csrc "
                   "headers — WIRE-PARITY cannot verify the codec")
        return findings

    # 1. Frame tag constants.
    for name in sorted(tags_py.keys() | tags_cpp.keys()):
        py_v, cpp_v = tags_py.get(name), tags_cpp.get(name)
        if py_v is None:
            finding(1, f"csrc/wire.h defines kTag{name.title()}={cpp_v} "
                       "but wire.py has no matching TAG_ constant")
        elif cpp_v is None:
            finding(1, f"wire.py defines TAG_{name}={py_v} but "
                       "csrc/wire.h has no matching kTag constant")
        elif py_v != cpp_v:
            finding(1, f"frame tag {name}: wire.py says {py_v:#x}, "
                       f"csrc/wire.h says {cpp_v:#x}")

    # 2. Dtype code table (both directions) + itemsize ground truth.
    codes_cpp: Dict[str, int] = {}
    for cpp_name, code in enum_cpp.items():
        np_name = config.CPP_DTYPE_TO_NUMPY.get(cpp_name)
        if np_name is None:
            finding(1, f"csrc/array.h DType::{cpp_name} has no numpy "
                       "mapping in analysis/config.py "
                       "CPP_DTYPE_TO_NUMPY — add one")
            continue
        codes_cpp[np_name] = code
    for name in sorted(codes_py.keys() | codes_cpp.keys()):
        py_c, cpp_c = codes_py.get(name), codes_cpp.get(name)
        if py_c is None:
            finding(1, f"dtype {name!r} (code {cpp_c}) exists in "
                       "csrc/array.h but not in wire.py _DTYPE_CODES")
        elif cpp_c is None:
            finding(1, f"dtype {name!r} (code {py_c}) exists in wire.py "
                       "_DTYPE_CODES but not in csrc/array.h DType")
        elif py_c != cpp_c:
            finding(1, f"dtype {name!r}: wire.py code {py_c} != "
                       f"csrc/array.h code {cpp_c}")
        expected = config.DTYPE_ITEMSIZE.get(name)
        if expected is None and (py_c is not None or cpp_c is not None):
            finding(1, f"dtype {name!r} missing from "
                       "analysis/config.py DTYPE_ITEMSIZE ground truth")
    for cpp_name, size in sizes_cpp.items():
        np_name = config.CPP_DTYPE_TO_NUMPY.get(cpp_name)
        expected = config.DTYPE_ITEMSIZE.get(np_name or "")
        if expected is not None and size != expected:
            finding(1, f"csrc/array.h itemsize({cpp_name}) = {size}, "
                       f"expected {expected} for {np_name}")
    for cpp_name in enum_cpp:
        if cpp_name not in sizes_cpp:
            finding(1, f"csrc/array.h itemsize() has no case for "
                       f"DType::{cpp_name} — decoding that code throws")

    # 3. Max frame bytes: wire.py default == csrc constant, and the C++
    # frame reader actually enforces it.
    if max_frame_cpp is None:
        finding(1, "could not parse kMaxFrameBytes from csrc/wire.h")
    elif max_frame_cpp != max_frame_py:
        finding(1, f"DEFAULT_MAX_FRAME_BYTES={max_frame_py} (wire.py) != "
                   f"kMaxFrameBytes={max_frame_cpp} (csrc/wire.h)")
    if client_h and "kMaxFrameBytes" not in client_h:
        finding(1, "csrc/client.h never references kMaxFrameBytes — the "
                   "C++ frame reader is not enforcing the frame bound")

    # 4. The driver flag default must resolve to the same constant.
    if poly_ctx is not None:
        default, line = _find_add_argument_default(
            poly_ctx.tree, "--max_frame_bytes"
        )
        if line is None:
            finding(1, "polybeast.py no longer defines --max_frame_bytes",
                    at=poly_ctx.path)
        elif isinstance(default, ast.Constant):
            if default.value != max_frame_py:
                finding(line, f"--max_frame_bytes default {default.value} "
                              f"!= wire.DEFAULT_MAX_FRAME_BYTES "
                              f"{max_frame_py}", at=poly_ctx.path)
        elif default is None or (
            not isinstance(default, ast.Attribute)
            or default.attr != "DEFAULT_MAX_FRAME_BYTES"
        ):
            finding(line or 1, "--max_frame_bytes default should be "
                               "wire.DEFAULT_MAX_FRAME_BYTES (or its "
                               "literal value) so py/C++ stay in lockstep",
                    at=poly_ctx.path)
    return findings


# Human-readable labels for the ring-layout contract fields.
_RING_FIELD_LABELS = {
    "header_bytes": "ring header size (ShmRing.HEADER_BYTES / "
                    "kRingHeaderBytes)",
    "head_word": "head counter word index (_HEAD / kRingHeadWord)",
    "tail_word": "tail counter word index (_TAIL / kRingTailWord)",
    "capacity_word": "capacity word index (_CAP / kRingCapacityWord)",
    "waiting_word": "waiting-flag word index (_WAITING / kRingWaitingWord)",
    "wrap_marker": "wrap marker (_WRAP / kRingWrapMarker)",
    "inline_marker": "inline marker (_INLINE / kRingInlineMarker)",
    "doorbell_wake": "doorbell WAKE byte (_DOORBELL_WAKE / kDoorbellWake)",
    "doorbell_inline": "doorbell INLINE byte (_DOORBELL_INLINE / "
                       "kDoorbellInline)",
    "eligibility_divisor": "ring-eligibility cap divisor "
                           "(max_frame_bytes: capacity // D - S)",
    "eligibility_slack": "ring-eligibility cap slack "
                         "(max_frame_bytes: capacity // D - S)",
}


def check_ring_parity(
    transport_ctx: FileContext, shm_h: str
) -> List[Finding]:
    """WIRE-PARITY (shm ring layout): a Python env server and a C++
    actor loop attach the SAME SharedMemory segments, so the header
    word layout, in-ring wrap/inline markers, doorbell control bytes,
    and the capacity//2-4 ring-eligibility cap must match byte for
    byte. Unparseable side = finding, not silence."""
    findings: List[Finding] = []
    path = transport_ctx.path

    def finding(msg: str):
        findings.append(Finding("WIRE-PARITY", path, 1, msg))

    ring_py = parse_py_ring(transport_ctx.tree)
    ring_cpp = parse_cpp_ring(shm_h)
    if all(v is None for v in ring_py.values()):
        finding("could not parse the ShmRing layout (HEADER_BYTES/"
                "_WRAP/_INLINE/word indices/doorbell bytes) from "
                "runtime/transport.py — WIRE-PARITY cannot verify the "
                "shm ring contract")
        return findings
    if all(v is None for v in ring_cpp.values()):
        finding("could not parse the ring layout (kRing*/kDoorbell* "
                "constants, max_frame_bytes) from csrc/shm.h — "
                "WIRE-PARITY cannot verify the shm ring contract")
        return findings
    for key, label in _RING_FIELD_LABELS.items():
        py_v, cpp_v = ring_py.get(key), ring_cpp.get(key)
        if py_v is None:
            finding(f"shm ring {label}: missing/unparseable on the "
                    f"Python side (csrc/shm.h says {cpp_v})")
        elif cpp_v is None:
            finding(f"shm ring {label}: missing/unparseable on the C++ "
                    f"side (transport.py says {py_v})")
        elif py_v != cpp_v:
            finding(f"shm ring {label}: transport.py says {py_v:#x}, "
                    f"csrc/shm.h says {cpp_v:#x}")
    return findings


class WireParityRule:
    """WIRE-PARITY: runtime/wire.py == csrc/ on tags, dtypes, frame
    bound — and runtime/transport.py == csrc/shm.h on the shm ring
    layout."""

    name = "WIRE-PARITY"

    def check_repo(
        self, root: str, contexts: Sequence[FileContext]
    ) -> List[Finding]:
        by_path = {ctx.path: ctx for ctx in contexts}
        py_ctx = by_path.get(config.WIRE_PY)
        if py_ctx is None:
            return []  # partial scan (explicit paths): parity not in scope

        def read(rel: str) -> str:
            p = os.path.join(root, rel)
            try:
                with open(p, encoding="utf-8", errors="replace") as f:
                    return f.read()
            except OSError:
                return ""

        wire_h = read(config.WIRE_H)
        array_h = read(config.ARRAY_H)
        client_h = read(config.CLIENT_H)
        if not wire_h or not array_h:
            return [
                Finding(
                    self.name, config.WIRE_PY, 1,
                    "csrc/wire.h or csrc/array.h missing — the C++ side "
                    "of the wire contract is gone",
                )
            ]
        findings = check_wire_parity(
            py_ctx, wire_h, array_h, client_h,
            by_path.get(config.POLYBEAST_PY),
        )
        # The shm ring layout contract (ISSUE 9 satellite): checked
        # whenever transport.py is in scope.
        transport_ctx = by_path.get(config.TRANSPORT_PY)
        if transport_ctx is not None:
            shm_h = read(config.SHM_H)
            if not shm_h:
                findings.append(Finding(
                    self.name, config.TRANSPORT_PY, 1,
                    "csrc/shm.h missing — the C++ side of the shm ring "
                    "contract is gone",
                ))
            else:
                findings.extend(check_ring_parity(transport_ctx, shm_h))
        return findings


# ---------------------------------------------------------------------------
# ROUTE-PARITY


# Human-readable labels for the splitmix64 contract fields.
_SPLITMIX_FIELD_LABELS = {
    "gamma": "splitmix64 gamma increment (kSplitMix64Gamma)",
    "mul1": "splitmix64 first multiplier (kSplitMix64Mul1)",
    "mul2": "splitmix64 second multiplier (kSplitMix64Mul2)",
    "shift1": "splitmix64 first xor-shift (kSplitMix64Shift1)",
    "shift2": "splitmix64 second xor-shift (kSplitMix64Shift2)",
    "shift3": "splitmix64 final xor-shift (kSplitMix64Shift3)",
}


def parse_py_splitmix(tree: ast.Module) -> Dict[str, Optional[int]]:
    """runtime/placement.py `_mix64` -> canonical splitmix64 fields.

    Constants are classified by operator context, not position: the Add
    operand is the gamma increment, RShift operands are the xor-shifts
    in statement order, Mult operands the multipliers. The
    `& 0xFFFFFFFFFFFFFFFF` masks are Python-only wrap emulation (C++
    uint64_t wraps natively) and are ignored (BitAnd)."""
    out: Dict[str, Optional[int]] = {
        key: None for key in _SPLITMIX_FIELD_LABELS
    }
    fn = next(
        (n for n in ast.walk(tree)
         if isinstance(n, ast.FunctionDef) and n.name == "_mix64"),
        None,
    )
    if fn is None:
        return out
    shifts: List[int] = []
    muls: List[int] = []
    for stmt in fn.body:  # statement order == finalizer stage order
        for node in ast.walk(stmt):
            if not isinstance(node, ast.BinOp):
                continue
            value = _fold_py_int(node.right)
            if value is None:
                continue
            if isinstance(node.op, ast.Add) and out["gamma"] is None:
                out["gamma"] = value
            elif isinstance(node.op, ast.RShift):
                shifts.append(value)
            elif isinstance(node.op, ast.Mult):
                muls.append(value)
    for i, value in enumerate(shifts[:3]):
        out[f"shift{i + 1}"] = value
    for i, value in enumerate(muls[:2]):
        out[f"mul{i + 1}"] = value
    return out


def parse_cpp_routing(
    routing_h: str,
) -> Tuple[Dict[str, Optional[int]], Optional[str]]:
    """csrc/routing.h -> (splitmix64 fields, slice-series prefix)."""
    names = {
        "Gamma": "gamma", "Mul1": "mul1", "Mul2": "mul2",
        "Shift1": "shift1", "Shift2": "shift2", "Shift3": "shift3",
    }
    out: Dict[str, Optional[int]] = {
        key: None for key in _SPLITMIX_FIELD_LABELS
    }
    for m in re.finditer(
        r"constexpr\s+(?:uint64_t|int)\s+kSplitMix64(\w+)\s*=\s*"
        r"(0[xX][0-9a-fA-F]+|\d+)(?:[uU]?[lL]{0,2})\s*;",
        routing_h,
    ):
        key = names.get(m.group(1))
        if key is not None:
            out[key] = int(m.group(2), 0)
    prefix_m = re.search(
        r"constexpr\s+const\s+char\s+kSliceSeriesPrefix\[\]\s*=\s*"
        r'"([^"]*)"',
        routing_h,
    )
    return out, (prefix_m.group(1) if prefix_m else None)


def _py_string_prefixes(tree: ast.Module) -> List[str]:
    """Every literal string prefix in the module: plain str constants
    verbatim, f-strings contribute their leading constant fragment
    (`f"inference.slice.{i}.depth"` -> "inference.slice.")."""
    out: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.append(node.value)
        elif isinstance(node, ast.JoinedStr) and node.values:
            head = node.values[0]
            if isinstance(head, ast.Constant) and isinstance(
                head.value, str
            ):
                out.append(head.value)
    return out


def check_route_parity(
    placement_ctx: FileContext,
    routing_h: str,
    series_ctxs: Sequence[FileContext],
) -> List[Finding]:
    """ROUTE-PARITY: the slot->slice hash and the per-slice telemetry
    namespace agree across languages. Both sides check against the
    SPLITMIX64_SPEC ground truth (a wrong constant on either side is a
    finding even if the other side drifted in lockstep); the series
    prefix pins csrc/routing.h kSliceSeriesPrefix AND every Python
    emitter to config.SLICE_SERIES_PREFIX. Unparseable side = finding,
    not silence."""
    findings: List[Finding] = []

    def finding(path: str, msg: str):
        findings.append(Finding("ROUTE-PARITY", path, 1, msg))

    mix_py = parse_py_splitmix(placement_ctx.tree)
    mix_cpp, prefix_cpp = parse_cpp_routing(routing_h)
    if all(v is None for v in mix_py.values()):
        finding(placement_ctx.path,
                "could not parse the _mix64 splitmix64 finalizer from "
                "runtime/placement.py — ROUTE-PARITY cannot verify the "
                "slot->slice hash")
        return findings
    if all(v is None for v in mix_cpp.values()):
        finding(config.ROUTING_H,
                "could not parse the kSplitMix64* constants from "
                "csrc/routing.h — ROUTE-PARITY cannot verify the "
                "slot->slice hash")
        return findings
    for key, label in _SPLITMIX_FIELD_LABELS.items():
        spec = config.SPLITMIX64_SPEC[key]
        py_v, cpp_v = mix_py.get(key), mix_cpp.get(key)
        if py_v is None:
            finding(placement_ctx.path,
                    f"{label}: missing/unparseable in placement._mix64 "
                    f"(spec says {spec:#x})")
        elif py_v != spec:
            finding(placement_ctx.path,
                    f"{label}: placement._mix64 uses {py_v:#x}, the "
                    f"pinned spec (analysis/config.py) says {spec:#x} — "
                    "a drifted hash remaps every slot's slice")
        if cpp_v is None:
            finding(config.ROUTING_H,
                    f"{label}: missing/unparseable in csrc/routing.h "
                    f"(spec says {spec:#x})")
        elif cpp_v != spec:
            finding(config.ROUTING_H,
                    f"{label}: csrc/routing.h says {cpp_v:#x}, the "
                    f"pinned spec (analysis/config.py) says {spec:#x} — "
                    "native and Python pools would route the same slot "
                    "to different slices")
    # The per-slice telemetry namespace.
    want = config.SLICE_SERIES_PREFIX
    if prefix_cpp is None:
        finding(config.ROUTING_H,
                "could not parse kSliceSeriesPrefix from csrc/routing.h "
                f"— expected the pinned prefix {want!r}")
    elif prefix_cpp != want:
        finding(config.ROUTING_H,
                f"kSliceSeriesPrefix is {prefix_cpp!r}, the pinned "
                f"per-slice series prefix is {want!r}")
    for ctx in series_ctxs:
        strings = _py_string_prefixes(ctx.tree)
        if not any(s.startswith(want) for s in strings):
            finding(ctx.path,
                    f"no telemetry series under the pinned per-slice "
                    f"prefix {want!r} — the per-slice schema emitter "
                    "moved or renamed its series")
    return findings


class RouteParityRule:
    """ROUTE-PARITY: runtime/placement.py == csrc/routing.h on the
    splitmix64 slot->slice hash, and every per-slice telemetry emitter
    uses the pinned `inference.slice.` namespace."""

    name = "ROUTE-PARITY"

    def check_repo(
        self, root: str, contexts: Sequence[FileContext]
    ) -> List[Finding]:
        by_path = {ctx.path: ctx for ctx in contexts}
        placement_ctx = by_path.get(config.PLACEMENT_PY)
        if placement_ctx is None:
            return []  # partial scan (explicit paths): parity not in scope
        routing_path = os.path.join(root, config.ROUTING_H)
        try:
            with open(routing_path, encoding="utf-8",
                      errors="replace") as f:
                routing_h = f.read()
        except OSError:
            routing_h = ""
        if not routing_h:
            return [
                Finding(
                    self.name, config.PLACEMENT_PY, 1,
                    "csrc/routing.h missing — the C++ side of the "
                    "slot->slice routing contract is gone",
                )
            ]
        series_ctxs = [
            by_path[p] for p in config.SLICE_SERIES_FILES if p in by_path
        ]
        return check_route_parity(placement_ctx, routing_h, series_ctxs)


# ---------------------------------------------------------------------------
# FLAG-PARITY


def _collect_flags(ctx: FileContext) -> Dict[str, dict]:
    """--flag -> {type, default, action, line} (unparsed expr text)."""
    out: Dict[str, dict] = {}
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and node.args[0].value.startswith("--")
        ):
            continue
        spec = {"type": "", "default": "", "action": "", "line": node.lineno}
        for kw in node.keywords:
            if kw.arg in ("type", "default", "action"):
                spec[kw.arg] = ast.unparse(kw.value)
        # Normalize cross-module constant spellings so
        # `wire.DEFAULT_MAX_FRAME_BYTES` == `DEFAULT_MAX_FRAME_BYTES` —
        # but only for identifier chains (a float literal like `0.1`
        # must not lose its integer part).
        if re.fullmatch(r"[A-Za-z_][\w.]*", spec["default"] or ""):
            spec["default"] = spec["default"].split(".")[-1]
        out[node.args[0].value] = spec
    return out


def check_flag_parity(
    ctx_a: FileContext, ctx_b: FileContext
) -> List[Finding]:
    """Shared flags must agree on type, default, and action. Findings
    anchor at the SECOND file's add_argument line (one finding per flag),
    so one inline suppression there exempts an intentional divergence."""
    flags_a = _collect_flags(ctx_a)
    flags_b = _collect_flags(ctx_b)
    findings: List[Finding] = []
    for flag in sorted(flags_a.keys() & flags_b.keys()):
        a, b = flags_a[flag], flags_b[flag]
        diffs = [
            f"{field} {a[field] or '<unset>'!r} (in {ctx_a.path}) vs "
            f"{b[field] or '<unset>'!r}"
            for field in ("type", "default", "action")
            if a[field] != b[field]
        ]
        if diffs:
            findings.append(
                Finding(
                    "FLAG-PARITY", ctx_b.path, b["line"],
                    f"flag {flag} diverges between drivers: "
                    + "; ".join(diffs),
                )
            )
    return findings


class FlagParityRule:
    """FLAG-PARITY: flags shared across driver pairs agree on type+default."""

    name = "FLAG-PARITY"

    def check_repo(
        self, root: str, contexts: Sequence[FileContext]
    ) -> List[Finding]:
        by_path = {ctx.path: ctx for ctx in contexts}
        findings: List[Finding] = []
        for path_a, path_b in config.FLAG_PARITY_GROUPS:
            ctx_a, ctx_b = by_path.get(path_a), by_path.get(path_b)
            if ctx_a is None or ctx_b is None:
                continue  # partial scan: this pair not in scope
            findings.extend(check_flag_parity(ctx_a, ctx_b))
        return findings


REPO_RULES = [WireParityRule(), RouteParityRule(), FlagParityRule()]
