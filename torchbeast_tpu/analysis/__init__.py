"""beastlint — repo-native static analysis for torchbeast_tpu.

`python -m torchbeast_tpu.analysis [--json] [--ci] [paths...]` runs the
rule set over the repo (default: the whole tree) and fails CI at the
offending file:line. The rules encode the repo's real runtime contracts:

    HOTPATH-SYNC     no implicit device->host syncs in annotated hot paths
    JIT-HAZARD       no jit/scan construction in loops, no unhashable
                     static args, no immediately-invoked jit
    DONATE-USE       no reads of consume-once staged buffers after dispatch
    IMPORT-PURITY    per-package import allowlists (telemetry/, analysis/)
    LOCK-DISCIPLINE  `# guarded-by:` attributes only touched under their
                     lock; no bare .acquire() without try/finally
    EXCEPT-SWALLOW   broad except bodies on runtime/ + resilience/ paths
                     re-raise, log, or count the failure (no silent
                     swallows on the failure-handling layers)
    WIRE-PARITY      runtime/wire.py == csrc/{wire,array,client}.h on the
                     dtype table, frame tags, and kMaxFrameBytes
    FLAG-PARITY      flags shared across driver pairs (mono/poly,
                     poly/polybeast_env, poly/chaos_run) agree on
                     default and type

Whole-program concurrency rules (ISSUE 7) ride the module -> call ->
thread-root graph in analysis/graph.py plus the per-function sync
summaries in analysis/summaries.py:

    RACE                cross-thread-root attribute conflicts with no
                        common lock (guards inferred from observed
                        `with self._lock:` dominance; `# guarded-by`
                        annotations become cross-checked assertions)
    LOCK-ORDER          lock-acquisition ordering cycles across roots +
                        non-reentrant re-acquisition self-deadlocks
    HOTPATH-SYNC-XPROC  interprocedural HOTPATH-SYNC: helpers that
                        host-convert tainted params flag at every hot
                        call site; device-returning helpers taint
                        their callers

Cross-language C++ rules (ISSUE 10) ride the stdlib-only C++ frontend
in analysis/cxx.py (lexer + extractor over csrc/*.h|*.cc — no libclang)
and the protocol spec in analysis/protocol.py (whose exhaustive model
checker runs as `--check-protocol`):

    GIL-DISCIPLINE       CPython API calls in the binding layer only
                         with the GIL held; no blocking calls (waits,
                         recvs, queue dequeues — direct or via the
                         may-block call summary) while holding it;
                         acquire/release pairing balanced
    ATOMIC-ORDER         shm ring header words only through the
                         designated atomic accessors with the documented
                         memory orders (C++) / named offsets (Python);
                         both languages' access sequences conform to the
                         model-checked protocol spec
    CXX-LOCK-DISCIPLINE  `// guarded-by: mu_` members only touched under
                         an RAII guard, plus cross-root conflicts over
                         std::thread spawn sites and Python-facing entry
                         methods (the C++ half of PR 7's thread graph)

Distributed-systems rules (ISSUE 20) ride the control-plane extractors
in analysis/fleetrules.py and the fleet protocol spec in
analysis/fleetproto.py (whose exhaustive model checker runs as
`--check-fleet`):

    FLEET-MSG-PARITY         every fleet control-plane send site (dict
                             literals with a "type" key into
                             _send/_broadcast) has a receiving-role
                             handler arm and the field sets agree, per
                             role (lead vs remote); handled types must
                             be sent by someone
    FLEET-TIMEOUT-DISCIPLINE every blocking control-plane operation
                             under fleet/ (accept, recv, dial,
                             cond/event wait, join) is under a deadline
                             or carries an explicit
                             `# unbounded-by-design: <why>` annotation
                             (the reader threads' EOF-side loss
                             detection, stated in the source)
    TELEMETRY-SCHEMA         the repo-wide series registry: naming
                             grammar (`layer.noun[_noun]`; the
                             `host<r>.` fold prefix reserved to the
                             lead's telemetry folder), one instrument
                             kind per name, and every series the chaos
                             verdicts / telemetry tests consume has an
                             emitter

See README "Static analysis" for the suppression syntax and how to add a
rule. The package is stdlib-only by contract (enforced by its own
IMPORT-PURITY entry).
"""

from .engine import (  # noqa: F401
    FileContext,
    Finding,
    Report,
    Suppression,
    discover_files,
    load_baseline,
    load_context,
    repo_root,
    run_rules,
    write_baseline,
)
from .cxxrules import CXX_RULES  # noqa: F401
from .fleetrules import FLEET_RULES  # noqa: F401
from .parity import REPO_RULES as PARITY_RULES  # noqa: F401
from .rules import CONCURRENCY_RULES, FILE_RULES  # noqa: F401

# Repo-level rules: cross-language/cross-driver parity, the
# whole-program concurrency rules (which share one Program model per
# run via graph.get_program's cache), the C++ concurrency rules over
# the analysis/cxx.py frontend contexts, and the distributed-systems
# rules over the fleet control plane + telemetry registry.
REPO_RULES = (
    list(PARITY_RULES) + list(CONCURRENCY_RULES) + list(CXX_RULES)
    + list(FLEET_RULES)
)

ALL_RULE_NAMES = (
    {r.name for r in FILE_RULES}
    | {r.name for r in REPO_RULES}
    | {"SUPPRESS-REASON"}
)


def analyze_source(source: str, path: str = "snippet.py", rules=None):
    """Lint a source string (fixture tests / selftest). Suppression and
    hygiene mechanics apply exactly as in a real run."""
    ctx = FileContext(path, source)
    report = run_rules(
        [ctx],
        rules if rules is not None else FILE_RULES,
        [],
        root="/",
        known_rules=ALL_RULE_NAMES,
    )
    return report


def analyze_sources(sources, repo_rules=None):
    """Lint a {path: source} program (multi-module fixtures): file rules
    per context plus the repo rules (concurrency rules by default) over
    the whole set."""
    contexts = [FileContext(path, src) for path, src in sources.items()]
    return run_rules(
        contexts,
        FILE_RULES,
        repo_rules if repo_rules is not None else list(CONCURRENCY_RULES),
        root="/",
        known_rules=ALL_RULE_NAMES,
    )


def analyze_cxx_sources(sources, repo_rules=None):
    """Lint a {path: source} fixture program through the C++ frontend:
    .h/.cc paths load as CxxFileContext, .py paths as FileContext, and
    the C++ rules (by default) run over the whole set — the selftest /
    test harness entry for GIL-DISCIPLINE, ATOMIC-ORDER, and
    CXX-LOCK-DISCIPLINE fixtures."""
    from . import cxx

    contexts = [
        cxx.CxxFileContext(path, src)
        if path.endswith((".h", ".hpp", ".cc", ".cpp"))
        else FileContext(path, src)
        for path, src in sources.items()
    ]
    return run_rules(
        contexts,
        [],
        repo_rules if repo_rules is not None else list(CXX_RULES),
        root="/",
        known_rules=ALL_RULE_NAMES,
    )


def analyze_paths(paths, root=None, baseline_path=None, only_paths=None):
    """Lint files/directories on disk with the full rule set.

    `only_paths` (repo-relative, posix) restricts FINDINGS to those
    files while the program graph and parity anchors still come from the
    full `paths` scan — the `--diff` mode's contract."""
    root = root or repo_root()
    files = discover_files(paths, root)
    contexts = [c for c in (load_context(f, root) for f in files) if c]
    baseline = load_baseline(baseline_path)
    return run_rules(
        contexts,
        FILE_RULES,
        REPO_RULES,
        root=root,
        baseline=baseline,
        known_rules=ALL_RULE_NAMES,
        only_paths=only_paths,
    )
