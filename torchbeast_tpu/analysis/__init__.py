"""beastlint — repo-native static analysis for torchbeast_tpu.

`python -m torchbeast_tpu.analysis [--json] [--ci] [paths...]` runs the
rule set over the repo (default: the whole tree) and fails CI at the
offending file:line. The rules encode the repo's real runtime contracts:

    HOTPATH-SYNC     no implicit device->host syncs in annotated hot paths
    JIT-HAZARD       no jit/scan construction in loops, no unhashable
                     static args, no immediately-invoked jit
    DONATE-USE       no reads of consume-once staged buffers after dispatch
    IMPORT-PURITY    per-package import allowlists (telemetry/, analysis/)
    LOCK-DISCIPLINE  `# guarded-by:` attributes only touched under their
                     lock; no bare .acquire() without try/finally
    EXCEPT-SWALLOW   broad except bodies on runtime/ + resilience/ paths
                     re-raise, log, or count the failure (no silent
                     swallows on the failure-handling layers)
    WIRE-PARITY      runtime/wire.py == csrc/{wire,array,client}.h on the
                     dtype table, frame tags, and kMaxFrameBytes
    FLAG-PARITY      flags shared by monobeast/polybeast agree on default
                     and type

See README "Static analysis" for the suppression syntax and how to add a
rule. The package is stdlib-only by contract (enforced by its own
IMPORT-PURITY entry).
"""

from .engine import (  # noqa: F401
    FileContext,
    Finding,
    Report,
    Suppression,
    discover_files,
    load_baseline,
    load_context,
    repo_root,
    run_rules,
    write_baseline,
)
from .parity import REPO_RULES  # noqa: F401
from .rules import FILE_RULES  # noqa: F401

ALL_RULE_NAMES = (
    {r.name for r in FILE_RULES}
    | {r.name for r in REPO_RULES}
    | {"SUPPRESS-REASON"}
)


def analyze_source(source: str, path: str = "snippet.py", rules=None):
    """Lint a source string (fixture tests / selftest). Suppression and
    hygiene mechanics apply exactly as in a real run."""
    ctx = FileContext(path, source)
    report = run_rules(
        [ctx],
        rules if rules is not None else FILE_RULES,
        [],
        root="/",
        known_rules=ALL_RULE_NAMES,
    )
    return report


def analyze_paths(paths, root=None, baseline_path=None):
    """Lint files/directories on disk with the full rule set."""
    root = root or repo_root()
    files = discover_files(paths, root)
    contexts = [c for c in (load_context(f, root) for f in files) if c]
    baseline = load_baseline(baseline_path)
    return run_rules(
        contexts,
        FILE_RULES,
        REPO_RULES,
        root=root,
        baseline=baseline,
        known_rules=ALL_RULE_NAMES,
    )
