"""beastlint — repo-native static analysis for torchbeast_tpu.

`python -m torchbeast_tpu.analysis [--json] [--ci] [paths...]` runs the
rule set over the repo (default: the whole tree) and fails CI at the
offending file:line. The rules encode the repo's real runtime contracts:

    HOTPATH-SYNC     no implicit device->host syncs in annotated hot paths
    JIT-HAZARD       no jit/scan construction in loops, no unhashable
                     static args, no immediately-invoked jit
    DONATE-USE       no reads of consume-once staged buffers after dispatch
    IMPORT-PURITY    per-package import allowlists (telemetry/, analysis/)
    LOCK-DISCIPLINE  `# guarded-by:` attributes only touched under their
                     lock; no bare .acquire() without try/finally
    EXCEPT-SWALLOW   broad except bodies on runtime/ + resilience/ paths
                     re-raise, log, or count the failure (no silent
                     swallows on the failure-handling layers)
    WIRE-PARITY      runtime/wire.py == csrc/{wire,array,client}.h on the
                     dtype table, frame tags, and kMaxFrameBytes
    FLAG-PARITY      flags shared across driver pairs (mono/poly,
                     poly/polybeast_env, poly/chaos_run) agree on
                     default and type

Whole-program concurrency rules (ISSUE 7) ride the module -> call ->
thread-root graph in analysis/graph.py plus the per-function sync
summaries in analysis/summaries.py:

    RACE                cross-thread-root attribute conflicts with no
                        common lock (guards inferred from observed
                        `with self._lock:` dominance; `# guarded-by`
                        annotations become cross-checked assertions)
    LOCK-ORDER          lock-acquisition ordering cycles across roots +
                        non-reentrant re-acquisition self-deadlocks
    HOTPATH-SYNC-XPROC  interprocedural HOTPATH-SYNC: helpers that
                        host-convert tainted params flag at every hot
                        call site; device-returning helpers taint
                        their callers

See README "Static analysis" for the suppression syntax and how to add a
rule. The package is stdlib-only by contract (enforced by its own
IMPORT-PURITY entry).
"""

from .engine import (  # noqa: F401
    FileContext,
    Finding,
    Report,
    Suppression,
    discover_files,
    load_baseline,
    load_context,
    repo_root,
    run_rules,
    write_baseline,
)
from .parity import REPO_RULES as PARITY_RULES  # noqa: F401
from .rules import CONCURRENCY_RULES, FILE_RULES  # noqa: F401

# Repo-level rules: cross-language/cross-driver parity plus the
# whole-program concurrency rules (which share one Program model per
# run via graph.get_program's cache).
REPO_RULES = list(PARITY_RULES) + list(CONCURRENCY_RULES)

ALL_RULE_NAMES = (
    {r.name for r in FILE_RULES}
    | {r.name for r in REPO_RULES}
    | {"SUPPRESS-REASON"}
)


def analyze_source(source: str, path: str = "snippet.py", rules=None):
    """Lint a source string (fixture tests / selftest). Suppression and
    hygiene mechanics apply exactly as in a real run."""
    ctx = FileContext(path, source)
    report = run_rules(
        [ctx],
        rules if rules is not None else FILE_RULES,
        [],
        root="/",
        known_rules=ALL_RULE_NAMES,
    )
    return report


def analyze_sources(sources, repo_rules=None):
    """Lint a {path: source} program (multi-module fixtures): file rules
    per context plus the repo rules (concurrency rules by default) over
    the whole set."""
    contexts = [FileContext(path, src) for path, src in sources.items()]
    return run_rules(
        contexts,
        FILE_RULES,
        repo_rules if repo_rules is not None else list(CONCURRENCY_RULES),
        root="/",
        known_rules=ALL_RULE_NAMES,
    )


def analyze_paths(paths, root=None, baseline_path=None, only_paths=None):
    """Lint files/directories on disk with the full rule set.

    `only_paths` (repo-relative, posix) restricts FINDINGS to those
    files while the program graph and parity anchors still come from the
    full `paths` scan — the `--diff` mode's contract."""
    root = root or repo_root()
    files = discover_files(paths, root)
    contexts = [c for c in (load_context(f, root) for f in files) if c]
    baseline = load_baseline(baseline_path)
    return run_rules(
        contexts,
        FILE_RULES,
        REPO_RULES,
        root=root,
        baseline=baseline,
        known_rules=ALL_RULE_NAMES,
        only_paths=only_paths,
    )
