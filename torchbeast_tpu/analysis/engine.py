"""beastlint engine: file discovery, annotation/suppression parsing,
baseline mechanics, and the rule runner.

The engine is deliberately stdlib-only (`ast` + `tokenize` + `json`): the
analyzer must run in CI images without jax/numpy installed, and must never
import the code it analyzes (a stray import could execute device-touching
module bodies). Rules receive a parsed `FileContext` and return `Finding`s;
repo-level rules (wire/flag parity) receive every context at once.

Annotation grammar (all live in comments, so the runtime never sees them):

    # beastlint: disable=RULE[,RULE2]  <reason>   suppress findings on this
                                                  line (trailing) or the next
                                                  line (standalone comment)
    # beastlint: hot                              on/above a `def`: function
                                                  is an acting/learning hot
                                                  path (HOTPATH-SYNC applies)
    # beastlint: hot-module                       whole module is hot
    # beastlint: holds self._lock                 on/above a `def`: method is
                                                  documented as called with
                                                  the lock already held
    # guarded-by: self._lock                      trailing `self.attr = ...`:
                                                  attr may only be touched
                                                  under `with self._lock`
                                                  (LOCK-DISCIPLINE)

Suppressions without a reason are themselves findings (SUPPRESS-REASON):
the whole point of an inline disable is the recorded justification.

Baseline: a committed JSON list of finding fingerprints (rule + path +
message, line-insensitive so pure code motion doesn't churn it). `--ci`
fails on any finding not in the baseline. The repo's committed baseline is
EMPTY — new debt needs an inline, reasoned suppression, not a baseline
entry.
"""

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import time
import tokenize
from typing import Dict, List, Optional, Sequence, Set, Tuple

# Directories never scanned (build outputs, VCS metadata, vendored eggs).
SKIP_DIRS = {
    ".git",
    "build",
    "dist",
    "__pycache__",
    ".eggs",
    ".pytest_cache",
    "node_modules",
}

_DISABLE_RE = re.compile(
    r"#\s*beastlint:\s*disable=([A-Za-z0-9_,\-]+)\s*(.*)$"
)
_HOT_RE = re.compile(r"#\s*beastlint:\s*hot\s*$")
_HOT_MODULE_RE = re.compile(r"#\s*beastlint:\s*hot-module\b")
_HOLDS_RE = re.compile(r"#\s*beastlint:\s*holds\s+(\S+)")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(\S+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str

    @property
    def fingerprint(self) -> str:
        """Line-insensitive identity: stable across pure code motion."""
        digest = hashlib.sha256(
            f"{self.rule}|{self.path}|{self.message}".encode()
        ).hexdigest()
        return digest[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


@dataclasses.dataclass
class Suppression:
    line: int  # line the comment sits on
    rules: Optional[Set[str]]  # None = all rules
    reason: str
    standalone: bool  # comment-only line: also covers the next line
    used: bool = False


class FileContext:
    """One parsed source file plus its beastlint annotations."""

    # C++ sources load as analysis.cxx.CxxFileContext (is_cxx=True);
    # file rules only see Python contexts, repo rules see both.
    is_cxx = False

    def __init__(self, path: str, source: str, abspath: str = ""):
        self.path = path.replace(os.sep, "/")
        self.abspath = abspath or path
        self.source = source
        self.tree = ast.parse(source)
        # line -> raw comment text (including '#').
        self.comments: Dict[int, str] = {}
        # line -> True when the line holds ONLY a comment.
        self._comment_only: Dict[int, bool] = {}
        self._scan_comments(source)

        self.suppressions: List[Suppression] = []
        self.hot_module = False
        self._hot_lines: Set[int] = set()
        self._holds: Dict[int, str] = {}
        self.guarded_annotations: Dict[int, str] = {}
        self._parse_annotations()

    def _scan_comments(self, source: str) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            prev_row_has_code: Dict[int, bool] = {}
            for tok in tokens:
                if tok.type == tokenize.COMMENT:
                    row = tok.start[0]
                    self.comments[row] = tok.string
                    self._comment_only[row] = not prev_row_has_code.get(
                        row, False
                    )
                elif tok.type not in (
                    tokenize.NL,
                    tokenize.NEWLINE,
                    tokenize.INDENT,
                    tokenize.DEDENT,
                    tokenize.ENDMARKER,
                ):
                    for row in range(tok.start[0], tok.end[0] + 1):
                        prev_row_has_code[row] = True
        except tokenize.TokenError:
            pass

    def _parse_annotations(self) -> None:
        for line, text in self.comments.items():
            m = _DISABLE_RE.search(text)
            if m:
                rules_text, reason = m.group(1), m.group(2).strip()
                names = {
                    r.strip() for r in rules_text.split(",") if r.strip()
                }
                self.suppressions.append(
                    Suppression(
                        line=line,
                        rules=None if "all" in names else names,
                        reason=reason,
                        standalone=self._comment_only.get(line, False),
                    )
                )
                continue
            if _HOT_MODULE_RE.search(text):
                self.hot_module = True
            elif _HOT_RE.search(text):
                self._hot_lines.add(line)
            m = _HOLDS_RE.search(text)
            if m:
                self._holds[line] = m.group(1)
            m = _GUARDED_RE.search(text)
            if m:
                self.guarded_annotations[line] = m.group(1)

    # -- annotation queries -------------------------------------------------

    def is_hot_def(self, node: ast.AST) -> bool:
        """A def annotated `# beastlint: hot` on its line, the line above,
        or above its first decorator."""
        if self.hot_module:
            return True
        first = getattr(node, "lineno", 0)
        decorators = getattr(node, "decorator_list", [])
        if decorators:
            first = min(first, min(d.lineno for d in decorators))
        for line in range(first - 1, getattr(node, "lineno", 0) + 1):
            if line in self._hot_lines:
                return True
        return False

    def comment_only(self, line: int) -> bool:
        """True when `line` holds only a comment (no code)."""
        return self._comment_only.get(line, False)

    def holds_annotation(self, node: ast.AST) -> Optional[str]:
        first = getattr(node, "lineno", 0)
        for line in (first - 1, first):
            if line in self._holds:
                return self._holds[line]
        return None

    # -- suppression application -------------------------------------------

    def suppression_for(self, finding: Finding) -> Optional[Suppression]:
        for sup in self.suppressions:
            covered = {sup.line}
            if sup.standalone:
                covered.add(sup.line + 1)
            if finding.line not in covered:
                continue
            if sup.rules is None or finding.rule in sup.rules:
                return sup
        return None


# C++ sources the frontend (analysis/cxx.py) lexes; the C++ rules
# (GIL-DISCIPLINE, ATOMIC-ORDER, CXX-LOCK-DISCIPLINE) run over these.
CXX_SUFFIXES = (".h", ".hpp", ".cc", ".cpp")


def discover_files(paths: Sequence[str], root: str) -> List[str]:
    """Expand files/directories into a sorted list of .py and C++
    (.h/.cc) sources."""
    suffixes = (".py",) + CXX_SUFFIXES
    out: Set[str] = set()
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap) and ap.endswith(suffixes):
            out.add(os.path.abspath(ap))
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [
                    d for d in dirnames
                    if d not in SKIP_DIRS and not d.endswith(".egg-info")
                ]
                for fn in filenames:
                    if fn.endswith(suffixes):
                        out.add(os.path.abspath(os.path.join(dirpath, fn)))
    return sorted(out)


def load_context(abspath: str, root: str) -> Optional[FileContext]:
    rel = os.path.relpath(abspath, root)
    try:
        with open(abspath, "r", encoding="utf-8", errors="replace") as f:
            source = f.read()
        if abspath.endswith(CXX_SUFFIXES):
            from . import cxx  # local import: engine stays ast-only

            return cxx.CxxFileContext(rel, source, abspath=abspath)
        return FileContext(rel, source, abspath=abspath)
    except (SyntaxError, ValueError, OSError):
        return None


@dataclasses.dataclass
class Report:
    findings: List[Finding]
    suppressed: List[Tuple[Finding, Suppression]]
    baselined: List[Finding]
    files_scanned: int
    elapsed_s: float = 0.0
    # Wall-clock per rule name, seconds (file rules summed across
    # contexts) — scripts/lint.sh prints these so a new whole-tree scan
    # cannot silently regress the CI budget.
    rule_timings: Dict[str, float] = dataclasses.field(
        default_factory=dict
    )

    def as_dict(self) -> dict:
        return {
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": [
                {**f.as_dict(), "reason": s.reason}
                for f, s in self.suppressed
            ],
            "baselined": [f.as_dict() for f in self.baselined],
            "files_scanned": self.files_scanned,
            "elapsed_s": self.elapsed_s,
            "rule_timings": {
                name: round(t, 4)
                for name, t in sorted(self.rule_timings.items())
            },
        }


def load_baseline(path: Optional[str]) -> Set[str]:
    if not path or not os.path.isfile(path):
        return set()
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict):
        data = data.get("fingerprints", [])
    return {str(fp) for fp in data}


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    fingerprints = sorted({f.fingerprint for f in findings})
    with open(path, "w") as f:
        json.dump({"fingerprints": fingerprints}, f, indent=2)
        f.write("\n")


def run_rules(
    contexts: Sequence[FileContext],
    file_rules,
    repo_rules,
    root: str,
    baseline: Set[str] = frozenset(),
    known_rules: Optional[Set[str]] = None,
    only_paths: Optional[Set[str]] = None,
) -> Report:
    """Run every rule, apply suppressions and the baseline.

    `only_paths` filters FINDINGS (and suppression hygiene) to a file
    subset while every rule still sees the full context set — the
    `--diff` mode: the whole-program graph and parity anchors need the
    repo, the gate only cares about the changed files.

    Suppression hygiene is enforced here, not per-rule: a reasonless
    suppression, or one naming an unknown rule, is a SUPPRESS-REASON
    finding anchored at the comment (these cannot themselves be
    suppressed — that would be a hole in the gate).
    """
    raw: List[Finding] = []
    timings: Dict[str, float] = {}
    ctx_by_path: Dict[str, FileContext] = {}
    for ctx in contexts:
        ctx_by_path[ctx.path] = ctx
        if ctx.is_cxx:
            continue  # Python file rules; C++ rules are repo rules
        for rule in file_rules:
            t0 = time.perf_counter()
            raw.extend(rule.check(ctx))
            timings[rule.name] = (
                timings.get(rule.name, 0.0) + time.perf_counter() - t0
            )
    for rule in repo_rules:
        t0 = time.perf_counter()
        raw.extend(rule.check_repo(root, contexts))
        timings[rule.name] = (
            timings.get(rule.name, 0.0) + time.perf_counter() - t0
        )
    if only_paths is not None:
        raw = [f for f in raw if f.path in only_paths]

    findings: List[Finding] = []
    suppressed: List[Tuple[Finding, Suppression]] = []
    baselined: List[Finding] = []
    for f in raw:
        ctx = ctx_by_path.get(f.path)
        sup = ctx.suppression_for(f) if ctx is not None else None
        if sup is not None:
            sup.used = True
            suppressed.append((f, sup))
        elif f.fingerprint in baseline:
            baselined.append(f)
        else:
            findings.append(f)

    all_rules = known_rules or set()
    for ctx in contexts:
        if only_paths is not None and ctx.path not in only_paths:
            continue
        for sup in ctx.suppressions:
            if not sup.reason:
                findings.append(
                    Finding(
                        "SUPPRESS-REASON",
                        ctx.path,
                        sup.line,
                        "beastlint suppression without a reason "
                        "(write `# beastlint: disable=RULE  <why>`)",
                    )
                )
            if sup.rules and all_rules:
                for name in sorted(sup.rules - all_rules):
                    findings.append(
                        Finding(
                            "SUPPRESS-REASON",
                            ctx.path,
                            sup.line,
                            f"suppression names unknown rule {name!r}",
                        )
                    )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return Report(
        findings=findings,
        suppressed=suppressed,
        baselined=baselined,
        files_scanned=len(contexts),
        rule_timings=timings,
    )


def repo_root() -> str:
    """The repository root: two levels above this package."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
