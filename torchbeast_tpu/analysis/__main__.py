"""beastlint CLI.

    python -m torchbeast_tpu.analysis                  lint the whole repo
    python -m torchbeast_tpu.analysis --ci             CI gate: terse, exit 1
                                                       on any new finding
    python -m torchbeast_tpu.analysis --json [paths]   machine output
    python -m torchbeast_tpu.analysis --selftest       fixture verdict JSON
    python -m torchbeast_tpu.analysis --diff REF       lint only files
                                                       changed vs REF (graph
                                                       built repo-wide;
                                                       scripts/lint.sh wraps
                                                       this as a pre-commit
                                                       hook)
    python -m torchbeast_tpu.analysis --write-baseline grandfather current
                                                       findings (the repo's
                                                       committed baseline is
                                                       empty — keep it that
                                                       way)

Exit codes: 0 clean, 1 findings, 2 usage/internal error.
"""

import argparse
import json
import os
import subprocess
import sys
import time

from . import REPO_RULES, analyze_paths
from .engine import repo_root, write_baseline
from .rules import FILE_RULES

DEFAULT_BASELINE = ".beastlint-baseline.json"


# The changed-file filter's path patterns: Python sources AND the C++
# core (ISSUE 10 satellite — the pre-commit wrapper used to feed only
# Python paths, so a csrc-only change skipped the C++ rules entirely).
DIFF_PATTERNS = ("*.py", "*.h", "*.hpp", "*.cc", "*.cpp")


def changed_files(root: str, ref: str):
    """Repo-relative .py/.h/.cc files changed vs `ref` (committed +
    working tree + untracked) — the `--diff` scope. Raises on git
    failure so the CLI exits 2 instead of silently linting nothing."""
    out = subprocess.run(
        ["git", "-C", root, "diff", "--name-only", ref, "--",
         *DIFF_PATTERNS],
        capture_output=True, text=True, check=True,
    ).stdout
    untracked = subprocess.run(
        ["git", "-C", root, "ls-files", "--others",
         "--exclude-standard", "--", *DIFF_PATTERNS],
        capture_output=True, text=True, check=True,
    ).stdout
    return {
        line.strip() for line in (out + untracked).splitlines()
        if line.strip()
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torchbeast_tpu.analysis",
        description="beastlint: repo-native static analysis",
    )
    parser.add_argument("paths", nargs="*",
                        help="Files/directories to lint (default: repo "
                             "root; parity rules need the default scope).")
    parser.add_argument("--json", action="store_true",
                        help="Emit one JSON document instead of text.")
    parser.add_argument("--ci", action="store_true",
                        help="CI gate mode: same checks and exit code, "
                             "plus a final machine-greppable "
                             "'beastlint-ci: PASS|FAIL' verdict line.")
    parser.add_argument("--selftest", action="store_true",
                        help="Run the embedded rule fixtures and print a "
                             "JSON verdict.")
    parser.add_argument("--check-protocol", action="store_true",
                        help="Exhaustively model-check the shm ring + "
                             "doorbell protocol spec (and prove the "
                             "seeded mutations produce counterexample "
                             "traces); prints a JSON verdict plus the "
                             "mutants' traces.")
    parser.add_argument("--check-fleet", action="store_true",
                        help="Exhaustively model-check the fleet "
                             "control-plane protocol spec (rendezvous, "
                             "sync barrier, halt plane, snapshot "
                             "monotonicity under crash/wedge faults; "
                             "conformance-pinned against "
                             "fleet/coordinator.py); prints a JSON "
                             "verdict plus the mutants' traces.")
    parser.add_argument("--timing", action="store_true",
                        help="Print per-rule wall-clock after the "
                             "report (scripts/lint.sh passes this so "
                             "rule-cost regressions are visible).")
    parser.add_argument("--diff", metavar="GIT_REF", default=None,
                        help="Lint only files changed vs GIT_REF "
                             "(committed, working tree, and untracked); "
                             "the whole-program graph and parity "
                             "anchors are still built repo-wide. The "
                             "scripts/lint.sh pre-commit wrapper uses "
                             "this.")
    parser.add_argument("--baseline", default=None,
                        help=f"Baseline file (default: <repo>/"
                             f"{DEFAULT_BASELINE}).")
    parser.add_argument("--write-baseline", action="store_true",
                        help="Write current findings to the baseline file "
                             "and exit 0.")
    parser.add_argument("--list-rules", action="store_true",
                        help="Print the rule set and exit.")
    args = parser.parse_args(argv)

    if args.selftest:
        from .selftest import main as selftest_main

        return selftest_main()

    if args.check_protocol:
        from .protocol import main as protocol_main

        return protocol_main()

    if args.check_fleet:
        from .fleetproto import main as fleet_main

        return fleet_main()

    if args.list_rules:
        for rule in (*FILE_RULES, *REPO_RULES):
            lines = (rule.__doc__ or "").strip().splitlines()
            print(f"{rule.name:16s} {lines[0] if lines else ''}")
        return 0

    if args.write_baseline and args.diff is not None:
        # A baseline written from a changed-files-only report would
        # silently DROP every grandfathered fingerprint in unchanged
        # files — the next full --ci run fails on intentionally
        # baselined findings.
        print(
            "beastlint: --write-baseline requires a full scan; "
            "drop --diff",
            file=sys.stderr,
        )
        return 2

    root = repo_root()
    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    paths = args.paths or ["."]

    t0 = time.perf_counter()
    try:
        only_paths = None
        if args.diff is not None:
            only_paths = changed_files(root, args.diff)
            if not only_paths:
                if args.json:
                    doc = {
                        "findings": [], "suppressed": [],
                        "baselined": [], "files_scanned": 0,
                        "elapsed_s": 0.0,
                        "note": "no .py/.h/.cc files changed vs "
                                f"{args.diff}",
                    }
                    if args.ci:
                        doc["ci"] = "PASS"
                    print(json.dumps(doc))
                else:
                    print(
                        "beastlint: no .py/.h/.cc files changed vs "
                        f"{args.diff}"
                    )
                    if args.ci:
                        print("beastlint-ci: PASS")
                return 0
        report = analyze_paths(
            paths, root=root,
            baseline_path=None if args.write_baseline else baseline_path,
            only_paths=only_paths,
        )
    except subprocess.CalledProcessError as e:
        print(
            f"beastlint: --diff failed: {e.stderr or e}", file=sys.stderr
        )
        return 2
    except Exception as e:  # beastlint: disable=EXCEPT-SWALLOW  CLI boundary: the failure is printed to stderr and surfaced as exit code 2
        print(f"beastlint: internal error: {e}", file=sys.stderr)
        return 2
    report.elapsed_s = round(time.perf_counter() - t0, 3)

    if args.write_baseline:
        write_baseline(baseline_path, report.findings)
        print(
            f"beastlint: wrote {len(report.findings)} fingerprint(s) to "
            f"{baseline_path}"
        )
        return 0

    verdict = "FAIL" if report.findings else "PASS"
    if args.json:
        doc = report.as_dict()
        if args.ci:
            doc["ci"] = verdict
        print(json.dumps(doc))
    else:
        for f in report.findings:
            print(f.render())
        print(
            f"beastlint: {len(report.findings)} finding(s), "
            f"{len(report.suppressed)} suppressed, "
            f"{len(report.baselined)} baselined; "
            f"{report.files_scanned} files in {report.elapsed_s:.2f}s"
        )
        if args.timing:
            for name, t in sorted(
                report.rule_timings.items(), key=lambda kv: -kv[1]
            ):
                print(f"beastlint-timing: {name:24s} {t:7.3f}s")
        if args.ci:
            print(f"beastlint-ci: {verdict}")
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
