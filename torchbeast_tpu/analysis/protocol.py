"""Exhaustive explicit-state model checking of the SPSC ring +
coalesced-doorbell protocol (ISSUE 10 tentpole).

The shm transport's correctness argument has always been an English
paragraph ("the re-check after set_waiting closes the lost-wakeup
window; the send syscall fences the marker publish") backed by
probabilistic dynamic tests. This module writes the protocol down ONCE
as a small transition system and enumerates EVERY interleaving of one
reader + one writer over a bounded ring — turning the paragraph into a
machine-checked proof, and turning each historical bug into a seeded
mutation whose counterexample trace the checker must reproduce.

What is modeled (matching runtime/transport.py and csrc/shm.h):

- The ring as a bounded FIFO of frame entries (frames and inline
  markers). Head/tail arithmetic, wrap markers, and byte sizes are
  abstracted away: they are layout, pinned separately by WIRE-PARITY;
  the protocol questions (who blocks, who rings, who re-checks) live at
  the entry level.
- The doorbell socket as an ordered byte queue: WAKE (0x01), INLINE
  (0x02), and abstract inline payloads.
- Store buffers: the writer's head-publish and the reader's
  waiting-flag store each sit in a per-process one-way buffer until a
  nondeterministic flush — CPython emits no store-load fence between
  the publish and the waiting-flag load, so the model must be able to
  reorder exactly the way x86 TSO does. A syscall (send/recv/poll)
  flushes the issuing process's buffer first: this is the "the sendmsg
  syscall fences the marker publish" property the inline recovery path
  relies on, stated as a model rule instead of a comment.
- The reader's bounded recheck (the adaptive poll timeout, ISSUE 12:
  initial RECHECK_MS walking within [RECHECK_MIN_MS, RECHECK_MAX_MS])
  as a timeout transition enabled while blocked. The transition is
  untimed, so it covers ANY finite positive bound — the adaptive
  policy changes WHEN the recheck fires, never WHETHER; the 100 us
  empty-spin is a latency optimization with no protocol content and is
  not modeled.

Checked properties (check_protocol):

- FIFO: every delivery appends the next message id, in order.
- error-free: no reachable state raises a protocol error ("bad doorbell
  byte", "inline byte with an empty ring", teardown on a live stream).
- no wedge (deadlock AND lost-wakeup freedom): from every reachable
  state, a completed state (all messages delivered, both sides done)
  is still reachable. This subsumes deadlock (no enabled transition)
  and livelock (cycles that cannot make progress): a lost wakeup that
  the recheck recovers is fine; one that wedges the run is a trace.

Seeded mutations (MUTATIONS) re-run the checker on a broken spec and
must FIND the bug as a counterexample trace:

- no_wake_recheck: remove the bounded poll timeout — the PR 9
  "metastable wait" (a lost wakeup parks the reader forever).
- no_inline_recovery: treat an INLINE byte arriving on a blocked reader
  as a protocol error — the PR 3 fence-less oversized-path lost-wakeup
  (sender reads stale waiting=0, skips WAKE, lands 0x02 on a blocked
  reader).

Conformance (SPEC_ACCESS / RECHECK_MS): the spec's accessor sequences
are pinned against BOTH implementations by the ATOMIC-ORDER rule via
the C++ frontend and the transport.py AST — reordering a header access
in either language breaks the pin (see cxxrules.check_conformance).
"""

import dataclasses
import json
from typing import Dict, FrozenSet, Iterator, List, Optional, Tuple

# ---------------------------------------------------------------------------
# The spec, as data

# The INITIAL bounded-recheck period both implementations must use (ms):
# transport.py _WAKE_RECHECK_S * 1000 == csrc/shm.h kWakeRecheckMs == this.
RECHECK_MS = 20

# Adaptive recheck policy (ISSUE 12): per connection, the bound walks
# within [RECHECK_MIN_MS, RECHECK_MAX_MS] — a window of RECHECK_WINDOW
# armed waits with >= RECHECK_TIGHTEN ended by the timeout halves it,
# <= RECHECK_RELAX doubles it. All five are pinned against transport.py
# (_RECHECK_*) and csrc/shm.h (kRecheck*) by cxxrules' ATOMIC-ORDER
# recheck check. The model's timeout transition (r:recheck_timeout) is
# UNTIMED: it models "the blocked reader eventually re-checks", which
# holds for ANY finite positive bound — so the adaptive policy is
# covered by the shipped verification as long as RECHECK_MIN_MS > 0
# (adaptive_recheck_covered() below; asserted by --check-protocol).
RECHECK_MIN_MS = 5
RECHECK_MAX_MS = 100
RECHECK_WINDOW = 32
RECHECK_TIGHTEN = 16
RECHECK_RELAX = 4


def adaptive_recheck_covered() -> bool:
    """True when the adaptive policy stays inside what the no-wedge
    proof covers: the bound is finite and positive at every point of
    the walk (the timeout transition stays enabled), and the window
    thresholds are a well-formed hysteresis band."""
    return (
        0 < RECHECK_MIN_MS <= RECHECK_MS <= RECHECK_MAX_MS
        and 0 <= RECHECK_RELAX < RECHECK_TIGHTEN <= RECHECK_WINDOW
    )

# Canonical per-method header/data access sequences (adjacent-duplicate
# collapsed), identical for transport.py's ShmRing and csrc/shm.h's —
# the two implementations must match each other AND this table.
SPEC_ACCESS: Dict[str, Tuple[str, ...]] = {
    "write_frame": ("R:head", "R:tail", "W:data", "R:tail", "W:data",
                    "W:head"),
    "write_inline_marker": ("R:head", "R:tail", "W:data", "R:tail",
                            "W:data", "W:head"),
    "read_frame": ("R:tail", "R:head", "R:data"),
    "release": ("R:tail", "W:tail"),
    "has_frame": ("R:head", "R:tail"),
    "set_waiting": ("W:waiting",),
    "reader_waiting": ("R:waiting",),
}

# Ordering invariants that survive branch-shape differences: per method,
# (op_a, op_b) pairs meaning every occurrence of op_a precedes the LAST
# occurrence of op_b, plus a required final op. These are the
# release-publish facts the model checker's atomicity assumptions rest
# on (data is visible when head is).
SPEC_FINAL_OP: Dict[str, str] = {
    "write_frame": "W:head",  # publish LAST: data before head
    "write_inline_marker": "W:head",
    "release": "W:tail",  # the slot frees only after the frame is done
}


@dataclasses.dataclass(frozen=True)
class Spec:
    """Protocol variant knobs. The shipped configuration is Spec();
    mutations flip one knob each (MUTATIONS)."""

    # Reader: the blocked doorbell wait re-checks the ring every
    # RECHECK_MS even without a byte (the lost-wakeup bound).
    wake_recheck: bool = True
    # Reader: an INLINE byte landing while blocked in the wait loop is
    # recovered (re-check the ring — the marker is fenced in by the
    # sender's syscall — and deliver via the marker path).
    inline_recovery: bool = True
    # Writer: ring the bell only when the reader's waiting flag is set
    # (coalescing). Disabling makes every send ring (safe, slower).
    coalesce_wakeups: bool = True
    # Reader: re-check the ring between arming the waiting flag and
    # blocking (the Dekker half of the handshake).
    post_arm_recheck: bool = True


MUTATIONS: Dict[str, Spec] = {
    # PR 9's metastable-wait class: without the bounded recheck a lost
    # wakeup parks the reader until the next (never-coming) doorbell.
    "no_wake_recheck": Spec(wake_recheck=False),
    # PR 3's historical fence-less oversized-path bug: the INLINE byte
    # lands on a blocked reader that treats it as a protocol error.
    "no_inline_recovery": Spec(inline_recovery=False),
    # Removing the post-arm recheck AND the timeout wedges even under
    # sequential consistency (kept as a third seeded mutant: it shows
    # the two guards are independently load-bearing).
    "no_arm_recheck_no_timeout": Spec(wake_recheck=False,
                                      post_arm_recheck=False),
}


# ---------------------------------------------------------------------------
# State
#
# Immutable tuples throughout; the whole state is hashable.
#
#   ring      tuple of ('F', id) / ('M', id) entries VISIBLE in memory
#   wbuf      writer store buffer: tuple of pending ring entries
#   rbuf      reader store buffer: pending waiting value or None
#   waiting   waiting flag value in memory (0/1)
#   sock      tuple of socket tokens: 'W', 'I', ('P', id)
#   wphase    writer phase (see below), windex = current message index
#   rphase    reader phase, delivered = count of delivered messages
#   held      reader holds an unreleased ring slot (freed at next recv)
#   inline_consumed  reader consumed the 0x02 during the wait loop
#
# Writer phases: 'space' -> 'waitcheck' -> ('bell' | next) for ring
# messages; 'space' -> 'waitcheck' -> ('bell_inline' | 'inline_byte')
# -> 'payload' for inline ones; 'done'.
# Reader phases: 'recv' (release+check) -> 'arm' -> 'recheck' ->
# 'blocked' -> ... ; 'inline_wait' reads the socket for the payload;
# 'done'; 'error'.

State = Tuple


def _initial(n_msgs: int) -> State:
    return (
        (),      # ring
        (),      # wbuf
        None,    # rbuf
        0,       # waiting
        (),      # sock
        "space", 0,   # wphase, windex
        "recv", 0,    # rphase, delivered
        False,   # held slot
        False,   # inline_consumed
    )


_RING, _WBUF, _RBUF, _WAITING, _SOCK = 0, 1, 2, 3, 4
_WPHASE, _WIDX, _RPHASE, _DELIVERED, _HELD, _INLINE = 5, 6, 7, 8, 9, 10


def _with(state: State, **kw) -> State:
    names = ["ring", "wbuf", "rbuf", "waiting", "sock", "wphase",
             "windex", "rphase", "delivered", "held", "inline_consumed"]
    vals = list(state)
    for key, value in kw.items():
        vals[names.index(key)] = value
    return tuple(vals)


def _flush_writer(state: State) -> State:
    if not state[_WBUF]:
        return state
    return _with(state, ring=state[_RING] + state[_WBUF], wbuf=())


def _flush_reader(state: State) -> State:
    if state[_RBUF] is None:
        return state
    return _with(state, waiting=state[_RBUF], rbuf=None)


def _reader_sees_ring(state: State) -> Tuple:
    # The reader sees memory; the writer's unflushed entries are
    # invisible (that IS the race).
    return state[_RING]


def _writer_occupancy(state: State) -> int:
    # The writer sees its own buffered entries plus memory; consumed
    # entries left in memory until release still occupy space — modeled
    # by the reader's `held` flag keeping one slot accounted.
    return len(state[_RING]) + len(state[_WBUF]) + (1 if state[_HELD] else 0)


@dataclasses.dataclass
class Violation:
    kind: str  # 'fifo' | 'error' | 'wedge'
    detail: str
    trace: List[str]


@dataclasses.dataclass
class Result:
    ok: bool
    states: int
    violations: List[Violation]
    properties: Dict[str, bool]

    def as_dict(self) -> dict:
        return {
            "ok": self.ok,
            "states": self.states,
            "properties": self.properties,
            "violations": [
                {"kind": v.kind, "detail": v.detail, "trace": v.trace}
                for v in self.violations
            ],
        }


def transitions(state: State, spec: Spec, script: Tuple[str, ...],
                capacity: int) -> Iterator[Tuple[str, State, Optional[str]]]:
    """Yield (label, next_state, error) for every enabled atomic step.

    `script` is the writer's message plan: 'ring' or 'inline' per
    message. `capacity` is the ring size in entries. `error` is a
    protocol-error description when the step lands in a violation state
    (the caller records it and stops exploring that branch).
    """
    n_msgs = len(script)
    (ring, wbuf, rbuf, waiting, sock, wphase, widx, rphase, delivered,
     held, inline_consumed) = state

    # -- store-buffer flushes (hardware, any time) -----------------------
    if wbuf:
        yield "w:flush", _flush_writer(state), None
    if rbuf is not None:
        yield "r:flush", _flush_reader(state), None

    # -- writer ----------------------------------------------------------
    if wphase == "space" and widx < n_msgs:
        kind = script[widx]
        if _writer_occupancy(state) < capacity:
            entry = ("F", widx) if kind == "ring" else ("M", widx)
            yield (
                f"w:publish[{widx}:{kind}]",
                _with(state, wbuf=wbuf + (entry,), wphase="waitcheck"),
                None,
            )
    elif wphase == "waitcheck":
        kind = script[widx]
        # Reads waiting from MEMORY (the reader's buffered store is
        # invisible — the fence-less half of the race).
        sees_waiting = waiting != 0 or not spec.coalesce_wakeups
        if kind == "ring":
            if sees_waiting:
                yield "w:bell", _with(state, wphase="bell"), None
            else:
                nxt = "space" if widx + 1 < n_msgs else "done"
                yield (
                    f"w:skip_bell[{widx}]",
                    _with(state, wphase=nxt, windex=widx + 1),
                    None,
                )
        else:
            yield (
                "w:inline_head",
                _with(state, wphase="bell_inline" if sees_waiting
                      else "inline_byte"),
                None,
            )
    elif wphase == "bell":
        # sendall(WAKE): syscall -> flush own buffer, then the byte.
        flushed = _flush_writer(state)
        nxt = "space" if widx + 1 < n_msgs else "done"
        yield (
            "w:send_wake",
            _with(flushed, sock=flushed[_SOCK] + ("W",), wphase=nxt,
                  windex=widx + 1),
            None,
        )
    elif wphase == "bell_inline":
        flushed = _flush_writer(state)
        yield (
            "w:send_wake",
            _with(flushed, sock=flushed[_SOCK] + ("W",),
                  wphase="inline_byte"),
            None,
        )
    elif wphase == "inline_byte":
        flushed = _flush_writer(state)
        yield (
            "w:send_inline_byte",
            _with(flushed, sock=flushed[_SOCK] + ("I",),
                  wphase="payload"),
            None,
        )
    elif wphase == "payload":
        flushed = _flush_writer(state)
        nxt = "space" if widx + 1 < n_msgs else "done"
        yield (
            f"w:send_payload[{widx}]",
            _with(flushed, sock=flushed[_SOCK] + (("P", widx),),
                  wphase=nxt, windex=widx + 1),
            None,
        )

    # -- reader ----------------------------------------------------------
    def deliver(st: State, entry, label: str):
        """Read the front entry: frame -> deliver; marker -> switch to
        the socket for the payload. The slot stays occupied until the
        NEXT recv (held)."""
        kind_e, msg_id = entry
        if msg_id != st[_DELIVERED]:
            return (
                label,
                st,
                f"FIFO violation: delivered message {msg_id} while "
                f"expecting {st[_DELIVERED]}",
            )
        base = _with(st, ring=st[_RING][1:], held=True)
        if kind_e == "F":
            done = base[_DELIVERED] + 1
            return (
                label + f" deliver[{msg_id}]",
                _with(base, delivered=done,
                      rphase="done" if done == n_msgs else "recv"),
                None,
            )
        return (label + f" marker[{msg_id}]",
                _with(base, rphase="inline_wait"), None)

    if rphase == "recv":
        seen = _reader_sees_ring(state)
        st = _with(state, held=False)  # release the previous slot
        if seen:
            yield deliver(st, seen[0], "r:read_frame")
        else:
            yield "r:arm_waiting", _with(st, rbuf=1, rphase="recheck"), None
    elif rphase == "recheck":
        seen = _reader_sees_ring(state)
        if spec.post_arm_recheck and seen:
            # Dekker half 2: the post-arm re-check. Clearing the flag is
            # another buffered store.
            yield deliver(_with(state, rbuf=0), seen[0],
                          "r:recheck_hit")
        else:
            # Enter the blocking recv: kernel entry flushes the waiting
            # store (it becomes visible no later than the block).
            yield ("r:block", _with(_flush_reader(state),
                                    rphase="blocked"), None)
    elif rphase == "blocked":
        if sock:
            byte, rest = sock[0], sock[1:]
            cleared = _with(state, sock=rest, rbuf=0)
            if byte == "W":
                yield "r:wake_byte", _with(cleared, rphase="recv"), None
            elif byte == "I":
                if not spec.inline_recovery:
                    yield (
                        "r:inline_byte_blocked",
                        _with(cleared, rphase="error"),
                        "protocol error: INLINE byte on a blocked "
                        "reader (stream teardown)",
                    )
                else:
                    seen = _reader_sees_ring(cleared)
                    if not seen:
                        yield (
                            "r:inline_byte_blocked",
                            _with(cleared, rphase="error"),
                            "inline byte with an empty ring (the "
                            "sender's syscall should have fenced the "
                            "marker in)",
                        )
                    else:
                        yield deliver(
                            _with(cleared, inline_consumed=True),
                            seen[0], "r:inline_recover",
                        )
            else:
                yield (
                    "r:payload_byte_blocked",
                    _with(cleared, rphase="error"),
                    "protocol error: payload byte read as doorbell",
                )
        elif spec.wake_recheck:
            # The bounded poll timeout: clear the flag, re-check.
            yield ("r:recheck_timeout",
                   _with(state, rbuf=0, rphase="recv"), None)
    elif rphase == "inline_wait":
        # Skip stale WAKEs up to the 0x02 (unless already consumed),
        # then the payload token delivers the message.
        if inline_consumed:
            if sock and sock[0][0] == "P":
                msg_id = sock[0][1]
                done = delivered + 1
                if msg_id != delivered:
                    yield (
                        "r:inline_payload",
                        state,
                        f"FIFO violation: inline payload {msg_id} while "
                        f"expecting {delivered}",
                    )
                else:
                    yield (
                        f"r:inline_payload[{msg_id}]",
                        _with(state, sock=sock[1:], delivered=done,
                              inline_consumed=False,
                              rphase="done" if done == n_msgs
                              else "recv"),
                        None,
                    )
        elif sock:
            byte, rest = sock[0], sock[1:]
            if byte == "W":
                yield ("r:skip_stale_wake",
                       _with(state, sock=rest), None)
            elif byte == "I":
                yield ("r:inline_byte",
                       _with(state, sock=rest, inline_consumed=True),
                       None)
            else:
                yield (
                    "r:payload_before_inline",
                    state,
                    "protocol error: payload byte before the INLINE "
                    "byte",
                )


def _is_success(state: State, n_msgs: int) -> bool:
    return (
        state[_WPHASE] == "done"
        and state[_RPHASE] == "done"
        and state[_DELIVERED] == n_msgs
    )


def check_protocol(spec: Spec = Spec(),
                   script: Tuple[str, ...] = ("ring", "ring", "inline",
                                              "ring"),
                   capacity: int = 2,
                   max_states: int = 2_000_000) -> Result:
    """Enumerate every interleaving; verify FIFO + error-freedom +
    no-wedge. Counterexamples carry the full transition-label trace
    from the initial state."""
    n_msgs = len(script)
    init = _initial(n_msgs)
    # BFS with predecessor tracking for trace reconstruction.
    parents: Dict[State, Optional[Tuple[State, str]]] = {init: None}
    order: List[State] = [init]
    violations: List[Violation] = []
    succ: Dict[State, List[State]] = {}
    i = 0
    while i < len(order):
        state = order[i]
        i += 1
        if len(parents) > max_states:
            raise RuntimeError(
                f"state space exceeded {max_states} states — shrink the "
                "script/capacity"
            )
        outs: List[State] = []
        for label, nxt, error in transitions(state, spec, script,
                                             capacity):
            if error is not None:
                kind = "fifo" if error.startswith("FIFO") else "error"
                violations.append(
                    Violation(kind, error,
                              _trace(parents, state) + [label]))
                continue
            outs.append(nxt)
            if nxt not in parents:
                parents[nxt] = (state, label)
                order.append(nxt)
        succ[state] = outs

    # No-wedge: backward reachability from success states.
    can_finish = {s for s in parents if _is_success(s, n_msgs)}
    changed = True
    while changed:
        changed = False
        for state, outs in succ.items():
            if state not in can_finish and any(
                o in can_finish for o in outs
            ):
                can_finish.add(state)
                changed = True
    wedged = [s for s in parents if s not in can_finish]
    if wedged:
        # Report the first wedged state in BFS order (shortest trace).
        first = min(wedged, key=lambda s: len(_trace(parents, s)))
        detail = (
            "wedged state: success unreachable "
            f"(writer={first[_WPHASE]}, reader={first[_RPHASE]}, "
            f"delivered={first[_DELIVERED]}/{n_msgs}, "
            f"ring={list(first[_RING])}, wbuf={list(first[_WBUF])}, "
            f"waiting={first[_WAITING]}, sock={list(first[_SOCK])})"
        )
        violations.append(Violation("wedge", detail,
                                    _trace(parents, first)))

    properties = {
        "fifo": not any(v.kind == "fifo" for v in violations),
        "error_free": not any(v.kind == "error" for v in violations),
        "no_wedge": not wedged,
        "success_reachable": bool(can_finish),
    }
    return Result(
        ok=all(properties.values()),
        states=len(parents),
        violations=violations,
        properties=properties,
    )


def _trace(parents, state: State) -> List[str]:
    labels: List[str] = []
    cur = state
    while parents.get(cur) is not None:
        prev, label = parents[cur]
        labels.append(label)
        cur = prev
    return list(reversed(labels))


def render_trace(violation: Violation) -> str:
    """The counterexample format the README documents: one numbered
    `actor:action` step per line, then the violated property."""
    lines = [
        f"  {i + 1:3d}. {step}" for i, step in enumerate(violation.trace)
    ]
    lines.append(f"  => {violation.kind.upper()}: {violation.detail}")
    return "\n".join(lines)


def verify_shipped_and_mutants(script=("ring", "ring", "inline", "ring"),
                               capacity: int = 2) -> dict:
    """The acceptance bundle (also `--check-protocol` in the CLI): the
    shipped spec must verify clean; every seeded mutation must produce
    a counterexample trace."""
    out: dict = {"script": list(script), "capacity": capacity}
    shipped = check_protocol(Spec(), script, capacity)
    out["shipped"] = shipped.as_dict()
    out["mutants"] = {}
    for name, spec in MUTATIONS.items():
        res = check_protocol(spec, script, capacity)
        out["mutants"][name] = res.as_dict()
    # The adaptive-timeout coverage argument (ISSUE 12) rides the
    # verdict: a config change that could park the bound at 0/infinite
    # (disabling the timeout transition the no-wedge proof needs) must
    # fail --check-protocol, not just drift.
    out["adaptive_recheck"] = {
        "initial_ms": RECHECK_MS,
        "min_ms": RECHECK_MIN_MS,
        "max_ms": RECHECK_MAX_MS,
        "window": RECHECK_WINDOW,
        "tighten_at": RECHECK_TIGHTEN,
        "relax_at": RECHECK_RELAX,
        "covered": adaptive_recheck_covered(),
    }
    out["ok"] = (
        shipped.ok
        and all(
            not m["ok"] and m["violations"]
            for m in out["mutants"].values()
        )
        and out["adaptive_recheck"]["covered"]
    )
    return out


def main() -> int:
    verdict = verify_shipped_and_mutants()
    print(json.dumps({
        "protocol": "shm-ring-doorbell",
        "ok": verdict["ok"],
        "shipped": verdict["shipped"]["properties"],
        "shipped_states": verdict["shipped"]["states"],
        "adaptive_recheck": verdict["adaptive_recheck"],
        "mutants": {
            name: {"found": bool(m["violations"]),
                   "kinds": sorted({v["kind"] for v in m["violations"]})}
            for name, m in verdict["mutants"].items()
        },
    }))
    if not verdict["ok"]:
        for name, m in verdict["mutants"].items():
            if m["ok"]:
                print(f"mutant {name}: NOT caught")
    else:
        # Show one counterexample per mutant (the README's documented
        # trace format).
        for name, m in verdict["mutants"].items():
            v = m["violations"][0]
            print(f"-- counterexample for mutant {name}:")
            print(render_trace(Violation(v["kind"], v["detail"],
                                         v["trace"])))
    return 0 if verdict["ok"] else 1
