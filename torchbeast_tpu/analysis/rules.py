"""beastlint per-file rules.

Each rule encodes one of this repo's real runtime contracts (see ISSUE 5 /
README "Static analysis"). Rules are deliberately conservative: they prefer
missing a violation over flagging correct code, because every finding fails
CI — escape hatches are the inline `# beastlint: disable=RULE  reason`
suppressions, not lax rules.
"""

import ast
from typing import Dict, List, Optional, Set

from . import config
from .engine import FileContext, Finding

# Names whose attribute chains indicate device/traced values. `lax` is
# included because `from jax import lax` is the repo idiom.
_DEVICE_ROOTS = {"jax", "jnp", "lax"}


def _root_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name of an Attribute/Call/Subscript chain."""
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, (ast.Call, ast.Subscript)):
            node = node.func if isinstance(node, ast.Call) else node.value
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return None


def _attr_chain(node: ast.AST) -> str:
    """Dotted text of a Name/Attribute chain ('' when not a plain chain)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _iter_defs(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class HotpathSyncRule:
    """HOTPATH-SYNC: implicit device->host syncs in annotated hot paths.

    Hot regions are functions annotated `# beastlint: hot` (or every
    function of a `# beastlint: hot-module` module). Within one:

    - `.item()` forces a device sync — always flagged (numpy `.item()` in
      a hot path is at best a refactor away from a device array).
    - `float()/int()/bool()/np.asarray()/np.array()` on a DEVICE-TAINTED
      value: a name assigned (in the same function) from a jax/jnp/lax
      expression, or derived from one. Host-only conversions (wire codec
      scalars, shapes) never taint, so hot-annotating a pure-host module
      is free.
    - `print()` — stdout in a per-step path is either a device-array
      print (a sync) or hot-loop IO; both belong in telemetry.

    Explicit syncs (`jax.device_get`, `np.asarray` on host data) pass:
    the contract bans *implicit* syncs, not data movement.
    """

    name = "HOTPATH-SYNC"

    def check(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        hot_defs = []
        for node in _iter_defs(ctx.tree):
            if ctx.is_hot_def(node):
                hot_defs.append(node)
        # Nested defs of a hot def are hot too; analyze each hot def as
        # one region (its own taint scope) and skip nested re-analysis.
        seen: Set[int] = set()
        for node in hot_defs:
            if id(node) in seen:
                continue
            for sub in ast.walk(node):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    seen.add(id(sub))
            findings.extend(self._check_region(ctx, node))
        return findings

    def _check_region(self, ctx: FileContext, fn: ast.AST) -> List[Finding]:
        tainted = self._taint(fn)
        out: List[Finding] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr == "item" and (
                not node.args and not node.keywords
            ):
                out.append(
                    Finding(
                        self.name, ctx.path, node.lineno,
                        f"`.item()` on `{_attr_chain(func.value) or '<expr>'}`"
                        " forces a device->host sync in a hot path",
                    )
                )
                continue
            if isinstance(func, ast.Name) and func.id == "print":
                out.append(
                    Finding(
                        self.name, ctx.path, node.lineno,
                        "print() in a hot path (device-array prints sync; "
                        "use telemetry counters/histograms)",
                    )
                )
                continue
            target = None
            if (
                isinstance(func, ast.Name)
                and func.id in ("float", "int", "bool")
                and len(node.args) == 1
            ):
                target = node.args[0]
                desc = f"{func.id}()"
            elif (
                isinstance(func, ast.Attribute)
                and func.attr in ("asarray", "array")
                and _root_name(func) in ("np", "numpy")
                and node.args
            ):
                target = node.args[0]
                desc = f"np.{func.attr}()"
            if target is not None and self._is_device(target, tainted):
                out.append(
                    Finding(
                        self.name, ctx.path, node.lineno,
                        f"{desc} on device value "
                        f"`{_attr_chain(target) or ast.dump(target)[:40]}` "
                        "is an implicit device->host sync in a hot path "
                        "(use an explicit jax.device_get at a fetch "
                        "boundary)",
                    )
                )
        return out

    def _taint(self, fn: ast.AST) -> Set[str]:
        """Names assigned from jax/jnp/lax-rooted expressions, with
        propagation through derived assignments (two fixpoint passes:
        enough for straight-line and one level of forward reference)."""
        tainted: Set[str] = set()
        for _ in range(2):
            before = len(tainted)
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    targets = node.targets
                    value = node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets = [node.target]
                    value = node.value
                else:
                    continue
                if self._is_device(value, tainted):
                    for t in targets:
                        for name_node in ast.walk(t):
                            if isinstance(name_node, ast.Name):
                                tainted.add(name_node.id)
            if len(tainted) == before:
                break
        return tainted

    # jax.* namespaces that do HOST work and calls that RETURN host
    # values regardless of their (device) arguments — shared with the
    # interprocedural rule via config (one contract, two analyses).
    _HOST_JAX_NAMESPACES = frozenset(config.HOST_JAX_NAMESPACES)
    _HOST_RETURNING_CALLS = frozenset(config.HOST_RETURNING_CALLS)

    def _is_device(self, expr: ast.AST, tainted: Set[str]) -> bool:
        node = expr
        if isinstance(node, ast.Call):
            if _attr_chain(node.func) in self._HOST_RETURNING_CALLS:
                return False  # prune: host result, args don't leak out
        if isinstance(node, ast.Name) and node.id in tainted:
            return True
        if isinstance(node, ast.Attribute):
            chain = _attr_chain(node)
            parts = chain.split(".") if chain else []
            if parts:
                if parts[0] in ("jnp", "lax"):
                    return True
                if parts[0] == "jax" and len(parts) > 1 and (
                    parts[1] not in self._HOST_JAX_NAMESPACES
                ):
                    return True
        return any(
            self._is_device(child, tainted)
            for child in ast.iter_child_nodes(node)
        )


def _is_jit_ctor(node: ast.Call, jax_imports: Set[str]) -> Optional[str]:
    """'jit'/'pmap'/'scan' when `node` constructs/launches compiled code."""
    func = node.func
    if isinstance(func, ast.Attribute):
        chain = _attr_chain(func)
        if chain in ("jax.jit", "jax.pmap"):
            return func.attr
        if chain in ("lax.scan", "jax.lax.scan"):
            return "scan"
        return None
    if isinstance(func, ast.Name) and func.id in jax_imports:
        return func.id
    return None


class JitHazardRule:
    """JIT-HAZARD: recompilation traps around jax.jit / lax.scan.

    - jit/pmap/scan constructed inside a `for`/`while` body: each
      iteration builds a fresh traced callable => a fresh compile cache
      entry => recompilation every pass.
    - Immediately-invoked `jax.jit(f)(x)`: the wrapper (and its cache)
      dies with the statement, so every execution recompiles.
    - `static_argnums`/`static_argnames` pointing at a parameter whose
      default is an unhashable literal (list/dict/set): hashing the
      static arg raises at call time.
    """

    name = "JIT-HAZARD"

    def check(self, ctx: FileContext) -> List[Finding]:
        jax_imports: Set[str] = set()
        module_defs: Dict[str, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module and (
                node.module == "jax" or node.module.startswith("jax.")
            ):
                for alias in node.names:
                    if alias.name in ("jit", "pmap"):
                        jax_imports.add(alias.asname or alias.name)
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                module_defs[node.name] = node
        findings: List[Finding] = []
        self._walk(ctx, ctx.tree, 0, jax_imports, module_defs, findings)
        return findings

    def _walk(self, ctx, node, loop_depth, jax_imports, module_defs,
              findings) -> None:
        for child in ast.iter_child_nodes(node):
            depth = loop_depth
            if isinstance(child, (ast.For, ast.While, ast.AsyncFor)):
                depth += 1
            if isinstance(child, ast.Call):
                kind = _is_jit_ctor(child, jax_imports)
                if kind is not None:
                    if loop_depth > 0:
                        findings.append(
                            Finding(
                                self.name, ctx.path, child.lineno,
                                f"{kind} constructed inside a loop: every "
                                "iteration traces and compiles afresh "
                                "(hoist the construction out of the loop)",
                            )
                        )
                    self._check_static_args(
                        ctx, child, module_defs, findings
                    )
                # jax.jit(f)(...) — wrapper discarded after one call.
                inner = child.func
                if isinstance(inner, ast.Call):
                    ikind = _is_jit_ctor(inner, jax_imports)
                    if ikind in ("jit", "pmap"):
                        findings.append(
                            Finding(
                                self.name, ctx.path, child.lineno,
                                f"immediately-invoked jax.{ikind}(...)(...):"
                                " the compiled wrapper (and its cache) is "
                                "discarded after this call — bind it once",
                            )
                        )
            self._walk(ctx, child, depth, jax_imports, module_defs, findings)

    def _check_static_args(self, ctx, call, module_defs, findings) -> None:
        static_nums: List[int] = []
        static_names: List[str] = []
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                static_nums = self._int_elts(kw.value)
            elif kw.arg == "static_argnames":
                static_names = self._str_elts(kw.value)
        if not static_nums and not static_names:
            return
        if not call.args or not isinstance(call.args[0], ast.Name):
            return
        fn = module_defs.get(call.args[0].id)
        if fn is None:
            return
        args = fn.args.args
        defaults = fn.args.defaults
        default_by_name: Dict[str, ast.AST] = {}
        for arg, default in zip(args[len(args) - len(defaults):], defaults):
            default_by_name[arg.arg] = default
        suspects = list(static_names) + [
            a.arg for i, a in enumerate(args) if i in static_nums
        ]
        for pname in suspects:
            default = default_by_name.get(pname)
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                findings.append(
                    Finding(
                        self.name, ctx.path, call.lineno,
                        f"static arg {pname!r} of {call.args[0].id!r} "
                        "defaults to an unhashable "
                        f"{type(default).__name__.lower()} literal — "
                        "jit static args must be hashable",
                    )
                )

    @staticmethod
    def _int_elts(node: ast.AST) -> List[int]:
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            return [
                e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)
            ]
        return []

    @staticmethod
    def _str_elts(node: ast.AST) -> List[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            return [
                e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
        return []


class DonateUseRule:
    """DONATE-USE: reads of a consumed (host-deleted) staging binding.

    The consume-once donation contract (learner.consume_staged_inputs,
    PR 4): a staged device pytree is `.delete()`d at dispatch; touching
    it afterwards raises "Array has been deleted" at runtime — this rule
    moves that failure to lint time. Consumption events:

    - `x.delete()` consumes `x`.
    - calling a name bound from `consume_staged_inputs(...)` (or a
      `make_*_update_step/superstep(..., donate_batch=True)` factory)
      consumes its batch/state arguments (positions 2+, matching
      `wrapped(params, opt_state, batch, initial_agent_state)`).

    Any later read of a consumed name — along ANY branch — flags, until
    the name is rebound. Loop bodies get a second pass seeded with the
    end-of-body consumed set, so a back-edge read-after-delete is caught
    while `x.delete(); x = next(...)` rebinding stays clean.
    """

    name = "DONATE-USE"

    _CONSUMER_FACTORIES = {"consume_staged_inputs"}
    _DONATING_FACTORIES = {
        "make_update_superstep",
        "make_parallel_update_step",
    }

    def check(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for fn in _iter_defs(ctx.tree):
            consumers = self._consumer_names(fn)
            state: Dict[str, int] = {}
            dedupe: Set = set()
            self._scan(ctx, fn.body, state, consumers, findings, dedupe)
        return findings

    def _consumer_names(self, fn: ast.AST) -> Set[str]:
        """Local names bound to a consuming update callable."""
        out: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            callee = value.func
            fname = (
                callee.id if isinstance(callee, ast.Name)
                else callee.attr if isinstance(callee, ast.Attribute)
                else ""
            )
            consuming = fname in self._CONSUMER_FACTORIES
            if fname in self._DONATING_FACTORIES:
                for kw in value.keywords:
                    if kw.arg == "donate_batch" and isinstance(
                        kw.value, ast.Constant
                    ) and kw.value.value is True:
                        consuming = True
            if consuming:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    # -- statement interpreter ---------------------------------------------

    def _scan(self, ctx, stmts, consumed, consumers, findings, dedupe):
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                self._expr(ctx, stmt.test, consumed, consumers, findings,
                           dedupe)
                branch_a = dict(consumed)
                branch_b = dict(consumed)
                self._scan(ctx, stmt.body, branch_a, consumers, findings,
                           dedupe)
                self._scan(ctx, stmt.orelse, branch_b, consumers, findings,
                           dedupe)
                consumed.clear()
                consumed.update(branch_b)
                consumed.update(branch_a)  # any-path union
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                if isinstance(stmt, ast.While):
                    self._expr(ctx, stmt.test, consumed, consumers,
                               findings, dedupe)
                else:
                    self._expr(ctx, stmt.iter, consumed, consumers,
                               findings, dedupe)
                    self._unbind(stmt.target, consumed)
                before = dict(consumed)
                self._scan(ctx, stmt.body, consumed, consumers, findings,
                           dedupe)
                if consumed.keys() - before.keys():
                    # Back-edge pass: reads at the loop top see the
                    # previous iteration's consumptions — but a for
                    # target is rebound by the iteration itself, so it
                    # re-enters the body clean.
                    back = dict(consumed)
                    if isinstance(stmt, (ast.For, ast.AsyncFor)):
                        self._unbind(stmt.target, back)
                    self._scan(ctx, stmt.body, back, consumers, findings,
                               dedupe)
                self._scan(ctx, stmt.orelse, consumed, consumers, findings,
                           dedupe)
            elif isinstance(stmt, ast.Try):
                body_state = dict(consumed)
                self._scan(ctx, stmt.body, body_state, consumers, findings,
                           dedupe)
                merged = dict(body_state)
                for handler in stmt.handlers:
                    h_state = dict(consumed)
                    h_state.update(body_state)
                    self._scan(ctx, handler.body, h_state, consumers,
                               findings, dedupe)
                    merged.update(h_state)
                self._scan(ctx, stmt.orelse, merged, consumers, findings,
                           dedupe)
                self._scan(ctx, stmt.finalbody, merged, consumers, findings,
                           dedupe)
                consumed.clear()
                consumed.update(merged)
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    self._expr(ctx, item.context_expr, consumed, consumers,
                               findings, dedupe)
                    if item.optional_vars is not None:
                        self._unbind(item.optional_vars, consumed)
                self._scan(ctx, stmt.body, consumed, consumers, findings,
                           dedupe)
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = getattr(stmt, "value", None)
                if value is not None:
                    self._expr(ctx, value, consumed, consumers, findings,
                               dedupe)
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                for t in targets:
                    self._unbind(t, consumed)
            elif isinstance(stmt, (ast.Expr, ast.Return, ast.Raise,
                                   ast.Assert, ast.Delete)):
                for value in ast.iter_child_nodes(stmt):
                    self._expr(ctx, value, consumed, consumers, findings,
                               dedupe)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue  # nested scopes analyzed separately
            else:
                for value in ast.iter_child_nodes(stmt):
                    if isinstance(value, ast.expr):
                        self._expr(ctx, value, consumed, consumers,
                                   findings, dedupe)

    @staticmethod
    def _unbind(target: ast.AST, consumed: Dict[str, int]) -> None:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                consumed.pop(node.id, None)

    def _expr(self, ctx, expr, consumed, consumers, findings, dedupe):
        if expr is None or not isinstance(expr, ast.AST):
            return
        consuming_now: List[str] = []
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr == "delete"
                    and isinstance(func.value, ast.Name)
                ):
                    consuming_now.append(func.value.id)
                elif isinstance(func, ast.Name) and func.id in consumers:
                    for arg in node.args[2:]:
                        if isinstance(arg, ast.Name):
                            consuming_now.append(arg.id)
        # Flag reads BEFORE registering this statement's consumptions
        # (the consuming call's own argument read is legal).
        skip = set(consuming_now)
        for node in ast.walk(expr):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in consumed
                and node.id not in skip
            ):
                key = (node.id, node.lineno)
                if key not in dedupe:
                    dedupe.add(key)
                    findings.append(
                        Finding(
                            self.name, ctx.path, node.lineno,
                            f"`{node.id}` read after being consumed/"
                            f"deleted at line {consumed[node.id]} "
                            "(consume-once donation: the device buffer "
                            "is gone)",
                        )
                    )
        for name in consuming_now:
            consumed[name] = expr.lineno if hasattr(expr, "lineno") else 0


class ImportPurityRule:
    """IMPORT-PURITY: per-package import allowlists (config.PURITY).

    `telemetry/` must stay stdlib-only so instrumentation can never add a
    device sync to a hot path (this rule replaces the hand-rolled
    source-pin test from PR 2); `analysis/` itself is held to the same
    bar so the linter runs without the runtime's dependencies.
    """

    name = "IMPORT-PURITY"

    def check(self, ctx: FileContext) -> List[Finding]:
        denied = None
        for prefix, mods in config.PURITY.items():
            if ctx.path.startswith(prefix + "/") or ctx.path == prefix:
                denied = set(mods)
                break
        if denied is None:
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            names: List[str] = []
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                names = [node.module or ""]
            elif isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                if chain in ("importlib.import_module", "__import__") and (
                    node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    names = [node.args[0].value]
            for mod in names:
                top = mod.split(".")[0]
                if top in denied:
                    findings.append(
                        Finding(
                            self.name, ctx.path, node.lineno,
                            f"import of {top!r} violates the "
                            "declared purity contract for this package "
                            "(see analysis/config.py PURITY)",
                        )
                    )
        return findings


class LockDisciplineRule:
    """LOCK-DISCIPLINE: `# guarded-by: self._lock` annotations.

    An attribute annotated guarded-by may only be loaded/stored inside a
    `with` on the named lock — or a Condition constructed FROM that lock
    (holding `self._not_empty` built as `Condition(self._lock)` holds
    `self._lock`). `__init__` is exempt (no concurrent readers exist yet);
    helper methods documented `# beastlint: holds self._lock` start with
    the lock held. Separately, a bare `.acquire()` whose very next
    statement is not `try/.../finally: .release()` flags everywhere —
    an exception between acquire and release deadlocks the process.
    """

    name = "LOCK-DISCIPLINE"

    def check(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                self._check_class(ctx, node, findings)
        self._check_bare_acquire(ctx, ctx.tree, findings)
        return findings

    # -- guarded attributes -------------------------------------------------

    def _check_class(self, ctx, cls, findings) -> None:
        guarded: Dict[str, str] = {}  # attr -> lock attr name
        acquires: Dict[str, Set[str]] = {}  # with-target attr -> held attrs
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                target = node.targets[0] if node.targets else None
            elif isinstance(node, ast.AnnAssign):
                target = node.target  # self._x: Dict[...] = {} form
            else:
                continue
            if not (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                continue
            attr = target.attr
            # Trailing on the assignment line, or STANDALONE just above
            # (a trailing comment on the previous statement must not
            # leak onto this one).
            annotation = ctx.guarded_annotations.get(node.lineno)
            if annotation is None and ctx.comment_only(node.lineno - 1):
                annotation = ctx.guarded_annotations.get(node.lineno - 1)
            if annotation is not None:
                lock_attr = annotation.split(".")[-1]
                guarded[attr] = lock_attr
            value = node.value
            if value is not None and isinstance(value, ast.Call):
                chain = _attr_chain(value.func)
                base = chain.split(".")[-1]
                if base in ("Lock", "RLock"):
                    acquires[attr] = {attr}
                elif base == "Condition":
                    held = {attr}
                    if value.args:
                        inner = value.args[0]
                        if (
                            isinstance(inner, ast.Attribute)
                            and isinstance(inner.value, ast.Name)
                            and inner.value.id == "self"
                        ):
                            held.add(inner.attr)
                        elif isinstance(inner, ast.Call):
                            pass  # Condition(Lock()): private lock
                    acquires[attr] = held
        # A lock/condition attribute is never itself "guarded": touching
        # it IS how you acquire it.
        for lock_attr in acquires:
            guarded.pop(lock_attr, None)
        if not guarded:
            return
        for method in cls.body:
            if not isinstance(
                method, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if method.name == "__init__":
                continue
            held: Set[str] = set()
            holds = ctx.holds_annotation(method)
            if holds:
                attr = holds.split(".")[-1]
                held |= acquires.get(attr, {attr})
            self._walk_method(
                ctx, method.body, guarded, acquires, set(held), findings
            )

    def _walk_method(self, ctx, stmts, guarded, acquires, held,
                     findings) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                new_held = set(held)
                for item in stmt.items:
                    expr = item.context_expr
                    if (
                        isinstance(expr, ast.Attribute)
                        and isinstance(expr.value, ast.Name)
                        and expr.value.id == "self"
                    ):
                        new_held |= acquires.get(expr.attr, {expr.attr})
                    self._check_exprs(
                        ctx, [expr], guarded, held, findings
                    )
                self._walk_method(
                    ctx, stmt.body, guarded, acquires, new_held, findings
                )
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Nested def: conservatively analyzed with the CURRENT
                # held set (closures usually run synchronously under the
                # enclosing with; a deferred closure needs a suppression).
                self._walk_method(
                    ctx, stmt.body, guarded, acquires, set(held), findings
                )
            else:
                # Generic compound statements: recurse into statement
                # lists (incl. except-handler bodies) as STATEMENTS so
                # nested `with` blocks keep their held-lock semantics;
                # everything else is checked as an expression.
                for _, value in ast.iter_fields(stmt):
                    if isinstance(value, list) and value:
                        if isinstance(value[0], ast.stmt):
                            self._walk_method(
                                ctx, value, guarded, acquires, held,
                                findings,
                            )
                        elif isinstance(value[0], ast.excepthandler):
                            for handler in value:
                                if handler.type is not None:
                                    self._check_exprs(
                                        ctx, [handler.type], guarded,
                                        held, findings,
                                    )
                                self._walk_method(
                                    ctx, handler.body, guarded, acquires,
                                    held, findings,
                                )
                        else:
                            self._check_exprs(
                                ctx,
                                [v for v in value
                                 if isinstance(v, ast.expr)],
                                guarded, held, findings,
                            )
                    elif isinstance(value, ast.expr):
                        self._check_exprs(
                            ctx, [value], guarded, held, findings
                        )

    def _check_exprs(self, ctx, exprs, guarded, held, findings) -> None:
        for expr in exprs:
            for node in ast.walk(expr):
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in guarded
                ):
                    lock = guarded[node.attr]
                    if lock not in held:
                        findings.append(
                            Finding(
                                self.name, ctx.path, node.lineno,
                                f"`self.{node.attr}` is guarded-by "
                                f"`self.{lock}` but accessed without "
                                "holding it",
                            )
                        )

    # -- bare acquire -------------------------------------------------------

    def _check_bare_acquire(self, ctx, tree, findings) -> None:
        for node in ast.walk(tree):
            body = getattr(node, "body", None)
            if not isinstance(body, list):
                continue
            for seq_name in ("body", "orelse", "finalbody"):
                seq = getattr(node, seq_name, None)
                if not isinstance(seq, list):
                    continue
                for i, stmt in enumerate(seq):
                    receiver = self._acquire_receiver(stmt)
                    if receiver is None:
                        continue
                    nxt = seq[i + 1] if i + 1 < len(seq) else None
                    if self._is_release_try(nxt, receiver):
                        continue
                    findings.append(
                        Finding(
                            self.name, ctx.path, stmt.lineno,
                            f"bare `{receiver}.acquire()` not immediately "
                            "followed by try/finally release — an "
                            "exception here leaks the lock (prefer "
                            "`with`)",
                        )
                    )

    @staticmethod
    def _acquire_receiver(stmt: ast.AST) -> Optional[str]:
        if not isinstance(stmt, ast.Expr):
            return None
        call = stmt.value
        if not (
            isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr == "acquire"
        ):
            return None
        return _attr_chain(call.func.value) or None

    @staticmethod
    def _is_release_try(stmt: Optional[ast.AST], receiver: str) -> bool:
        if not isinstance(stmt, ast.Try) or not stmt.finalbody:
            return False
        for node in ast.walk(ast.Module(body=stmt.finalbody,
                                        type_ignores=[])):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "release"
                and _attr_chain(node.func.value) == receiver
            ):
                return True
        return False


class ExceptSwallowRule:
    """EXCEPT-SWALLOW: broad exception handlers that hide failures.

    On the pipeline's failure-handling paths (config.EXCEPT_SWALLOW_PATHS:
    runtime/ and resilience/), a `except:` / `except Exception:` /
    `except BaseException:` body must do at least one of:

    - re-raise (`raise`),
    - log (any `*.debug/info/warning/error/exception/critical/log` call —
      `log.exception` is the idiom),
    - count it (a telemetry `.inc()`/`.observe()`), or
    - surface it to the waiting producer (`.fail(e)` on a batch promise).

    A broad handler that silently `pass`es or returns a default is how a
    DEGRADED pipeline hides: the chaos machinery (ISSUE 6) can only
    assert recovery == injected when every absorbed failure leaves a
    trace. Narrow handlers (`except OSError:` teardown guards) stay out
    of scope — the contract targets the catch-alls that can absorb
    *anything*.
    """

    name = "EXCEPT-SWALLOW"

    _BROAD = {"Exception", "BaseException"}
    _LOG_METHODS = {
        "debug", "info", "warning", "error", "exception", "critical",
        "log",
    }
    _ACCOUNT_METHODS = {"inc", "observe", "fail"}

    def check(self, ctx: FileContext) -> List[Finding]:
        if not any(
            ctx.path.startswith(prefix + "/") or ctx.path == prefix
            for prefix in config.EXCEPT_SWALLOW_PATHS
        ):
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            spec = self._broad_spec(node.type)
            if spec is None:
                continue
            if self._accounts_for_failure(node.body):
                continue
            findings.append(
                Finding(
                    self.name, ctx.path, node.lineno,
                    f"`{spec}` body neither re-raises, logs, "
                    "nor counts the failure (silent swallows are how "
                    "degraded pipelines hide)",
                )
            )
        return findings

    def _broad_spec(self, type_node) -> Optional[str]:
        """The handler's spec text when it is broad, else None."""
        if type_node is None:
            return "except:"  # bare
        names = []
        if isinstance(type_node, ast.Tuple):
            names = [_attr_chain(e).rsplit(".", 1)[-1]
                     for e in type_node.elts]
        else:
            chain = _attr_chain(type_node)
            if chain:
                names = [chain.rsplit(".", 1)[-1]]
        for name in names:
            if name in self._BROAD:
                return f"except {name}:"
        return None

    def _accounts_for_failure(self, body) -> bool:
        """True when the handler body raises/logs/counts. Nested
        function/lambda bodies are SKIPPED (they don't execute as part
        of handling). Known conservatism: a raise or log inside a
        nested try's own handler credits the outer one even though it
        only covers that inner exception class — acceptable, since
        partial surfacing exists and the rule prefers missing a
        violation over flagging correct code."""
        stack = list(body)
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                attr = node.func.attr
                if (
                    attr in self._LOG_METHODS
                    or attr in self._ACCOUNT_METHODS
                ):
                    return True
            stack.extend(ast.iter_child_nodes(node))
        return False


# ---------------------------------------------------------------------------
# Whole-program concurrency rules (ISSUE 7). These are REPO rules: they
# run over every scanned context at once, sharing one Program model
# (analysis/graph.py) via its single-entry cache.


def _concurrency_scope(contexts):
    return [
        ctx for ctx in contexts
        if any(
            ctx.path.startswith(prefix + "/") or ctx.path == prefix
            for prefix in config.CONCURRENCY_PATHS
        )
    ]


def _root_label(prog, root_id: str) -> str:
    root = prog.roots.get(root_id)
    if root is None:
        return root_id
    label = root_id
    if root.multi:
        label += " [xN]"
    return label


class RaceRule:
    """RACE: cross-thread-root attribute conflicts with no common lock.

    For every `self.<attr>` (and typed-local attr / declared module
    global) the program graph maps each access to the thread roots that
    can reach it and the lock set lexically held there. A location
    written from one root and read/written from another — or written
    from a multi-instance root (a spawn site inside a loop/comprehension
    runs N copies of the same body against shared state) — must have at
    least one lock held at EVERY conflicting access. Guards are INFERRED
    from observed `with self._lock:` dominance; `# guarded-by`
    annotations become cross-checked assertions (the rule reports when
    the annotated lock is not what the conflicting paths actually hold).

    Conservatism: construction (`__init__`) accesses are exempt (no
    concurrent readers exist yet), attributes never written outside
    `__init__` are immutable-after-construction, writes in the method
    that spawns a root are ordered by `Thread.start()` against that
    root, and anything the call graph cannot resolve is silence, not a
    guess. Benign races are suppressed inline with the interleaving
    described: `# beastlint: disable=RACE  <why the interleaving is
    safe>`.
    """

    name = "RACE"

    def check_repo(self, root: str, contexts) -> List[Finding]:
        from . import graph as graph_mod

        scoped = _concurrency_scope(contexts)
        if not scoped:
            return []
        prog = graph_mod.get_program(scoped)
        shared_owners = self._shared_owners(prog)
        groups: Dict = {}
        for acc in prog.accesses:
            if acc.in_init:
                continue
            if acc.owner not in shared_owners:
                continue
            groups.setdefault((acc.owner, acc.attr), []).append(acc)
        findings: List[Finding] = []
        for (owner, attr), accs in sorted(groups.items()):
            if not any(a.kind == "write" for a in accs):
                continue  # immutable after construction
            per_root: Dict[str, List] = {}
            for a in accs:
                for r in prog.func_roots.get(a.func, ()):
                    per_root.setdefault(r, []).append(a)
            involved_ids: Dict[int, object] = {}
            involved_roots = set()
            roots_list = sorted(per_root)
            for ra in roots_list:
                a_accs = per_root[ra]
                for rb in roots_list:
                    if rb == ra:
                        continue
                    writes_a = [
                        a for a in a_accs
                        if a.kind == "write"
                        and not self._spawn_ordered(prog, a, ra, rb)
                    ]
                    if not writes_a:
                        continue
                    accs_b = [
                        b for b in per_root[rb]
                        if not self._spawn_ordered(prog, b, ra, rb)
                    ]
                    if not accs_b:
                        continue
                    involved_roots |= {ra, rb}
                    for a in writes_a + accs_b:
                        involved_ids[id(a)] = a
                # Multi-instance root: N copies of the same body run
                # against shared state — it conflicts with itself.
                if prog.roots[ra].multi:
                    own = [
                        a for a in a_accs
                        if not self._spawn_ordered(prog, a, ra, ra)
                    ]
                    if self._self_conflict(own):
                        involved_roots.add(ra)
                        for a in own:
                            involved_ids[id(a)] = a
            if not involved_roots:
                continue
            involved = list(involved_ids.values())
            common = frozenset.intersection(
                *[a.held for a in involved]
            ) if involved else frozenset()
            if common:
                continue  # a lock every conflicting access holds
            findings.append(
                self._finding(prog, owner, attr, involved, per_root,
                              involved_roots)
            )
        return findings

    @staticmethod
    def _shared_owners(prog) -> set:
        """Classes whose instances are actually thread-shared: they own
        a lock (you lock because you share) or one of their methods is a
        thread-root body (the instance spans spawner and thread).
        Everything else — per-connection codecs, per-run writers — is
        single-owner by construction and exempt. Declared module globals
        are always in scope."""
        root_funcs = {r.func for r in prog.roots.values()}
        out = set()
        for qual, cls in prog.classes.items():
            if cls.lock_attrs:
                out.add(qual)
            elif any(m.qual in root_funcs for m in cls.methods.values()):
                out.add(qual)
        out |= {
            acc.owner for acc in prog.accesses
            if acc.owner.startswith("<module>")
        }
        return out

    @staticmethod
    def _spawn_ordered(prog, access, ra: str, rb: str) -> bool:
        """True when `access` is ordered against the conflict pair by
        `Thread.start()`: it sits in the method that spawns root ra or
        rb, before that method's first `.start()` call."""
        for r in (ra, rb):
            info = prog.roots[r]
            if info.spawn_func is None or access.func != info.spawn_func:
                continue
            first_start = prog.start_lines.get(info.spawn_func)
            if first_start is not None and access.line < first_start:
                return True
        return False

    @staticmethod
    def _self_conflict(r_accs) -> bool:
        """Within ONE multi-instance root: a read-modify-write, a write
        plus a read at another line, or writes at two lines conflict."""
        writes = [a for a in r_accs if a.kind == "write"]
        if not writes:
            return False
        reads = [a for a in r_accs if a.kind == "read"]
        if any(getattr(a, "rmw", False) for a in writes):
            return True
        write_lines = {(a.path, a.line) for a in writes}
        if len(write_lines) > 1:
            return True
        return any(
            (a.path, a.line) not in write_lines for a in reads
        )

    def _finding(self, prog, owner, attr, involved, per_root,
                 involved_roots) -> Finding:
        # Majority lock (if any) names the inferred guard; the anchor is
        # the first conflicting write that does not hold it.
        lock_votes: Dict[str, int] = {}
        for a in involved:
            for lock in a.held:
                lock_votes[lock] = lock_votes.get(lock, 0) + 1
        candidate = max(lock_votes, key=lock_votes.get) if lock_votes else None
        unguarded = [
            a for a in involved
            if candidate is None or candidate not in a.held
        ] or involved
        unguarded.sort(key=lambda a: (a.path, a.line))
        anchor = next(
            (a for a in unguarded if a.kind == "write"), unguarded[0]
        )
        other = next(
            (
                a for a in sorted(involved, key=lambda x: (x.path, x.line))
                if (a.path, a.line) != (anchor.path, anchor.line)
            ),
            anchor,
        )
        roots_text = ", ".join(
            sorted(_root_label(prog, r) for r in involved_roots)[:3]
        )
        attr_text = (
            f"`{attr}`" if owner.startswith("<module>")
            else f"`self.{attr}` ({owner.split('::')[-1]})"
        )
        cls = prog.classes.get(owner)
        annotated = cls.guarded.get(attr) if cls is not None else None
        if annotated is not None:
            return Finding(
                self.name, anchor.path, anchor.line,
                f"annotation claims `self.{annotated}` guards "
                f"{attr_text}, but it is not held on the path through "
                f"{anchor.func.split('::')[-1]} (roots: {roots_text}; "
                f"counterpart at {other.path}:{other.line})",
            )
        if candidate is not None:
            guard_text = (
                f"`{candidate.split('::')[-1].split('.')[-1]}` guards "
                f"{lock_votes[candidate]}/{len(involved)} conflicting "
                "accesses but not this one"
            )
        else:
            guard_text = "no lock is held at any conflicting access"
        return Finding(
            self.name, anchor.path, anchor.line,
            f"{attr_text} is {anchor.kind[:-1]}ten from roots "
            f"{roots_text} with no common lock — {guard_text} "
            f"(counterpart access at {other.path}:{other.line})"
            if anchor.kind == "write" else
            f"{attr_text} is accessed from roots {roots_text} with no "
            f"common lock — {guard_text} (counterpart at "
            f"{other.path}:{other.line})",
        )


class LockOrderRule:
    """LOCK-ORDER: lock-acquisition ordering cycles across thread roots.

    The program graph records every acquisition edge `A -> B` (lock B
    acquired — lexically or anywhere inside a callee, via per-function
    transitive-acquire summaries — while A is held). A cycle in the
    merged graph means two roots can take the same locks in opposite
    orders: a potential deadlock. Re-acquiring a non-reentrant lock
    already held on the path (directly, or by calling a helper that
    takes it) is a guaranteed self-deadlock and flags on its own.
    """

    name = "LOCK-ORDER"

    def check_repo(self, root: str, contexts) -> List[Finding]:
        from . import graph as graph_mod

        scoped = _concurrency_scope(contexts)
        if not scoped:
            return []
        prog = graph_mod.get_program(scoped)
        trans = graph_mod.transitive_acquires(prog)
        # (a, b) -> (path, line, func, via)
        edges: Dict = {}
        findings: List[Finding] = []
        for e in prog.lock_edges:
            if e.held == e.acquired:
                if e.held not in prog.reentrant_ids:
                    findings.append(
                        Finding(
                            self.name, e.path, e.line,
                            f"`{_short_lock(e.held)}` acquired while "
                            "already held on this path — non-reentrant "
                            "lock, guaranteed self-deadlock",
                        )
                    )
                continue
            edges.setdefault((e.held, e.acquired),
                             (e.path, e.line, e.func, e.via))
        for caller, callee, path, line, held in prog.call_sites:
            for h in held:
                for a in trans.get(callee, ()):
                    if a == h:
                        if h not in prog.reentrant_ids:
                            findings.append(
                                Finding(
                                    self.name, path, line,
                                    f"`{_short_lock(h)}` is held here "
                                    f"and re-acquired inside "
                                    f"{callee.split('::')[-1]}() — "
                                    "non-reentrant lock, guaranteed "
                                    "self-deadlock",
                                )
                            )
                        continue
                    edges.setdefault((h, a), (path, line, caller, callee))
        findings.extend(self._cycle_findings(prog, edges))
        # One finding per distinct site+message.
        out, seen = [], set()
        for f in findings:
            key = (f.path, f.line, f.message)
            if key not in seen:
                seen.add(key)
                out.append(f)
        return out

    def _cycle_findings(self, prog, edges) -> List[Finding]:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        findings = []
        reported: Set[frozenset] = set()
        for start in sorted(graph):
            # BFS back to `start` over the edge graph.
            stack = [(nxt, [start, nxt]) for nxt in sorted(graph[start])]
            found = None
            seen: Set[str] = set()
            while stack and found is None:
                node, path_nodes = stack.pop()
                if node == start:
                    found = path_nodes
                    break
                if node in seen:
                    continue
                seen.add(node)
                for nxt in sorted(graph.get(node, ())):
                    stack.append((nxt, path_nodes + [nxt]))
            if found is None:
                continue
            cycle_key = frozenset(found[:-1])
            if cycle_key in reported:
                continue
            reported.add(cycle_key)
            parts = []
            for a, b in zip(found, found[1:]):
                site = edges[(a, b)]
                root_ids = prog.func_roots.get(site[2], set())
                root_text = (
                    sorted(root_ids)[0] if root_ids else "unreached"
                )
                via = f" via {site[3].split('::')[-1]}()" if site[3] else ""
                parts.append(
                    f"`{_short_lock(a)}` -> `{_short_lock(b)}` at "
                    f"{site[0]}:{site[1]}{via} (root {root_text})"
                )
            first = edges[(found[0], found[1])]
            findings.append(
                Finding(
                    self.name, first[0], first[1],
                    "lock ordering cycle (potential deadlock): "
                    + "; ".join(parts),
                )
            )
        return findings


class XprocSyncRule:
    """HOTPATH-SYNC-XPROC: interprocedural implicit syncs in hot paths.

    HOTPATH-SYNC sees `float(x)` only when `x`'s jax taint is assigned
    in the same function. This rule escalates the same contract through
    per-function summaries (analysis/summaries.py): a helper that
    `.item()`s / `float()`s / `np.asarray()`s a tainted PARAMETER flags
    at every hot call site that passes it a device value, and a helper
    that RETURNS a device value taints its callers' assignments, so a
    conversion two hops away is caught where the hot path commits to it.
    Findings are disjoint from HOTPATH-SYNC by construction: anything
    the inline taint already sees is left to the inline rule.
    """

    name = "HOTPATH-SYNC-XPROC"

    def check_repo(self, root: str, contexts) -> List[Finding]:
        from . import graph as graph_mod
        from . import summaries as summaries_mod

        scoped = _concurrency_scope(contexts)
        if not scoped:
            return []
        prog = graph_mod.get_program(scoped)
        hot = [
            info for info in prog.functions.values()
            if self._is_hot(prog, info)
        ]
        if not hot:
            return []
        closure: Set[str] = set()
        stack = [info.qual for info in hot]
        while stack:
            cur = stack.pop()
            if cur in closure:
                continue
            closure.add(cur)
            stack.extend(prog.call_edges.get(cur, ()))
        summaries = summaries_mod.compute_summaries(prog, only=closure)
        inline = HotpathSyncRule()
        findings: List[Finding] = []
        seen = set()
        for info in hot:
            inline_tainted = inline._taint(self._hot_ancestor(prog, info))
            for event in summaries_mod.analyze_hot_region(
                prog, summaries, info
            ):
                if not event.via_call:
                    if event.desc == ".item()":
                        continue  # inline flags every hot .item()
                    if event.name and event.name in inline_tainted:
                        continue  # inline taint already sees this
                key = (info.path, event.line, event.desc)
                if key in seen:
                    continue
                seen.add(key)
                if event.via_call:
                    msg = (
                        f"{event.desc} host-converts its device-tainted "
                        "argument — implicit device->host sync reached "
                        "from this hot path (do the conversion behind "
                        "an explicit jax.device_get at the boundary)"
                    )
                else:
                    msg = (
                        f"{event.desc} on `{event.name or '<expr>'}` — "
                        "device taint flows through called helpers into "
                        "this implicit host sync in a hot path"
                    )
                findings.append(
                    Finding(self.name, info.path, event.line, msg)
                )
        return findings

    @staticmethod
    def _is_hot(prog, info) -> bool:
        cur = info
        while cur is not None:
            if cur.ctx.is_hot_def(cur.node):
                return True
            cur = prog.functions.get(cur.parent) if cur.parent else None
        return False

    @staticmethod
    def _hot_ancestor(prog, info):
        cur = info
        node = info.node
        while cur is not None:
            if cur.ctx.is_hot_def(cur.node):
                node = cur.node
            cur = prog.functions.get(cur.parent) if cur.parent else None
        return node


def _short_lock(lock_id: str) -> str:
    return lock_id.split("::")[-1]


FILE_RULES = [
    HotpathSyncRule(),
    JitHazardRule(),
    DonateUseRule(),
    ImportPurityRule(),
    LockDisciplineRule(),
    ExceptSwallowRule(),
]

CONCURRENCY_RULES = [
    RaceRule(),
    LockOrderRule(),
    XprocSyncRule(),
]
