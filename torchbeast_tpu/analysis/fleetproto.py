"""Exhaustive explicit-state model checking of the fleet control-plane
protocol (ISSUE 20 tentpole, `--check-fleet`).

fleet/coordinator.py speaks a hand-rolled dict protocol over sockets —
hello rendezvous, heartbeat/verdict health folding, a synchronous
param-averaging barrier, snapshot fan-out — exactly the class of code
IMPALA-style multi-host systems historically get wrong under partial
failure. This module writes that protocol down ONCE as a small
transition system and enumerates EVERY interleaving of one lead and
N-1 remotes with at most one injected fault, the same way protocol.py
does for the shm ring.

What is modeled (matching fleet/coordinator.py):

- Rendezvous: remotes dial and send hello; the lead accepts until all
  are in or its connect deadline fires (TimeoutError -> the lead run
  fails). Remote dials are deadline-bounded the same way. Both
  deadlines are untimed transitions — "the deadline eventually fires",
  true for ANY finite positive bound.
- The run: the lead publishes MAX_SNAPS policy snapshots (fan-out to
  every connected remote; delivery per remote is unordered, because
  the store-level version guard — not the socket — is the ordering
  authority apply_snapshot relies on across re-broadcasts and
  reconnects); each remote takes MAX_ACTS acting steps, then enters
  one param-sync round.
- The sync barrier: a remote sends `params` and waits for
  `params_mean`; the lead waits until every expected live remote
  contributed, then broadcasts the mean. Both waits escape by
  `sync_timeout_s` (the spec knob `sync_deadline`), by halt, or — on
  the remote — by lead departure. A mean that arrived BEFORE the
  remote entered the round is stale (the `_mean_seq` capture in
  `_sync_remote`) and does not satisfy the wait.
- Failures (at most one per run): "crash" — the process dies, its
  socket EOFs, the peer's reader DETECTS it (`_on_host_lost` /
  `_on_lead_lost`); and "wedge" — the process hangs with the socket
  alive, which is NEVER detected, because the lead's loss detection is
  reader-EOF only (there is no heartbeat timeout — the
  unbounded-by-design contract FLEET-TIMEOUT-DISCIPLINE pins). Sync
  deadlines are the only thing standing between a wedged host and a
  fleet-wide barrier deadlock; the no_sync_deadline mutant proves they
  are load-bearing.
- The halt plane: a detected loss that drops live hosts below
  `min_live_hosts` halts the lead and broadcasts a HALT verdict
  (`_on_host_lost` -> `_broadcast_verdict`); a remote processing it
  halts. Above the floor the lead degrades and keeps going.

Checked properties (check_fleet), per scenario:

- error_free: no reachable state applies a snapshot version below the
  one already applied (monotonicity), and no host that processed a
  HALT verdict takes another acting step.
- no_wedge: from every reachable state, a state where every host is
  terminal (done / halted / crashed / wedged / dial-failed) is still
  reachable — this subsumes "rendezvous terminates" and "sync_params
  always returns by its deadline with no barrier deadlock".
- halt_propagation: from every reachable state where the lead is
  floor-halted and remote r is still live, a state where r has halted
  is reachable (the HALT verdict cannot be lost short of r crashing).

Seeded mutations (MUTATIONS) re-run the checker on a broken spec and
must FIND the bug as a counterexample trace:

- no_sync_deadline: remove the sync_timeout_s escape — a wedged host
  deadlocks the averaging barrier fleet-wide (wedge trace).
- no_halt_broadcast: the floor-halted lead never tells the survivors —
  they run to completion un-halted (halt_propagation trace).
- act_through_halt: a remote processes the HALT verdict and keeps
  acting (direct safety error).
- no_snapshot_guard: drop apply_snapshot's stale-version guard — an
  unordered delivery applies versions backwards (monotonicity error).

Conformance (check_conformance): the model's constants are pinned
against the real source the way protocol.py pins the ring offsets —
the message-tag set is re-extracted from coordinator.py with the
FLEET-MSG-PARITY extractors, `sync_timeout_s` must default positive,
both sync waits must carry the `remaining <= 0` deadline escape,
`_on_host_lost` must check `min_live_hosts` and halt+broadcast,
`_on_lead_lost` must halt, and snapshot_wire.apply_snapshot must keep
the `snap.version <= store.version` guard. The model cannot silently
drift from the code.
"""

import ast
import dataclasses
import json
import os
from typing import Dict, FrozenSet, Iterator, List, Optional, Tuple

from . import config
from .fleetrules import extract_handler_arms, extract_send_sites

# ---------------------------------------------------------------------------
# The spec, as data

# Every control-plane message tag the coordinator speaks; conformance
# re-extracts this set from the source so a new tag (or a renamed one)
# fails --check-fleet until the model covers it.
MSG_TYPES = (
    "hello", "hb", "verdict", "params", "params_mean", "done", "bye",
)

# Bounded run shape: snapshots the lead publishes (two, so stale-vs-
# fresh ordering exists to check) and acting steps per remote (one: the
# act-after-halt property needs an act that can land after a verdict).
MAX_SNAPS = 2
MAX_ACTS = 1


@dataclasses.dataclass(frozen=True)
class Spec:
    """Protocol variant knobs. The shipped configuration is Spec();
    mutations flip one knob each (MUTATIONS)."""

    # Both sync_params waits escape at sync_timeout_s (degrade to a
    # partial mean / None) — the only defense against a WEDGED host,
    # which reader-EOF loss detection never sees.
    sync_deadline: bool = True
    # The floor-halted lead broadcasts the HALT verdict to survivors
    # (_on_host_lost -> _broadcast_verdict).
    halt_broadcast: bool = True
    # A remote that processed a HALT verdict stops acting (the driver
    # checkpoint-and-exits instead of training on).
    halt_stops_acting: bool = True
    # apply_snapshot drops snap.version <= store.version (the stale
    # guard that makes unordered delivery safe).
    snapshot_guard: bool = True


MUTATIONS: Dict[str, Spec] = {
    # A wedged host parks the averaging barrier forever on BOTH sides:
    # the lead waits for params that never come from a host it cannot
    # detect; remotes wait for a mean a wedged lead never sends.
    "no_sync_deadline": Spec(sync_deadline=False),
    # The lead halts below the floor but the survivors never hear it:
    # they finish the run un-halted (checkpoint skew across the fleet).
    "no_halt_broadcast": Spec(halt_broadcast=False),
    # The verdict arrives and is ignored: a live host keeps acting
    # after the fleet decided to checkpoint-and-exit.
    "act_through_halt": Spec(halt_stops_acting=False),
    # Without the store guard, re-broadcast/reconnect reordering
    # applies an old snapshot over a newer one.
    "no_snapshot_guard": Spec(snapshot_guard=False),
}


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One bounded fleet shape to enumerate."""

    hosts: int  # num_hosts (lead + hosts-1 remotes)
    min_live: int  # --min_live_hosts floor
    failures: int = 1  # fault budget (crash OR wedge, any host)

    @property
    def name(self) -> str:
        return f"n{self.hosts}_floor{self.min_live}_f{self.failures}"


# n=2 exercises the two-party barrier; n=3 with floor 3 gives the halt
# a live survivor to propagate to; n=3 with floor 1 is the
# degrade-and-continue path (a loss shrinks the barrier, nobody halts).
SCENARIOS = (
    Scenario(hosts=2, min_live=2),
    Scenario(hosts=3, min_live=3),
    Scenario(hosts=3, min_live=1),
)


# ---------------------------------------------------------------------------
# State
#
# Immutable tuples throughout; the whole state is hashable.
#
#   lead      lead phase: 'accept' -> 'run' (publishes snapshots) ->
#             'sync' (the barrier) -> 'done'; 'failed' (rendezvous
#             deadline), 'halted' (floor), 'crashed', 'wedged'.
#   published snapshot versions published so far (1..published)
#   lost      frozenset of remote ranks whose crash the lead DETECTED
#   got       frozenset of remote ranks whose params the lead holds
#   remotes   tuple of per-remote tuples:
#               (phase, acts, applied, snaps, halt_pending, mean_pending)
#             phase: 'join' -> 'run' -> 'sync' -> 'done'; 'halted',
#             'crashed', 'wedged', 'dialfail'.
#             applied = newest snapshot version applied; snaps = the
#             in-flight (unordered) snapshot channel.
#   fuel      remaining fault budget

State = Tuple

_LEAD, _PUB, _LOST, _GOT, _REMOTES, _FUEL = 0, 1, 2, 3, 4, 5
_RPHASE, _RACTS, _RAPPLIED, _RSNAPS, _RHALT, _RMEAN = 0, 1, 2, 3, 4, 5

# Phases from which a host takes no further steps, ever.
LEAD_TERMINAL = ("done", "failed", "halted", "crashed", "wedged")
REMOTE_TERMINAL = ("done", "halted", "crashed", "wedged", "dialfail")


def _initial(scenario: Scenario) -> State:
    remote = ("join", 0, 0, frozenset(), False, False)
    return (
        "accept", 0, frozenset(), frozenset(),
        tuple(remote for _ in range(scenario.hosts - 1)),
        scenario.failures,
    )


def _with_remote(state: State, idx: int, **kw) -> State:
    names = ["phase", "acts", "applied", "snaps", "halt_pending",
             "mean_pending"]
    r = list(state[_REMOTES][idx])
    for key, value in kw.items():
        r[names.index(key)] = value
    remotes = list(state[_REMOTES])
    remotes[idx] = tuple(r)
    return state[:_REMOTES] + (tuple(remotes),) + state[_REMOTES + 1:]


def _with(state: State, **kw) -> State:
    names = ["lead", "published", "lost", "got", "remotes", "fuel"]
    vals = list(state)
    for key, value in kw.items():
        vals[names.index(key)] = value
    return tuple(vals)


def _joined(remote: Tuple) -> bool:
    # 'join' has not said hello yet; 'dialfail' never will.
    return remote[_RPHASE] not in ("join", "dialfail")


def _expected(state: State) -> FrozenSet[int]:
    """The lead barrier's rendezvous set: connected ranks that have not
    finished cleanly (`set(self._conns) - self._done`). Crashed-but-
    undetected and wedged hosts ARE still expected — that is the bug
    class the sync deadline exists for."""
    return frozenset(
        i for i, r in enumerate(state[_REMOTES])
        if r[_RPHASE] in ("run", "sync", "crashed", "wedged")
        and i not in state[_LOST]
    )


def _broadcast_flag(state: State, flag: str) -> State:
    """Set halt_pending/mean_pending on every remote that can still
    read it (run/sync; terminal hosts have no reader to care)."""
    for i, r in enumerate(state[_REMOTES]):
        if r[_RPHASE] in ("run", "sync") and i not in state[_LOST]:
            state = _with_remote(state, i, **{flag: True})
    return state


@dataclasses.dataclass
class Violation:
    kind: str  # 'error' | 'wedge' | 'halt_propagation'
    detail: str
    trace: List[str]


@dataclasses.dataclass
class Result:
    ok: bool
    states: int
    violations: List[Violation]
    properties: Dict[str, bool]

    def as_dict(self) -> dict:
        return {
            "ok": self.ok,
            "states": self.states,
            "properties": self.properties,
            "violations": [
                {"kind": v.kind, "detail": v.detail, "trace": v.trace}
                for v in self.violations
            ],
        }


def transitions(state: State, spec: Spec,
                scenario: Scenario) -> Iterator[
                    Tuple[str, State, Optional[str]]]:
    """Yield (label, next_state, error) for every enabled atomic step.

    `error` carries a safety-violation description when the step lands
    in a violation state (the caller records it and stops exploring
    that branch).
    """
    lead, published, lost, got, remotes, fuel = state
    n = scenario.hosts

    def floor_check(st: State, label: str):
        """A detected loss: live drops; below the floor the lead halts
        and (spec permitting) broadcasts the HALT verdict — and, when
        torn out of the sync wait, still broadcasts its partial mean
        (_sync_lead breaks on is_halted and publishes what it has)."""
        live = n - len(st[_LOST])
        if live >= scenario.min_live:
            return label + " degrade", st, None
        if st[_LEAD] == "sync":
            st = _broadcast_flag(st, "mean_pending")
        st = _with(st, lead="halted")
        if spec.halt_broadcast:
            st = _broadcast_flag(st, "halt_pending")
        return label + " floor_halt", st, None

    # -- fault injection ---------------------------------------------------
    if fuel > 0:
        if lead in ("accept", "run", "sync"):
            yield ("lead:crash",
                   _with(state, lead="crashed", fuel=fuel - 1), None)
            yield ("lead:wedge",
                   _with(state, lead="wedged", fuel=fuel - 1), None)
        for i, r in enumerate(remotes):
            if r[_RPHASE] in ("run", "sync"):
                # In-flight messages to the dying host are lost with it.
                dead = _with_remote(
                    state, i, snaps=frozenset(), halt_pending=False,
                    mean_pending=False,
                )
                yield (f"r{i}:crash",
                       _with_remote(dead, i, phase="crashed",
                                    )[:_FUEL] + (fuel - 1,), None)
                yield (f"r{i}:wedge",
                       _with_remote(dead, i, phase="wedged",
                                    )[:_FUEL] + (fuel - 1,), None)

    # -- rendezvous ----------------------------------------------------------
    if lead == "accept":
        if all(_joined(r) for r in remotes):
            yield "lead:rendezvous_done", _with(state, lead="run"), None
        else:
            # The accept loop's connect_timeout_s: raises TimeoutError,
            # the lead run fails before it starts.
            yield ("lead:accept_deadline",
                   _with(state, lead="failed"), None)
    for i, r in enumerate(remotes):
        if r[_RPHASE] != "join":
            continue
        if lead == "accept":
            yield (f"r{i}:hello",
                   _with_remote(state, i, phase="run"), None)
        # dial_transport's deadline_s: the remote gives up (also the
        # shape a host that died before joining takes).
        yield (f"r{i}:dial_deadline",
               _with_remote(state, i, phase="dialfail"), None)

    # -- lead: snapshots, barrier, loss detection ----------------------------
    if lead == "run":
        if published < MAX_SNAPS:
            version = published + 1
            st = _with(state, published=version)
            for i, r in enumerate(remotes):
                if r[_RPHASE] in ("run", "sync") and i not in lost:
                    st = _with_remote(
                        st, i, snaps=st[_REMOTES][i][_RSNAPS]
                        | {version},
                    )
            yield f"lead:publish_snapshot[v{version}]", st, None
        else:
            yield "lead:enter_sync", _with(state, lead="sync"), None
    elif lead == "sync":
        expected = _expected(state)
        if expected <= got:
            st = _broadcast_flag(state, "mean_pending")
            yield ("lead:sync_complete",
                   _with(st, lead="done"), None)
        elif spec.sync_deadline:
            # sync_timeout_s fires: mean whatever arrived, broadcast
            # the partial, move on (the round degraded, nobody waits).
            st = _broadcast_flag(state, "mean_pending")
            yield ("lead:sync_deadline",
                   _with(st, lead="done"), None)
    if lead in ("run", "sync"):
        for i, r in enumerate(remotes):
            if r[_RPHASE] == "crashed" and i not in lost:
                # Reader EOF: _on_host_lost pops the conn and the
                # pending params, then checks the floor.
                st = _with(state, lost=lost | {i}, got=got - {i})
                yield floor_check(st, f"lead:detect_loss[r{i}]")

    # -- remotes -------------------------------------------------------------
    for i, r in enumerate(remotes):
        phase, acts, applied, snaps, halt_pending, mean_pending = r
        if phase == "run":
            if acts < MAX_ACTS:
                yield (f"r{i}:act",
                       _with_remote(state, i, acts=acts + 1), None)
            else:
                # Enter the sync round: send params (delivered unless
                # the lead process is gone), arm the wait. A mean that
                # arrived before this point is STALE — _sync_remote
                # captures _mean_seq before sending, so the old bump
                # does not satisfy the new wait.
                st = _with_remote(state, i, phase="sync",
                                  mean_pending=False)
                if lead in ("accept", "run", "sync", "halted"):
                    st = _with(st, got=st[_GOT] | {i})
                yield f"r{i}:send_params", st, None
        elif phase == "sync":
            if mean_pending:
                yield (f"r{i}:recv_mean",
                       _with_remote(state, i, phase="done",
                                    mean_pending=False), None)
            if spec.sync_deadline:
                yield (f"r{i}:sync_deadline",
                       _with_remote(state, i, phase="done"), None)
            if lead == "done":
                # Clean lead departure: _lead_gone, sync returns None.
                yield (f"r{i}:lead_gone",
                       _with_remote(state, i, phase="done"), None)
        elif phase == "halted" and not spec.halt_stops_acting:
            if acts < MAX_ACTS:
                yield (
                    f"r{i}:act",
                    _with_remote(state, i, acts=acts + 1),
                    f"safety: host {i + 1} took an acting step after "
                    "processing a HALT verdict",
                )
        if phase in ("run", "sync"):
            if halt_pending:
                yield (f"r{i}:process_halt",
                       _with_remote(state, i, phase="halted",
                                    halt_pending=False,
                                    mean_pending=False), None)
            if lead in ("crashed", "failed"):
                # Reader EOF on the lead socket: _on_lead_lost halts.
                yield (f"r{i}:detect_lead_loss",
                       _with_remote(state, i, phase="halted",
                                    halt_pending=False,
                                    mean_pending=False), None)
            for version in sorted(snaps):
                st = _with_remote(state, i, snaps=snaps - {version})
                if spec.snapshot_guard:
                    if version > applied:
                        st = _with_remote(st, i, applied=version)
                        yield (f"r{i}:apply_snapshot[v{version}]",
                               st, None)
                    else:
                        yield (f"r{i}:drop_stale_snapshot[v{version}]",
                               st, None)
                else:
                    st = _with_remote(st, i, applied=version)
                    error = None
                    if version < applied:
                        error = (
                            f"monotonicity: host {i + 1} applied "
                            f"snapshot v{version} after v{applied}"
                        )
                    yield (f"r{i}:apply_snapshot[v{version}]", st,
                           error)


def _is_terminal(state: State) -> bool:
    return state[_LEAD] in LEAD_TERMINAL and all(
        r[_RPHASE] in REMOTE_TERMINAL for r in state[_REMOTES]
    )


def _explore(spec: Spec, scenario: Scenario, max_states: int):
    """BFS the full state graph. Returns (parents, succ, violations)."""
    init = _initial(scenario)
    parents: Dict[State, Optional[Tuple[State, str]]] = {init: None}
    order: List[State] = [init]
    succ: Dict[State, List[State]] = {}
    violations: List[Violation] = []
    i = 0
    while i < len(order):
        state = order[i]
        i += 1
        if len(parents) > max_states:
            raise RuntimeError(
                f"state space exceeded {max_states} states — shrink "
                "the scenario"
            )
        outs: List[State] = []
        for label, nxt, error in transitions(state, spec, scenario):
            if error is not None:
                violations.append(
                    Violation("error", error,
                              _trace(parents, state) + [label]))
                continue
            outs.append(nxt)
            if nxt not in parents:
                parents[nxt] = (state, label)
                order.append(nxt)
        succ[state] = outs
    return parents, succ, violations


def _backward_reachable(succ: Dict[State, List[State]],
                        targets) -> set:
    reach = set(targets)
    changed = True
    while changed:
        changed = False
        for state, outs in succ.items():
            if state not in reach and any(o in reach for o in outs):
                reach.add(state)
                changed = True
    return reach


def check_fleet(spec: Spec = Spec(),
                scenario: Scenario = SCENARIOS[0],
                max_states: int = 2_000_000) -> Result:
    """Enumerate every interleaving of one scenario; verify safety
    (monotonic snapshots, no acting past a HALT) + no-wedge +
    halt-propagation. Counterexamples carry the full transition-label
    trace from the initial state."""
    parents, succ, violations = _explore(spec, scenario, max_states)

    # No-wedge: every reachable state can still reach all-terminal.
    can_finish = _backward_reachable(
        succ, {s for s in parents if _is_terminal(s)}
    )
    wedged = [s for s in parents if s not in can_finish]
    if wedged:
        first = min(wedged, key=lambda s: len(_trace(parents, s)))
        remote_txt = ", ".join(
            f"r{i}={r[_RPHASE]}" for i, r in enumerate(first[_REMOTES])
        )
        violations.append(Violation(
            "wedge",
            "wedged state: no terminal state reachable "
            f"(lead={first[_LEAD]}, {remote_txt}, "
            f"expected_barrier={sorted(_expected(first))}, "
            f"got_params={sorted(first[_GOT])})",
            _trace(parents, first),
        ))

    # Halt propagation: a floor-halted lead's verdict reaches every
    # still-live remote (a state where that remote has halted stays
    # reachable; crashing out instead is the remote's own business).
    halt_holes = []
    for i in range(scenario.hosts - 1):
        can_halt = _backward_reachable(
            succ,
            {s for s in parents
             if s[_REMOTES][i][_RPHASE] == "halted"},
        )
        for s in parents:
            if (
                s[_LEAD] == "halted"
                and s[_REMOTES][i][_RPHASE] in ("run", "sync")
                and s not in can_halt
            ):
                halt_holes.append((i, s))
    if halt_holes:
        i, first = min(
            halt_holes, key=lambda pair: len(_trace(parents, pair[1]))
        )
        violations.append(Violation(
            "halt_propagation",
            f"lead is floor-halted but live host {i + 1} "
            f"(phase {first[_REMOTES][i][_RPHASE]}) can never learn "
            "it — the HALT verdict is lost",
            _trace(parents, first),
        ))

    properties = {
        "error_free": not any(v.kind == "error" for v in violations),
        "no_wedge": not wedged,
        "halt_propagation": not halt_holes,
        "terminal_reachable": bool(can_finish),
    }
    return Result(
        ok=all(properties.values()),
        states=len(parents),
        violations=violations,
        properties=properties,
    )


def _trace(parents, state: State) -> List[str]:
    labels: List[str] = []
    cur = state
    while parents.get(cur) is not None:
        prev, label = parents[cur]
        labels.append(label)
        cur = prev
    return list(reversed(labels))


def render_trace(violation: Violation) -> str:
    """The counterexample format the README documents: one numbered
    `actor:action` step per line, then the violated property."""
    lines = [
        f"  {i + 1:3d}. {step}" for i, step in enumerate(violation.trace)
    ]
    lines.append(f"  => {violation.kind.upper()}: {violation.detail}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Conformance: pin the model's constants against the real source


def _parse(root: str, rel: str) -> Optional[ast.Module]:
    path = os.path.join(root, rel)
    try:
        with open(path, "r", encoding="utf-8") as f:
            return ast.parse(f.read(), filename=rel)
    except (OSError, SyntaxError):
        return None


def _find_method(tree: ast.Module, name: str):
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def _has_deadline_escape(func) -> bool:
    """A `remaining <= 0` compare — the sync waits' deadline escape."""
    for node in ast.walk(func):
        if (
            isinstance(node, ast.Compare)
            and isinstance(node.left, ast.Name)
            and node.left.id == "remaining"
            and len(node.ops) == 1
            and isinstance(node.ops[0], (ast.LtE, ast.Lt))
            and isinstance(node.comparators[0], ast.Constant)
            and node.comparators[0].value == 0
        ):
            return True
    return False


def _calls_attr(func, attr: str) -> bool:
    return any(
        isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and n.func.attr == attr
        for n in ast.walk(func)
    )


def _names_attr(func, attr: str) -> bool:
    return any(
        isinstance(n, ast.Attribute) and n.attr == attr
        for n in ast.walk(func)
    )


def check_conformance(root: str) -> dict:
    """Pin the model against fleet/coordinator.py and snapshot_wire.py.
    Returns {"ok": bool, "pins": {name: {"ok": bool, "detail": str}}}."""
    pins: Dict[str, dict] = {}

    def pin(name: str, ok: bool, detail: str) -> None:
        pins[name] = {"ok": bool(ok), "detail": detail}

    coord = _parse(root, config.FLEET_COORDINATOR)
    if coord is None:
        pin("coordinator_parses", False,
            f"{config.FLEET_COORDINATOR} missing or unparseable")
        return {"ok": False, "pins": pins}

    # 1. The tag set: every sent and every handled message type, as the
    # FLEET-MSG-PARITY extractors see them, equals the model's.
    seen = {s.msg_type for s in extract_send_sites(coord)}
    seen |= {a.msg_type for a in extract_handler_arms(coord)}
    pin("message_tags", seen == set(MSG_TYPES),
        f"source speaks {sorted(seen)}, model speaks "
        f"{sorted(MSG_TYPES)}")

    # 2. sync_timeout_s defaults positive (the deadline the no-wedge
    # proof needs is actually armed by default).
    init = _find_method(coord, "__init__")
    default_ok = False
    detail = "no sync_timeout_s default found"
    if init is not None:
        args = init.args
        names = [a.arg for a in args.args]
        defaults = args.defaults
        offset = len(names) - len(defaults)
        for idx, arg_name in enumerate(names):
            if arg_name == "sync_timeout_s" and idx >= offset:
                d = defaults[idx - offset]
                if isinstance(d, ast.Constant) and isinstance(
                    d.value, (int, float)
                ):
                    default_ok = d.value > 0
                    detail = f"sync_timeout_s defaults to {d.value}"
    pin("sync_timeout_positive", default_ok, detail)

    # 3. Both sync waits carry the deadline escape.
    for fn in ("_sync_lead", "_sync_remote"):
        func = _find_method(coord, fn)
        pin(f"{fn}_deadline", func is not None
            and _has_deadline_escape(func),
            f"{fn} has the `remaining <= 0` escape"
            if func is not None else f"{fn} not found")

    # 4. The floor: _on_host_lost checks min_live_hosts, halts, and
    # broadcasts the verdict.
    ohl = _find_method(coord, "_on_host_lost")
    pin("floor_halts_and_broadcasts", ohl is not None
        and _names_attr(ohl, "min_live_hosts")
        and _calls_attr(ohl, "halt")
        and _calls_attr(ohl, "_broadcast_verdict"),
        "_on_host_lost: min_live_hosts check -> halt -> "
        "_broadcast_verdict" if ohl is not None
        else "_on_host_lost not found")

    # 5. Lead loss halts the remote.
    oll = _find_method(coord, "_on_lead_lost")
    pin("lead_loss_halts", oll is not None and _calls_attr(oll, "halt"),
        "_on_lead_lost calls _health.halt" if oll is not None
        else "_on_lead_lost not found")

    # 6. The snapshot stale guard the monotonicity proof rests on.
    wire_tree = _parse(root, "torchbeast_tpu/fleet/snapshot_wire.py")
    guard_ok = False
    if wire_tree is not None:
        apply_fn = _find_method(wire_tree, "apply_snapshot")
        if apply_fn is not None:
            for node in ast.walk(apply_fn):
                if (
                    isinstance(node, ast.Compare)
                    and isinstance(node.left, ast.Attribute)
                    and node.left.attr == "version"
                    and len(node.ops) == 1
                    and isinstance(node.ops[0], ast.LtE)
                    and isinstance(node.comparators[0], ast.Attribute)
                    and node.comparators[0].attr == "version"
                ):
                    guard_ok = True
    pin("snapshot_stale_guard", guard_ok,
        "apply_snapshot keeps the `snap.version <= store.version` "
        "guard")

    return {"ok": all(p["ok"] for p in pins.values()), "pins": pins}


# ---------------------------------------------------------------------------
# The acceptance bundle


def verify_shipped_and_mutants(root: Optional[str] = None) -> dict:
    """The `--check-fleet` verdict: the shipped spec must verify clean
    on every scenario; every seeded mutation must produce a
    counterexample on at least one; the conformance pins must hold."""
    out: dict = {"scenarios": {}, "mutants": {}}
    shipped_ok = True
    for scenario in SCENARIOS:
        res = check_fleet(Spec(), scenario)
        out["scenarios"][scenario.name] = res.as_dict()
        shipped_ok = shipped_ok and res.ok
    for name, spec in MUTATIONS.items():
        found: List[dict] = []
        per_scenario: Dict[str, dict] = {}
        for scenario in SCENARIOS:
            res = check_fleet(spec, scenario)
            per_scenario[scenario.name] = {
                "ok": res.ok,
                "violations": len(res.violations),
            }
            if res.violations and not found:
                found = [
                    {"kind": v.kind, "detail": v.detail,
                     "trace": v.trace, "scenario": scenario.name}
                    for v in res.violations[:1]
                ]
        out["mutants"][name] = {
            "caught": bool(found),
            "scenarios": per_scenario,
            "counterexample": found[0] if found else None,
        }
    if root is None:
        from .engine import repo_root

        root = repo_root()
    out["conformance"] = check_conformance(root)
    out["ok"] = (
        shipped_ok
        and all(m["caught"] for m in out["mutants"].values())
        and out["conformance"]["ok"]
    )
    return out


def main() -> int:
    verdict = verify_shipped_and_mutants()
    print(json.dumps({
        "protocol": "fleet-control-plane",
        "ok": verdict["ok"],
        "scenarios": {
            name: {"states": s["states"], "properties": s["properties"]}
            for name, s in verdict["scenarios"].items()
        },
        "explored_states_total": sum(
            s["states"] for s in verdict["scenarios"].values()
        ),
        "mutants": {
            name: {"caught": m["caught"]}
            for name, m in verdict["mutants"].items()
        },
        "conformance": {
            name: p["ok"]
            for name, p in verdict["conformance"]["pins"].items()
        },
    }))
    if not verdict["ok"]:
        for name, s in verdict["scenarios"].items():
            for v in s["violations"]:
                print(f"-- shipped-spec violation in {name}:")
                print(render_trace(Violation(v["kind"], v["detail"],
                                             v["trace"])))
        for name, m in verdict["mutants"].items():
            if not m["caught"]:
                print(f"mutant {name}: NOT caught")
        for name, p in verdict["conformance"]["pins"].items():
            if not p["ok"]:
                print(f"conformance pin {name}: FAILED — {p['detail']}")
    else:
        # Show one counterexample per mutant (the README's documented
        # trace format).
        for name, m in verdict["mutants"].items():
            v = m["counterexample"]
            print(f"-- counterexample for mutant {name} "
                  f"({v['scenario']}):")
            print(render_trace(Violation(v["kind"], v["detail"],
                                         v["trace"])))
    return 0 if verdict["ok"] else 1
