"""beastlint distributed-systems rules (ISSUE 20): the fleet
control-plane dict protocol and the telemetry series schema.

Three repo-level rules, same extractor -> summaries -> rules shape as
the ISSUE 7/10 tiers:

- FLEET-MSG-PARITY extracts every control-plane send site (dict
  literals with a "type" key flowing into `_send`/`_broadcast`) and
  every handler arm (`_handle`'s `msg.get("type")` dispatch plus the
  `hello`/`bye` special cases in `_start_lead`/`_reader`) from
  fleet/coordinator.py, assigns each a role (lead vs remote), and
  cross-checks: sent types must have a receiving-role handler, handled
  types must be sent by someone, and the field sets must agree (a key a
  handler reads that no send site packs is a silent default; a key a
  send site packs that no handler reads is dead wire weight).

- FLEET-TIMEOUT-DISCIPLINE requires every blocking control-plane
  operation under fleet/ (accept, recv, dial, condition/event wait,
  join) to be deadline-bounded or carry an explicit
  `# unbounded-by-design: <why>` annotation — the reader threads'
  EOF-side loss-detection contract stated in the source instead of in a
  reviewer's head.

- TELEMETRY-SCHEMA builds the registry of every reg.counter / gauge /
  histogram name across the tree (f-string names become `*` patterns),
  checks the naming grammar (`layer.noun[_noun]`, the `host<r>.` fold
  prefix reserved to the lead's telemetry folder), flags duplicate
  registrations with conflicting instrument kinds, and flags series the
  chaos verdicts / telemetry tests consume that no scanned code emits.

All three read their anchors/scopes from analysis/config.py and return
[] on partial scans that lack them — same contract as WIRE-PARITY.
"""

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import config
from .engine import FileContext, Finding

# The annotation grammar FLEET-TIMEOUT-DISCIPLINE accepts: a trailing
# comment on the blocking call's line (or a standalone comment on the
# line above) naming the contract that bounds it instead of a deadline.
_UNBOUNDED_RE = re.compile(r"#\s*unbounded-by-design\s*:?\s*(.*)$")


# ---------------------------------------------------------------------------
# Shared extraction helpers


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _iter_funcs(tree: ast.Module):
    """Yield (name, FunctionDef) for module functions and methods of
    top-level classes (the coordinator's surface)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    yield item.name, item


def _dict_fields(d: ast.Dict) -> Optional[Tuple[str, Dict[str, int]]]:
    """A control-plane dict literal -> (msg type, {field: lineno}), or
    None when it has no literal "type" key."""
    msg_type = None
    fields: Dict[str, int] = {}
    for key, value in zip(d.keys, d.values):
        name = _const_str(key) if key is not None else None
        if name is None:
            continue
        if name == "type":
            msg_type = _const_str(value)
        else:
            fields[name] = key.lineno
    if msg_type is None:
        return None
    return msg_type, fields


def _reads_of(body: Sequence[ast.AST], var: str) -> Dict[str, int]:
    """Keys read from dict variable `var` via var.get("k") / var["k"]
    anywhere under `body` -> {key: lineno}."""
    out: Dict[str, int] = {}
    for stmt in body:
        for node in ast.walk(stmt):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == var
                and node.args
            ):
                key = _const_str(node.args[0])
                if key is not None:
                    out.setdefault(key, node.lineno)
            elif (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == var
            ):
                key = _const_str(node.slice)
                if key is not None:
                    out.setdefault(key, node.lineno)
    return out


class _SendSite:
    def __init__(self, msg_type: str, fields: Dict[str, int],
                 roles: Set[str], line: int, func: str):
        self.msg_type = msg_type
        self.fields = fields  # field -> lineno
        self.roles = roles  # receiving roles
        self.line = line
        self.func = func


class _HandlerArm:
    def __init__(self, msg_type: str, reads: Dict[str, int],
                 roles: Set[str], line: int, func: str):
        self.msg_type = msg_type
        self.reads = reads  # field -> lineno
        self.roles = roles  # roles that run this handler
        self.line = line
        self.func = func


def extract_send_sites(tree: ast.Module) -> List[_SendSite]:
    """Every dict literal with a "type" key flowing into a
    config.FLEET_SEND_FUNCS call — directly or through one local
    assignment (`bye = {...}; self._send(rank, bye)`)."""
    sites: List[_SendSite] = []
    for fname, func in _iter_funcs(tree):
        local_dicts: Dict[str, ast.Dict] = {}
        for node in ast.walk(func):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Dict)
            ):
                local_dicts[node.targets[0].id] = node.value
        for node in ast.walk(func):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in config.FLEET_SEND_FUNCS
            ):
                continue
            if node.func.attr == "_send":
                if len(node.args) < 2:
                    continue
                target, payload = node.args[0], node.args[1]
                if (
                    isinstance(target, ast.Constant)
                    and target.value == 0
                ):
                    roles = {"lead"}
                else:
                    roles = {"lead", "remote"}
            else:  # _broadcast: the lead fans out to every remote
                if not node.args:
                    continue
                payload, roles = node.args[0], {"remote"}
            if isinstance(payload, ast.Name):
                payload = local_dicts.get(payload.id)
            if not isinstance(payload, ast.Dict):
                continue
            parsed = _dict_fields(payload)
            if parsed is None:
                continue
            msg_type, fields = parsed
            sites.append(
                _SendSite(msg_type, fields, roles, node.lineno, fname)
            )
    return sites


def _walk_bodies(stmts: Sequence[ast.AST]):
    for stmt in stmts:
        for node in ast.walk(stmt):
            yield node


def _arm_roles(fname: str) -> Set[str]:
    if fname in config.FLEET_LEAD_FUNCS:
        return {"lead"}
    if fname in config.FLEET_REMOTE_FUNCS:
        return {"remote"}
    return {"lead", "remote"}


def extract_handler_arms(tree: ast.Module) -> List[_HandlerArm]:
    """Every dispatch arm: `kind = msg.get("type")` equality compares
    (the `_handle` chain) plus direct `x.get("type") == "lit"` compares
    (`_reader`'s bye, `_start_lead`'s hello). An arm's field reads are
    the dispatch variable's reads in the arm body, plus — one level
    deep — the reads of any method the arm forwards the message to."""
    methods = dict(_iter_funcs(tree))
    arms: List[_HandlerArm] = []
    for fname, func in _iter_funcs(tree):
        roles = _arm_roles(fname)
        # Dispatch variables: kind = <msg>.get("type").
        kind_vars: Dict[str, str] = {}  # kind var -> msg var
        for node in ast.walk(func):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "get"
                and isinstance(node.value.func.value, ast.Name)
                and node.value.args
                and _const_str(node.value.args[0]) == "type"
            ):
                kind_vars[node.targets[0].id] = node.value.func.value.id

        def _compare_arm(test: ast.AST) -> Optional[Tuple[str, str]]:
            """An If test of the form `kind == "t"` / `x.get("type") ==
            "t"` (Eq or NotEq) -> (msg var, msg type)."""
            for cmp_node in ast.walk(test):
                if not (
                    isinstance(cmp_node, ast.Compare)
                    and len(cmp_node.ops) == 1
                    and isinstance(cmp_node.ops[0], (ast.Eq, ast.NotEq))
                ):
                    continue
                left, right = cmp_node.left, cmp_node.comparators[0]
                lit = _const_str(right)
                if lit is None:
                    continue
                if (
                    isinstance(left, ast.Name)
                    and left.id in kind_vars
                ):
                    return kind_vars[left.id], lit
                if (
                    isinstance(left, ast.Call)
                    and isinstance(left.func, ast.Attribute)
                    and left.func.attr == "get"
                    and isinstance(left.func.value, ast.Name)
                    and left.args
                    and _const_str(left.args[0]) == "type"
                ):
                    return left.func.value.id, lit
            return None

        for node in ast.walk(func):
            if not isinstance(node, ast.If):
                continue
            arm = _compare_arm(node.test)
            if arm is None:
                continue
            msg_var, msg_type = arm
            # NotEq arms ("bad hello" guards) read fields in the rest
            # of the FUNCTION, not the If body; approximate both shapes
            # by scanning the whole function for the message var.
            reads = _reads_of([func], msg_var)
            reads.pop("type", None)
            # One-level delegation: self._on_x(..., msg) pulls in the
            # target method's reads of its corresponding parameter.
            # Scan the arm's BODY only — an elif chain is nested Ifs in
            # `orelse`, and walking the whole node would smear every
            # later arm's delegate into this one.
            for call in _walk_bodies(node.body):
                if not (
                    isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and isinstance(call.func.value, ast.Name)
                    and call.func.value.id == "self"
                    and call.func.attr in methods
                ):
                    continue
                for pos, arg in enumerate(call.args):
                    if (
                        isinstance(arg, ast.Name)
                        and arg.id == msg_var
                    ):
                        target_fn = methods[call.func.attr]
                        params = [
                            a.arg for a in target_fn.args.args
                            if a.arg != "self"
                        ]
                        if pos < len(params):
                            inner = _reads_of([target_fn], params[pos])
                            inner.pop("type", None)
                            reads.update(inner)
            arms.append(
                _HandlerArm(msg_type, reads, roles, node.lineno, fname)
            )
    return arms


# ---------------------------------------------------------------------------
# FLEET-MSG-PARITY


class FleetMsgParityRule:
    """Fleet control-plane sends and handlers agree on message types and
    field sets, per role (lead vs remote)."""

    name = "FLEET-MSG-PARITY"

    def check_repo(self, root: str,
                   contexts: Sequence[FileContext]) -> List[Finding]:
        ctx = next(
            (c for c in contexts if c.path == config.FLEET_COORDINATOR),
            None,
        )
        if ctx is None:
            return []  # partial scan without the anchor
        findings: List[Finding] = []
        sends = extract_send_sites(ctx.tree)
        arms = extract_handler_arms(ctx.tree)
        standard = set(config.FLEET_MSG_STANDARD_FIELDS)

        sent_types = {s.msg_type for s in sends}
        arm_types = {a.msg_type for a in arms}

        for site in sends:
            receivers = [
                a for a in arms
                if a.msg_type == site.msg_type and a.roles & site.roles
            ]
            if not receivers:
                role_txt = "/".join(sorted(site.roles))
                findings.append(Finding(
                    self.name, ctx.path, site.line,
                    f"message type {site.msg_type!r} is sent "
                    f"(in {site.func}) but no {role_txt}-side handler "
                    "dispatches on it",
                ))
                continue
            read_fields = set()
            for a in receivers:
                read_fields |= set(a.reads)
            for field in sorted(set(site.fields) - read_fields - standard):
                findings.append(Finding(
                    self.name, ctx.path, site.fields[field],
                    f"send site of {site.msg_type!r} (in {site.func}) "
                    f"packs field {field!r} that no handler of that "
                    "type reads",
                ))

        for arm in arms:
            senders = [
                s for s in sends
                if s.msg_type == arm.msg_type and s.roles & arm.roles
            ]
            if not senders:
                findings.append(Finding(
                    self.name, ctx.path, arm.line,
                    f"handler arm for message type {arm.msg_type!r} "
                    f"(in {arm.func}) but no send site produces it",
                ))
                continue
            packed = set()
            for s in senders:
                packed |= set(s.fields)
            for field in sorted(set(arm.reads) - packed - standard):
                findings.append(Finding(
                    self.name, ctx.path, arm.reads[field],
                    f"handler of {arm.msg_type!r} (in {arm.func}) reads "
                    f"field {field!r} that no send site of that type "
                    "packs (the read always hits its default)",
                ))
        return findings


# ---------------------------------------------------------------------------
# FLEET-TIMEOUT-DISCIPLINE


class FleetTimeoutRule:
    """Blocking control-plane operations under fleet/ are deadline-
    bounded or carry `# unbounded-by-design: <why>`."""

    name = "FLEET-TIMEOUT-DISCIPLINE"

    def check_repo(self, root: str,
                   contexts: Sequence[FileContext]) -> List[Finding]:
        findings: List[Finding] = []
        for ctx in contexts:
            if ctx.is_cxx or not ctx.path.startswith(
                config.FLEET_TIMEOUT_PATHS
            ):
                continue
            findings.extend(self._check_file(ctx))
        return findings

    def _annotation(self, ctx: FileContext,
                    line: int) -> Optional[Tuple[int, str]]:
        """The unbounded-by-design annotation covering `line`:
        trailing on the line itself, or a standalone comment above."""
        for cand in (line, line - 1):
            text = ctx.comments.get(cand)
            if text is None:
                continue
            if cand == line - 1 and not ctx.comment_only(cand):
                continue
            m = _UNBOUNDED_RE.search(text)
            if m:
                return cand, m.group(1).strip()
        return None

    def _flag(self, ctx: FileContext, node: ast.AST, what: str,
              findings: List[Finding]) -> None:
        ann = self._annotation(ctx, node.lineno)
        if ann is None:
            findings.append(Finding(
                self.name, ctx.path, node.lineno,
                f"{what} with no deadline — bound it or annotate the "
                "contract that bounds it "
                "(`# unbounded-by-design: <why>`)",
            ))
        elif not ann[1]:
            findings.append(Finding(
                self.name, ctx.path, ann[0],
                "unbounded-by-design annotation without a reason "
                "(write `# unbounded-by-design: <why>`)",
            ))

    def _check_file(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for fname, func in _iter_funcs(ctx.tree):
            # Does this function ever arm a finite socket timeout?
            has_settimeout = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "settimeout"
                and n.args
                and not (
                    isinstance(n.args[0], ast.Constant)
                    and n.args[0].value is None
                )
                for n in ast.walk(func)
            )
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if isinstance(fn, ast.Attribute):
                    attr = fn.attr
                    if (
                        attr == "settimeout"
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and node.args[0].value is None
                    ):
                        self._flag(ctx, node,
                                   "settimeout(None) (socket made "
                                   "blocking forever)", findings)
                    elif attr == "accept" and not has_settimeout:
                        self._flag(ctx, node,
                                   "accept() on a socket this function "
                                   "never arms a timeout on", findings)
                    elif (
                        attr == "recv"
                        and not node.args
                        and not has_settimeout
                    ):
                        self._flag(ctx, node,
                                   "recv() on a transport this "
                                   "function never arms a timeout on",
                                   findings)
                    elif attr in ("wait", "wait_for") and not (
                        node.args or node.keywords
                    ):
                        self._flag(ctx, node,
                                   f"{attr}() with no timeout",
                                   findings)
                    elif attr == "join" and not (
                        node.args or node.keywords
                    ):
                        self._flag(ctx, node, "join() with no timeout",
                                   findings)
                name = None
                if isinstance(fn, ast.Name):
                    name = fn.id
                elif isinstance(fn, ast.Attribute):
                    name = fn.attr
                if name in config.FLEET_DIAL_FUNCS:
                    bounded = len(node.args) >= 2 or any(
                        k.arg == "deadline_s" for k in node.keywords
                    )
                    if not bounded:
                        self._flag(ctx, node,
                                   f"{name}() without deadline_s "
                                   "(unbounded redial)", findings)
        return findings


# ---------------------------------------------------------------------------
# TELEMETRY-SCHEMA


_KINDS = ("counter", "gauge", "histogram")
# layer.noun[_noun]: lowercase/digit/underscore segments, >= 2 deep.
# `*` is the wildcard a dynamic f-string segment collapses to.
_SEGMENT_RE = re.compile(r"^[a-z0-9_*]+$")
_FOLD_PREFIX_RE = re.compile(r"^host(\d+|\*)$")


def _series_pattern(node: ast.AST) -> Optional[str]:
    """A registration/consumption name argument -> the series name, with
    every dynamic f-string piece collapsed to `*`. None when the name is
    not statically visible at all (a plain variable)."""
    lit = _const_str(node)
    if lit is not None:
        return lit
    if isinstance(node, ast.JoinedStr):
        parts = []
        for value in node.values:
            if isinstance(value, ast.Constant):
                parts.append(str(value.value))
            else:
                parts.append("*")
        return "".join(parts)
    return None


def _valid_series(pattern: str) -> bool:
    segments = pattern.split(".")
    return len(segments) >= 2 and all(
        seg and _SEGMENT_RE.match(seg) for seg in segments
    )


def _segments_overlap(a: List[str], b: List[str]) -> bool:
    """Can the two dotted patterns name the same series? A bare `*`
    segment matches one-or-more segments of the other side (an f-string
    hole can expand to a dotted name); a partial-wildcard segment
    (`host*`) matches a single segment."""
    if not a and not b:
        return True
    if not a or not b:
        return False
    a0, b0 = a[0], b[0]
    if a0 == "*" or b0 == "*":
        if _segments_overlap(a[1:], b[1:]):
            return True
        if a0 == "*" and _segments_overlap(a, b[1:]):
            return True
        if b0 == "*" and _segments_overlap(a[1:], b):
            return True
        return False
    import fnmatch

    if not (
        fnmatch.fnmatchcase(a0, b0) or fnmatch.fnmatchcase(b0, a0)
    ):
        return False
    return _segments_overlap(a[1:], b[1:])


def patterns_overlap(a: str, b: str) -> bool:
    return _segments_overlap(a.split("."), b.split("."))


def extract_registrations(
    tree: ast.Module,
) -> List[Tuple[str, str, int]]:
    """Every reg.counter/gauge/histogram call with a statically visible
    name -> (pattern, kind, lineno)."""
    out: List[Tuple[str, str, int]] = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _KINDS
            and node.args
        ):
            continue
        pattern = _series_pattern(node.args[0])
        if pattern is not None:
            out.append((pattern, node.func.attr, node.lineno))
    return out


def _is_telemetry_receiver(node: ast.AST) -> bool:
    """Does the receiver expression plainly hold a counters / gauges /
    histograms mapping (`counters.get(...)`, `snap["gauges"][...]`)?"""
    for sub in ast.walk(node):
        text = None
        if isinstance(sub, ast.Name):
            text = sub.id
        elif isinstance(sub, ast.Attribute):
            text = sub.attr
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            text = sub.value
        if text and any(k in text.lower() for k in _KINDS):
            return True
    return False


def extract_consumptions(tree: ast.Module) -> Dict[str, int]:
    """Series names a consumer file commits to: .get()/[...] reads on a
    telemetry mapping, plus the keys of `expected`-style dict literals
    in functions that sweep a telemetry mapping with a variable key."""
    out: Dict[str, int] = {}

    def _note(node: ast.AST, lineno: int) -> None:
        pattern = _series_pattern(node)
        if pattern is not None and _valid_series(pattern):
            out.setdefault(pattern, lineno)

    funcs = [f for _, f in _iter_funcs(tree)] or [tree]
    for func in funcs:
        swept = False  # telemetry .get with a non-literal key
        for node in ast.walk(func):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
                and _is_telemetry_receiver(node.func.value)
            ):
                if _series_pattern(node.args[0]) is None:
                    swept = True
                else:
                    _note(node.args[0], node.lineno)
            elif (
                isinstance(node, ast.Subscript)
                and _is_telemetry_receiver(node.value)
            ):
                _note(node.slice, node.lineno)
        if not swept:
            continue
        for node in ast.walk(func):
            if isinstance(node, ast.Dict):
                for key in node.keys:
                    if key is not None:
                        _note(key, key.lineno)
            elif isinstance(node, ast.DictComp):
                _note(node.key, node.key.lineno)
    return out


class TelemetrySchemaRule:
    """Telemetry series names follow the grammar, register with one
    instrument kind, and every consumed series has an emitter."""

    name = "TELEMETRY-SCHEMA"

    def check_repo(self, root: str,
                   contexts: Sequence[FileContext]) -> List[Finding]:
        findings: List[Finding] = []
        # (pattern, kind) -> first (path, line); emitted patterns.
        first_kind: Dict[str, Tuple[str, str, int]] = {}
        emitted: List[str] = []
        by_path = {c.path: c for c in contexts}
        for ctx in contexts:
            if ctx.is_cxx or not ctx.path.startswith(
                config.TELEMETRY_SCAN_PATHS
            ):
                continue
            for pattern, kind, line in extract_registrations(ctx.tree):
                emitted.append(pattern)
                if not _valid_series(pattern):
                    findings.append(Finding(
                        self.name, ctx.path, line,
                        f"series name {pattern!r} violates the naming "
                        "grammar (lowercase `layer.noun[_noun]` dotted "
                        "segments, at least two deep)",
                    ))
                    continue
                if (
                    _FOLD_PREFIX_RE.match(pattern.split(".")[0])
                    and ctx.path not in config.TELEMETRY_FOLD_FILES
                ):
                    findings.append(Finding(
                        self.name, ctx.path, line,
                        f"series {pattern!r} uses the `host<r>.` fold "
                        "prefix, which is reserved to the lead's "
                        "telemetry folder "
                        f"({', '.join(config.TELEMETRY_FOLD_FILES)})",
                    ))
                prev = first_kind.get(pattern)
                if prev is None:
                    first_kind[pattern] = (kind, ctx.path, line)
                elif prev[0] != kind:
                    findings.append(Finding(
                        self.name, ctx.path, line,
                        f"series {pattern!r} registered as {kind} here "
                        f"but as {prev[0]} at {prev[1]}:{prev[2]} — the "
                        "registry raises on the kind conflict at "
                        "runtime",
                    ))

        # Consumed-but-never-emitted: only when the scan plainly covers
        # the tree (the sentinel and every consumer file in scope).
        scan_complete = (
            config.TELEMETRY_SENTINEL_FILE in by_path
            and all(
                path in by_path
                for path in config.TELEMETRY_CONSUMER_FILES
            )
        )
        if scan_complete:
            for path in config.TELEMETRY_CONSUMER_FILES:
                ctx = by_path[path]
                for pattern, line in sorted(
                    extract_consumptions(ctx.tree).items()
                ):
                    if not any(
                        patterns_overlap(pattern, e) for e in emitted
                    ):
                        findings.append(Finding(
                            self.name, ctx.path, line,
                            f"series {pattern!r} is consumed here but "
                            "no scanned code registers it (emitter "
                            "renamed or removed?)",
                        ))
        return findings


FLEET_RULES = [
    FleetMsgParityRule(),
    FleetTimeoutRule(),
    TelemetrySchemaRule(),
]
