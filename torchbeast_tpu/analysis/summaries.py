"""Per-function device-sync summaries for HOTPATH-SYNC-XPROC.

The intraprocedural HOTPATH-SYNC rule only sees a sync written inline:
`float(x)` where `x` was assigned from a jax expression in the SAME
function. A helper that does the conversion — `def to_host(x): return
float(x)` — is invisible to it at every call site. This module computes
whole-program summaries so the cross-procedure rule can catch exactly
that shape:

    returns_device        the function's return value is device-resident
                          regardless of its arguments (rooted in
                          jnp/lax/jax.* or a device-returning callee)
    returns_taint_of      param indices whose taint propagates to the
                          return value (`def scale(x): return x * 2`)
    converts_params       param indices that reach an implicit
                          device->host conversion (`.item()`,
                          `float()/int()/bool()`, `np.asarray/array`)
                          inside the function or transitively through
                          its callees

Summaries are computed by a bounded fixpoint over the call graph
(graph.Program supplies call resolution), using a labeled taint lattice:
a value's label set may contain `"dev"` (device-resident now) and/or
`"p<i>"` (tainted iff param i is). `jax.device_get` results are host —
the explicit fetch the rules recommend must never re-taint.

The same labeled walker doubles as the rule-side analysis: seeded with
real `"dev"` labels inside a hot region, it reports conversion events
(direct, and through callee summaries) that the inline rule cannot see.
"""

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Set, Tuple

from . import config
from .graph import (
    Program,
    _attr_chain,
    _build_env_chain,
    _own_nodes,
    _resolve_call_targets,
)

_DEV = "dev"

# Shared with the intraprocedural HOTPATH-SYNC rule via config (one
# contract, two analyses).
_HOST_JAX_NAMESPACES = frozenset(config.HOST_JAX_NAMESPACES)
_HOST_RETURNING_CALLS = frozenset(config.HOST_RETURNING_CALLS)


@dataclasses.dataclass
class FuncSummary:
    returns_device: bool = False
    returns_taint_of: Set[int] = dataclasses.field(default_factory=set)
    converts_params: Set[int] = dataclasses.field(default_factory=set)

    def key(self) -> Tuple:
        return (
            self.returns_device,
            frozenset(self.returns_taint_of),
            frozenset(self.converts_params),
        )


@dataclasses.dataclass
class SyncEvent:
    """One implicit conversion the labeled walker observed."""

    line: int
    desc: str  # e.g. "float()", "helper to_host()"
    labels: FrozenSet[str]  # labels of the converted value
    via_call: bool  # True when the sync happens inside a callee
    name: str = ""  # converted value's name/chain when it has one


class _LabeledTaint:
    """One pass of labeled taint over a single function body."""

    def __init__(self, prog: Program, summaries: Dict[str, FuncSummary],
                 info, seed_params: bool):
        self.prog = prog
        self.summaries = summaries
        self.info = info
        self.env = _build_env_chain(prog, info)
        self.labels: Dict[str, Set[str]] = {}
        self.events: List[SyncEvent] = []
        if seed_params:
            params = info.params[1:] if info.cls else info.params
            for i, name in enumerate(params):
                self.labels[name] = {f"p{i}"}

    # -- label evaluation --------------------------------------------------

    def eval(self, expr) -> Set[str]:
        if expr is None:
            return set()
        if isinstance(expr, ast.Name):
            return set(self.labels.get(expr.id, ()))
        if isinstance(expr, ast.Call):
            return self._call_labels(expr)
        if isinstance(expr, ast.Attribute):
            chain = _attr_chain(expr)
            parts = chain.split(".") if chain else []
            if parts:
                if parts[0] in ("jnp", "lax"):
                    return {_DEV}
                if parts[0] == "jax" and len(parts) > 1 and (
                    parts[1] not in _HOST_JAX_NAMESPACES
                ):
                    return {_DEV}
                if parts[0] in self.labels:
                    return set(self.labels[parts[0]])
            out: Set[str] = set()
            for child in ast.iter_child_nodes(expr):
                out |= self.eval(child)
            return out
        out = set()
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                out |= self.eval(child)
        return out

    def _call_labels(self, call: ast.Call) -> Set[str]:
        chain = _attr_chain(call.func)
        if chain in _HOST_RETURNING_CALLS:
            return set()  # explicit fetch: host result by contract
        self._check_conversion(call)
        arg_labels = [self.eval(a) for a in call.args]
        targets = self._targets(call)
        out: Set[str] = set()
        resolved = False
        for qual in targets:
            summary = self.summaries.get(qual)
            if summary is None:
                continue
            resolved = True
            if summary.returns_device:
                out.add(_DEV)
            for i in summary.returns_taint_of:
                if i < len(arg_labels):
                    out |= arg_labels[i]
            for i in summary.converts_params:
                if i < len(arg_labels) and arg_labels[i]:
                    self.events.append(
                        SyncEvent(
                            call.lineno,
                            f"helper {qual.split('::')[-1]}()",
                            frozenset(arg_labels[i]),
                            via_call=True,
                            name=_attr_chain(call.args[i]),
                        )
                    )
        if not resolved:
            # Unknown callee: device-rooted callables (jnp.*, a stored
            # jitted step) produce device values; the attribute branch
            # already covers jnp/lax/jax chains via func labels.
            func_labels = self.eval(call.func)
            out |= func_labels & {_DEV}
            # A method on a tainted value usually stays tainted
            # (x.mean(), x.reshape(...)).
            if isinstance(call.func, ast.Attribute):
                out |= self.eval(call.func.value)
        return out

    def _targets(self, call) -> Set[str]:
        return _resolve_call_targets(self.prog, self.info, self.env, call)

    def _check_conversion(self, call: ast.Call) -> None:
        """Direct implicit conversions (same set as HOTPATH-SYNC)."""
        func = call.func
        target = None
        desc = ""
        if isinstance(func, ast.Attribute) and func.attr == "item" and (
            not call.args and not call.keywords
        ):
            target, desc = func.value, ".item()"
        elif (
            isinstance(func, ast.Name)
            and func.id in ("float", "int", "bool")
            and len(call.args) == 1
        ):
            target, desc = call.args[0], f"{func.id}()"
        elif (
            isinstance(func, ast.Attribute)
            and func.attr in ("asarray", "array")
            and _attr_chain(func).split(".")[0] in ("np", "numpy")
            and call.args
        ):
            target, desc = call.args[0], f"np.{func.attr}()"
        if target is None:
            return
        labels = self.eval(target)
        if labels:
            self.events.append(
                SyncEvent(call.lineno, desc, frozenset(labels),
                          via_call=False,
                          name=_attr_chain(target))
            )

    # -- statement pass ----------------------------------------------------

    def run(self) -> Tuple[bool, Set[int]]:
        """Process the body; returns (returns_device, returns_taint_of)."""
        returns_device = False
        returns_taint: Set[int] = set()
        # Two passes: assignments may forward-reference (same bounded
        # fixpoint HotpathSyncRule uses).
        for _ in range(2):
            before = {k: set(v) for k, v in self.labels.items()}
            self.events.clear()
            for node in _own_nodes(self.info.node):
                if isinstance(node, ast.Assign):
                    value_labels = self.eval(node.value)
                    for t in node.targets:
                        for name_node in ast.walk(t):
                            if isinstance(name_node, ast.Name):
                                if value_labels:
                                    self.labels.setdefault(
                                        name_node.id, set()
                                    ).update(value_labels)
                elif isinstance(node, ast.AnnAssign) and node.value:
                    value_labels = self.eval(node.value)
                    if isinstance(node.target, ast.Name) and value_labels:
                        self.labels.setdefault(
                            node.target.id, set()
                        ).update(value_labels)
            if before == self.labels:
                break
        # Final event + return pass with stable labels. Each statement's
        # DIRECT expression fields are evaluated exactly once (nested
        # statements evaluate their own), so every call site's events
        # are gathered once.
        self.events.clear()
        for node in _own_nodes(self.info.node):
            if isinstance(node, ast.withitem):
                self.eval(node.context_expr)
                continue
            if not isinstance(node, ast.stmt):
                continue
            if isinstance(node, ast.Return) and node.value is not None:
                labels = self.eval(node.value)
                if _DEV in labels:
                    returns_device = True
                for label in labels:
                    if label.startswith("p"):
                        returns_taint.add(int(label[1:]))
                continue
            for _, value in ast.iter_fields(node):
                if isinstance(value, ast.expr):
                    self.eval(value)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.expr):
                            self.eval(v)
        return returns_device, returns_taint


def compute_summaries(
    prog: Program, only: Set[str] = None
) -> Dict[str, FuncSummary]:
    """Bounded fixpoint over the call graph (callee summaries feed the
    caller's labeled pass; 8 rounds cover any realistic helper depth).
    `only` restricts the fixpoint to a subset of function quals —
    the rule passes the closure of the hot regions, keeping the cost
    proportional to the annotated surface, not the repo."""
    quals = prog.functions.keys() if only is None else (
        only & prog.functions.keys()
    )
    summaries: Dict[str, FuncSummary] = {q: FuncSummary() for q in quals}
    for _ in range(8):
        changed = False
        for qual in quals:
            info = prog.functions[qual]
            walker = _LabeledTaint(prog, summaries, info,
                                   seed_params=True)
            returns_device, returns_taint = walker.run()
            converts: Set[int] = set()
            for event in walker.events:
                for label in event.labels:
                    if label.startswith("p"):
                        converts.add(int(label[1:]))
            new = FuncSummary(returns_device, returns_taint, converts)
            if new.key() != summaries[qual].key():
                summaries[qual] = new
                changed = True
        if not changed:
            break
    return summaries


def analyze_hot_region(
    prog: Program, summaries: Dict[str, FuncSummary], info
) -> List[SyncEvent]:
    """Run the labeled analysis over one HOT function with real seeds
    (no param labels: a hot region's own arguments are not assumed
    device-resident — same stance as the inline rule)."""
    walker = _LabeledTaint(prog, summaries, info, seed_params=False)
    walker.run()
    return [e for e in walker.events if _DEV in e.labels]
