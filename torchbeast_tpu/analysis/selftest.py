"""beastlint --selftest: every rule must catch its seeded violation and
stay silent on the clean twin, and the suppression/baseline mechanics must
hold. Runs from embedded fixtures (no repo state touched), prints one JSON
verdict line — the cheap CI guard that the analyzer itself still works
(same pattern as `python -m torchbeast_tpu.telemetry --selftest`).
"""

import json
import time

from . import (
    ALL_RULE_NAMES,
    analyze_cxx_sources,
    analyze_source,
    analyze_sources,
)
from .engine import FileContext, run_rules
from .fleetrules import FLEET_RULES
from .parity import (
    check_flag_parity,
    check_route_parity,
    check_wire_parity,
)
from .rules import FILE_RULES

# --------------------------------------------------------------------------
# Per-rule fixture pairs. Each positive seeds >= 1 violation of exactly its
# rule; each clean twin exercises the same constructs legally.

_HOTPATH_POSITIVE = '''
import jax.numpy as jnp

# beastlint: hot
def act(env):
    logits = jnp.tanh(env)
    loss = float(logits.mean())
    print(loss)
    return logits.item()
'''

_HOTPATH_CLEAN = '''
import jax
import jax.numpy as jnp
import numpy as np

# beastlint: hot
def act(env, n):
    logits = jnp.tanh(env)
    rows = int(n)
    host = jax.device_get(logits)
    return np.asarray(rows), host

def cold(x):
    return float(jnp.mean(x))
'''

_JIT_POSITIVE = '''
import jax

def train(steps, f, x):
    for _ in range(steps):
        step = jax.jit(f)
        x = step(x)
    return jax.jit(f)(x)
'''

_JIT_CLEAN = '''
import jax

def train(steps, f, x):
    step = jax.jit(f)
    for _ in range(steps):
        x = step(x)
    return x
'''

_DONATE_POSITIVE = '''
def drive(update, params, opt, batch, state, cond):
    wrapped = consume_staged_inputs(update)
    out = wrapped(params, opt, batch, state)
    if cond:
        tail = batch.mean()
    else:
        tail = 0.0
    state.delete()
    return out, tail, state
'''

_DONATE_CLEAN = '''
def drive(update, params, opt, batch, state, queue):
    wrapped = consume_staged_inputs(update)
    scale = batch.mean()
    out = wrapped(params, opt, batch, state)
    batch = queue.get()
    return out, scale, batch.shape
'''

_PURITY_POSITIVE = '''
import json
import numpy as np
'''

_PURITY_CLEAN = '''
import json
import threading
'''

_LOCK_POSITIVE = '''
import threading

class Queue:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: self._lock

    def size(self):
        return len(self._items)

def busy(lock, work):
    lock.acquire()
    work()
    lock.release()
'''

_LOCK_CLEAN = '''
import threading

class Queue:
    def __init__(self):
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._items = []  # guarded-by: self._lock

    def size(self):
        with self._lock:
            return len(self._items)

    def pop(self):
        with self._not_empty:
            return self._items.pop()

    # beastlint: holds self._lock
    def _drain_locked(self):
        self._items.clear()

def busy(lock, work):
    lock.acquire()
    try:
        work()
    finally:
        lock.release()
'''

_SWALLOW_POSITIVE = '''
def teardown(sock, conns):
    try:
        sock.close()
    except Exception:
        pass
    for c in conns:
        try:
            c.shutdown()
        except BaseException:
            return False
    return True
'''

_SWALLOW_CLEAN = '''
import logging

log = logging.getLogger(__name__)


def teardown(sock, conns, counter):
    try:
        sock.close()
    except Exception:
        log.exception("close failed")
    except OSError:
        pass
    for c in conns:
        try:
            c.shutdown()
        except Exception:
            counter.inc()
    try:
        risky()
    except BaseException:
        raise
'''

_SUPPRESSED = '''
import jax.numpy as jnp

# beastlint: hot
def act(env):
    logits = jnp.tanh(env)
    return logits.item()  # beastlint: disable=HOTPATH-SYNC  fixture: intended sync
'''

_REASONLESS = '''
import jax.numpy as jnp

# beastlint: hot
def act(env):
    logits = jnp.tanh(env)
    return logits.item()  # beastlint: disable=HOTPATH-SYNC
'''

# -- whole-program concurrency fixtures (ISSUE 7) ---------------------------
# These run through the repo rules (analyze_sources), so the fixture
# paths sit inside the concurrency scope (config.CONCURRENCY_PATHS).

_RACE_POSITIVE = '''
import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0
        self._thread = threading.Thread(target=self._drain)

    def start(self):
        self._thread.start()

    def _drain(self):
        while True:
            self._total += 1

    def snapshot(self):
        return self._total


def main():
    pump = Pump()
    pump.start()
    return pump.snapshot()
'''

_RACE_CLEAN = '''
import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0
        self._thread = threading.Thread(target=self._drain)

    def start(self):
        self._thread.start()

    def _drain(self):
        while True:
            with self._lock:
                self._total += 1

    def snapshot(self):
        with self._lock:
            return self._total


def main():
    pump = Pump()
    pump.start()
    return pump.snapshot()
'''

_LOCK_ORDER_POSITIVE = '''
import threading


class Mixer:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._thread = threading.Thread(target=self._worker)

    def start(self):
        self._thread.start()

    def _worker(self):
        with self._a:
            with self._b:
                self.tick()

    def tick(self):
        pass


def main():
    mixer = Mixer()
    mixer.start()
    with mixer._b:
        with mixer._a:
            mixer.tick()
'''

_LOCK_ORDER_CLEAN = '''
import threading


class Mixer:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._thread = threading.Thread(target=self._worker)

    def start(self):
        self._thread.start()

    def _worker(self):
        with self._a:
            with self._b:
                self.tick()

    def tick(self):
        pass


def main():
    mixer = Mixer()
    mixer.start()
    with mixer._a:
        with mixer._b:
            mixer.tick()
'''

_XPROC_POSITIVE = '''
import jax.numpy as jnp


def embed(v):
    return jnp.tanh(v)


def to_host(x):
    return float(x)


# beastlint: hot
def act(env):
    z = embed(env)
    return to_host(z)
'''

_XPROC_CLEAN = '''
import jax
import jax.numpy as jnp


def embed(v):
    return jnp.tanh(v)


def to_host(x):
    return float(x)


# beastlint: hot
def act(env, n):
    z = embed(env)
    host = jax.device_get(z)
    return to_host(host), to_host(n)
'''

# -- C++ rule fixtures (ISSUE 10) -------------------------------------------
# These load through the analysis/cxx.py frontend (analyze_cxx_sources).
# Paths matter: GIL-DISCIPLINE only checks config.GIL_FILES (the .h
# fixture path gives non-entry functions an UNHELD default, so a bare
# API call seeds a finding); ATOMIC-ORDER's C++ half anchors on
# config.SHM_H; CXX-LOCK-DISCIPLINE covers all of csrc/.

_GIL_POSITIVE = """
void helper_wait() { cv.wait(lk); }

void loop_body() {
  PyObject* obj = PyLong_FromLong(1);
}

void hook() {
  PyGILState_STATE gil = PyGILState_Ensure();
  helper_wait();
  PyGILState_Release(gil);
}
"""

_GIL_CLEAN = """
void helper_wait() { cv.wait(lk); }

void loop_body() {
  GILGuard gil;
  PyObject* obj = PyLong_FromLong(1);
}

void hook() {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* obj = PyLong_FromLong(1);
  PyGILState_Release(gil);
  helper_wait();
}
"""

_ATOMIC_POSITIVE = """
constexpr size_t kRingHeadWord = 0;
constexpr size_t kRingTailWord = 1;

class ShmRing {
 public:
  void write_frame() {
    word(kRingHeadWord)->store(1);
  }
  bool has_frame() const {
    return word(kRingHeadWord)->load(std::memory_order_relaxed) != 0;
  }
  void peek() {
    uint64_t* raw = reinterpret_cast<uint64_t*>(base_) + kRingTailWord;
  }
 private:
  std::atomic<uint64_t>* word(size_t i) const;
  uint8_t* base_;
};
"""

_ATOMIC_CLEAN = """
constexpr size_t kRingHeadWord = 0;
constexpr size_t kRingTailWord = 1;

class ShmRing {
 public:
  void write_frame() {
    word(kRingHeadWord)->store(1, std::memory_order_release);
  }
  bool has_frame() const {
    return word(kRingHeadWord)->load(std::memory_order_acquire) !=
           word(kRingTailWord)->load(std::memory_order_relaxed);
  }
 private:
  std::atomic<uint64_t>* word(size_t i) const {
    return reinterpret_cast<std::atomic<uint64_t>*>(base_ + 8 * i);
  }
  uint8_t* base_;
};
"""

_CXX_LOCK_POSITIVE = """
class Pump {
 public:
  void start() {
    threads_.emplace_back([this] { drain(); });
    threads_.emplace_back([this] { publish(); });
  }
  void drain() {
    total_ += 1;
    seen_ = total_;
  }
  void publish() {
    last_ = seen_;
  }
  int snapshot() {
    std::lock_guard<std::mutex> lock(mu_);
    return total_;
  }
 private:
  std::mutex mu_;
  int total_ = 0;  // guarded-by: mu_
  int seen_ = 0;
  int last_ = 0;
  std::vector<std::thread> threads_;
};
"""

_CXX_LOCK_CLEAN = """
class Pump {
 public:
  void start() {
    threads_.emplace_back([this] { drain(); });
  }
  void drain() {
    std::lock_guard<std::mutex> lock(mu_);
    total_ += 1;
    seen_ += 1;
  }
  int snapshot() {
    std::lock_guard<std::mutex> lock(mu_);
    return total_ + seen_;
  }
 private:
  std::mutex mu_;
  int total_ = 0;  // guarded-by: mu_
  int seen_ = 0;
  std::vector<std::thread> threads_;
};
"""

# A seeded violation silenced by the C++ `//` suppression grammar — the
# one suppression mechanism must cover both languages.
_CXX_SUPPRESSED = """
class Pump {
 public:
  void drain() {
    total_ += 1;  // beastlint: disable=CXX-LOCK-DISCIPLINE  fixture: init-only path, no reader yet
  }
 private:
  std::mutex mu_;
  int total_ = 0;  // guarded-by: mu_
};
"""

# -- wire-parity fixtures ---------------------------------------------------

_WIRE_PY = '''
import numpy as np

TAG_ARRAY = 0x01
TAG_LIST = 0x02

DEFAULT_MAX_FRAME_BYTES = 16 * 1024

_DTYPE_CODES = {
    np.dtype(np.uint8): 0,
    np.dtype(np.float32): 4,
}
'''

_WIRE_H_CLEAN = """
constexpr uint8_t kTagArray = 0x01;
constexpr uint8_t kTagList = 0x02;
constexpr size_t kMaxFrameBytes = 16ull * 1024;
"""

_WIRE_H_DRIFTED = """
constexpr uint8_t kTagArray = 0x01;
constexpr uint8_t kTagList = 0x09;
constexpr size_t kMaxFrameBytes = 8ull * 1024;
"""

_ARRAY_H = """
enum class DType : uint8_t {
  kU8 = 0,
  kF32 = 4,
};

inline size_t itemsize(DType dtype) {
  switch (dtype) {
    case DType::kU8:
      return 1;
    case DType::kF32:
      return 4;
  }
  throw std::invalid_argument("unknown dtype");
}
"""

_CLIENT_H = """
if (length > wire::kMaxFrameBytes) throw WireError("too big");
"""

# -- flag-parity fixtures ---------------------------------------------------

_FLAGS_A = '''
def parse(parser):
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--learning_rate", type=float, default=0.1)
    parser.add_argument("--mono_only", type=str, default="x")
'''

_FLAGS_B_CLEAN = '''
def parse(parser):
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--learning_rate", type=float, default=0.1)
    parser.add_argument("--poly_only", type=int, default=3)
'''

_FLAGS_B_DRIFTED = '''
def parse(parser):
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--learning_rate", type=str, default=0.1)
'''

_ROUTE_PLACEMENT = '''
def _mix64(x):
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)
'''

_ROUTE_SERIES = '''
def series(i):
    return f"inference.slice.{i}.requests"
'''

_ROUTING_H_CLEAN = '''
constexpr uint64_t kSplitMix64Gamma = 0x9E3779B97F4A7C15ULL;
constexpr uint64_t kSplitMix64Mul1 = 0xBF58476D1CE4E5B9ULL;
constexpr uint64_t kSplitMix64Mul2 = 0x94D049BB133111EBULL;
constexpr int kSplitMix64Shift1 = 30;
constexpr int kSplitMix64Shift2 = 27;
constexpr int kSplitMix64Shift3 = 31;
constexpr const char kSliceSeriesPrefix[] = "inference.slice.";
'''

# Two seeded drifts: a finalizer multiplier off by one nibble AND a
# renamed per-slice series prefix.
_ROUTING_H_DRIFTED = '''
constexpr uint64_t kSplitMix64Gamma = 0x9E3779B97F4A7C15ULL;
constexpr uint64_t kSplitMix64Mul1 = 0xBF58476D1CE4E5B8ULL;
constexpr uint64_t kSplitMix64Mul2 = 0x94D049BB133111EBULL;
constexpr int kSplitMix64Shift1 = 30;
constexpr int kSplitMix64Shift2 = 27;
constexpr int kSplitMix64Shift3 = 31;
constexpr const char kSliceSeriesPrefix[] = "serving.slice.";
'''


# -- fleet fixtures (ISSUE 20) ----------------------------------------------

# Seeded: a sent type with no handler ("claim"), a handled type never
# sent ("grant"), and field skew both ways on "sync" (packs "extra"
# nobody reads; the handler reads "missing" nobody packs).
_FLEET_PARITY_POSITIVE = '''
class Coordinator:
    def _push(self):
        self._send(0, {"type": "claim", "rank": 1, "epoch": 3})
        self._broadcast({"type": "sync", "extra": 1, "round": 2})

    def _handle(self, rank, msg):
        kind = msg.get("type")
        if kind == "grant":
            pass
        elif kind == "sync":
            self._on_sync(msg)

    def _on_sync(self, msg):
        return msg.get("round"), msg.get("missing")
'''

_FLEET_PARITY_CLEAN = '''
class Coordinator:
    def _push(self):
        self._broadcast({"type": "sync", "round": 2})

    def _ack(self):
        payload = {"type": "claim", "rank": 1, "epoch": 3}
        self._send(0, payload)

    def _handle(self, rank, msg):
        kind = msg.get("type")
        if kind == "claim":
            self._on_claim(msg)
        elif kind == "sync":
            self._on_sync(msg)

    def _on_claim(self, msg):
        return msg.get("epoch")

    def _on_sync(self, msg):
        return msg["round"]
'''

# Seeded: settimeout(None), accept/recv with no armed timeout, a bare
# cond wait, a deadline-less dial, and a reasonless annotation.
_FLEET_TIMEOUT_POSITIVE = '''
def serve(sock):
    conn, _ = sock.accept()
    conn.settimeout(None)
    return conn

def pump(t, cv):
    msg = t.recv()
    cv.wait()
    return msg

def dial(address):
    return dial_transport(address)

def drain(t):
    # unbounded-by-design:
    return t.recv()
'''

_FLEET_TIMEOUT_CLEAN = '''
def serve(sock):
    sock.settimeout(5.0)
    conn, _ = sock.accept()
    return conn

def pump(t, cv):
    # unbounded-by-design: reader EOF is this fixture's loss detector
    msg = t.recv()
    cv.wait(1.0)
    return msg

def dial(address):
    return dial_transport(address, deadline_s=10.0)
'''

# Seeded: a name outside the `layer.noun` grammar, the reserved
# `host<r>.` fold prefix outside the telemetry folder, and one name
# registered under two instrument kinds.
_TELEMETRY_POSITIVE = '''
def setup(reg, rank):
    reg.counter("BadName")
    reg.gauge(f"host{rank}.inference.depth")
    reg.counter("queue.depth")
    reg.gauge("queue.depth")
'''

_TELEMETRY_CLEAN = '''
def setup(reg, slice_index):
    reg.counter("queue.items_in")
    reg.gauge("queue.depth")
    reg.histogram(f"inference.slice.{slice_index}.depth")
'''

# Consumption drift: the chaos verdict reads a counter nothing emits;
# the telemetry test reads one that IS emitted (no finding). The
# sentinel file must be present or the check stays off (partial scan).
_TELEMETRY_CONSUME_POSITIVE = {
    "torchbeast_tpu/telemetry/metrics.py": (
        'def mk(reg):\n    reg.counter("recovery.server_restarts")\n'
    ),
    "scripts/chaos_run.py": (
        "def verdict(counters):\n"
        '    return counters.get("recovery.ghost_restarts", 0)\n'
    ),
    "tests/test_telemetry.py": (
        "def check(snap):\n"
        '    return snap["counters"]["recovery.server_restarts"]\n'
    ),
}

_TELEMETRY_CONSUME_CLEAN = {
    "torchbeast_tpu/telemetry/metrics.py": (
        'def mk(reg):\n    reg.counter("recovery.server_restarts")\n'
    ),
    "scripts/chaos_run.py": (
        "def verdict(counters):\n"
        '    return counters.get("recovery.server_restarts", 0)\n'
    ),
    "tests/test_telemetry.py": (
        "def check(snap):\n"
        '    return snap["counters"]["recovery.server_restarts"]\n'
    ),
}


def run_selftest() -> dict:
    t0 = time.perf_counter()
    rules: dict = {}

    pairs = {
        "HOTPATH-SYNC": (_HOTPATH_POSITIVE, _HOTPATH_CLEAN, "snippet.py"),
        "JIT-HAZARD": (_JIT_POSITIVE, _JIT_CLEAN, "snippet.py"),
        "DONATE-USE": (_DONATE_POSITIVE, _DONATE_CLEAN, "snippet.py"),
        "IMPORT-PURITY": (
            _PURITY_POSITIVE,
            _PURITY_CLEAN,
            "torchbeast_tpu/telemetry/fixture.py",
        ),
        "LOCK-DISCIPLINE": (_LOCK_POSITIVE, _LOCK_CLEAN, "snippet.py"),
        "EXCEPT-SWALLOW": (
            _SWALLOW_POSITIVE,
            _SWALLOW_CLEAN,
            "torchbeast_tpu/runtime/fixture.py",
        ),
    }
    for name, (positive, clean, path) in pairs.items():
        pos_report = analyze_source(positive, path=path)
        clean_report = analyze_source(clean, path=path)
        rules[name] = {
            "positive": any(f.rule == name for f in pos_report.findings),
            "clean": not any(
                f.rule == name for f in clean_report.findings
            ),
            # The seeded violation must be the ONLY rule firing: a noisy
            # fixture would hide a rule bleeding into its neighbors.
            "isolated": all(
                f.rule == name for f in pos_report.findings
            ),
        }

    concurrency_pairs = {
        "RACE": (
            _RACE_POSITIVE, _RACE_CLEAN,
            "torchbeast_tpu/fixture_race.py",
        ),
        "LOCK-ORDER": (
            _LOCK_ORDER_POSITIVE, _LOCK_ORDER_CLEAN,
            "torchbeast_tpu/fixture_lockorder.py",
        ),
        "HOTPATH-SYNC-XPROC": (
            _XPROC_POSITIVE, _XPROC_CLEAN,
            "torchbeast_tpu/fixture_xproc.py",
        ),
    }
    for name, (positive, clean, path) in concurrency_pairs.items():
        pos_report = analyze_sources({path: positive})
        clean_report = analyze_sources({path: clean})
        rules[name] = {
            "positive": any(f.rule == name for f in pos_report.findings),
            "clean": not any(
                f.rule == name for f in clean_report.findings
            ),
            "isolated": all(
                f.rule == name for f in pos_report.findings
            ),
        }

    cxx_pairs = {
        "GIL-DISCIPLINE": (
            _GIL_POSITIVE, _GIL_CLEAN, "csrc/actor_pool.h",
        ),
        "ATOMIC-ORDER": (
            _ATOMIC_POSITIVE, _ATOMIC_CLEAN, "csrc/shm.h",
        ),
        "CXX-LOCK-DISCIPLINE": (
            _CXX_LOCK_POSITIVE, _CXX_LOCK_CLEAN, "csrc/queues.h",
        ),
    }
    for name, (positive, clean, path) in cxx_pairs.items():
        pos_report = analyze_cxx_sources({path: positive})
        clean_report = analyze_cxx_sources({path: clean})
        rules[name] = {
            "positive": any(f.rule == name for f in pos_report.findings),
            "clean": not any(
                f.rule == name for f in clean_report.findings
            ),
            "isolated": all(
                f.rule == name for f in pos_report.findings
            ),
        }

    # Fleet rules are repo rules over plain Python contexts; the paths
    # matter (FLEET-MSG-PARITY anchors on the real coordinator path,
    # FLEET-TIMEOUT-DISCIPLINE only scans under fleet/).
    fleet_pairs = {
        "FLEET-MSG-PARITY": (
            _FLEET_PARITY_POSITIVE, _FLEET_PARITY_CLEAN,
            "torchbeast_tpu/fleet/coordinator.py",
        ),
        "FLEET-TIMEOUT-DISCIPLINE": (
            _FLEET_TIMEOUT_POSITIVE, _FLEET_TIMEOUT_CLEAN,
            "torchbeast_tpu/fleet/fixture_ctl.py",
        ),
        "TELEMETRY-SCHEMA": (
            _TELEMETRY_POSITIVE, _TELEMETRY_CLEAN,
            "torchbeast_tpu/runtime/fixture_tele.py",
        ),
    }
    for name, (positive, clean, path) in fleet_pairs.items():
        pos_report = analyze_sources(
            {path: positive}, repo_rules=list(FLEET_RULES)
        )
        clean_report = analyze_sources(
            {path: clean}, repo_rules=list(FLEET_RULES)
        )
        rules[name] = {
            "positive": any(f.rule == name for f in pos_report.findings),
            "clean": not any(
                f.rule == name for f in clean_report.findings
            ),
            "isolated": all(
                f.rule == name for f in pos_report.findings
            ),
        }

    # TELEMETRY-SCHEMA's consumption check only arms on a full scan
    # (sentinel + both consumer files present) — exercise it with a
    # multi-file program where the chaos verdict reads a ghost series.
    consume_pos = analyze_sources(
        _TELEMETRY_CONSUME_POSITIVE, repo_rules=list(FLEET_RULES)
    )
    consume_clean = analyze_sources(
        _TELEMETRY_CONSUME_CLEAN, repo_rules=list(FLEET_RULES)
    )
    rules["TELEMETRY-SCHEMA"]["positive"] &= any(
        f.rule == "TELEMETRY-SCHEMA" for f in consume_pos.findings
    )
    rules["TELEMETRY-SCHEMA"]["clean"] &= not consume_clean.findings

    wire_ctx = FileContext("torchbeast_tpu/runtime/wire.py", _WIRE_PY)
    drifted = check_wire_parity(
        wire_ctx, _WIRE_H_DRIFTED, _ARRAY_H, _CLIENT_H, None
    )
    clean = check_wire_parity(
        wire_ctx, _WIRE_H_CLEAN, _ARRAY_H, _CLIENT_H, None
    )
    rules["WIRE-PARITY"] = {
        "positive": len(drifted) >= 2,  # tag drift AND frame-bound drift
        "clean": not clean,
        "isolated": all(f.rule == "WIRE-PARITY" for f in drifted),
    }

    ctx_a = FileContext("monobeast.py", _FLAGS_A)
    drifted = check_flag_parity(
        ctx_a, FileContext("polybeast.py", _FLAGS_B_DRIFTED)
    )
    clean = check_flag_parity(
        ctx_a, FileContext("polybeast.py", _FLAGS_B_CLEAN)
    )
    rules["FLAG-PARITY"] = {
        "positive": len(drifted) == 2,  # one default drift + one type drift
        "clean": not clean,
        "isolated": all(f.rule == "FLAG-PARITY" for f in drifted),
    }

    placement_ctx = FileContext(
        "torchbeast_tpu/runtime/placement.py", _ROUTE_PLACEMENT
    )
    series_ctxs = [FileContext(
        "torchbeast_tpu/parallel/sebulba.py", _ROUTE_SERIES
    )]
    drifted = check_route_parity(
        placement_ctx, _ROUTING_H_DRIFTED, series_ctxs
    )
    clean = check_route_parity(
        placement_ctx, _ROUTING_H_CLEAN, series_ctxs
    )
    rules["ROUTE-PARITY"] = {
        "positive": len(drifted) == 2,  # hash drift + series-prefix drift
        "clean": not clean,
        "isolated": all(f.rule == "ROUTE-PARITY" for f in drifted),
    }

    # -- mechanics ---------------------------------------------------------
    sup_report = analyze_source(_SUPPRESSED)
    reasonless_report = analyze_source(_REASONLESS)
    positive_report = analyze_source(_HOTPATH_POSITIVE)
    baseline = {f.fingerprint for f in positive_report.findings}
    baselined_report = run_rules(
        [FileContext("snippet.py", _HOTPATH_POSITIVE)],
        FILE_RULES,
        [],
        root="/",
        baseline=baseline,
        known_rules=ALL_RULE_NAMES,
    )
    cxx_sup_report = analyze_cxx_sources({"csrc/queues.h": _CXX_SUPPRESSED})
    mechanics = {
        "suppression": (
            not sup_report.findings and len(sup_report.suppressed) == 1
        ),
        "cxx_suppression": (
            not cxx_sup_report.findings
            and len(cxx_sup_report.suppressed) == 1
        ),
        "suppress_reason": any(
            f.rule == "SUPPRESS-REASON" for f in reasonless_report.findings
        ),
        "baseline": (
            not baselined_report.findings
            and len(baselined_report.baselined)
            == len(positive_report.findings)
        ),
    }

    ok = all(
        all(checks.values()) for checks in rules.values()
    ) and all(mechanics.values())
    return {
        "selftest": "beastlint",
        "ok": ok,
        "rules": rules,
        "mechanics": mechanics,
        "elapsed_s": round(time.perf_counter() - t0, 3),
    }


def main() -> int:
    verdict = run_selftest()
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1
