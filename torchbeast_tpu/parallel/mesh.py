"""Device mesh construction and sharding vocabulary.

The reference has NO collective layer at all — its learner is a single
process and its only multi-device trick is putting the inference model on a
second GPU (SURVEY.md §2.3). This module is the missing piece built
first-class: a `jax.sharding.Mesh` over TPU chips (ICI) and hosts (DCN),
with named axes and `NamedSharding` helpers that the learner step is jitted
against. XLA inserts the gradient all-reduce (psum over the `data` axis)
because params are replicated while the batch is sharded.

Axes:
- `data`: batch-dimension sharding for the learner (gradient all-reduce
  rides ICI).
- `model` (optional, size 1 by default): reserved for sharding wide layers;
  the IMPALA conv nets don't need it, but the axis exists so the same mesh
  recipe scales to models that do.
"""

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def create_mesh(
    n_devices: Optional[int] = None,
    model_parallelism: int = 1,
    devices: Optional[Sequence] = None,
    expert_parallelism: int = 1,
    seq_parallelism: int = 1,
    pipe_parallelism: int = 1,
) -> Mesh:
    """(data[, model][, seq][, expert]) mesh over the first n devices.

    `n_devices` is the TOTAL device count; the data axis gets
    n / (model_parallelism * expert_parallelism * seq_parallelism). The
    `expert`/`seq` axes only exist when their parallelism is > 1 (so
    plain meshes keep their two-axis shape), letting ONE mesh carry a
    data-parallel learner with expert-sharded MoE layers (all-to-alls on
    `expert`) or sequence-sharded attention (ppermute ring / all-to-alls
    on `seq`) — or BOTH at once on a (data, model, seq, expert) mesh:
    the attention shard_maps partition over (`data`, `seq`) and the MoE
    constraints over `expert`, each leaving the other's axis unmentioned
    (= replicated), so gradients still all-reduce over `data` and the
    two collective families never collide. The compute duplicated across
    an unmentioned axis (attention x expert, MoE x seq) is the standard
    cost of not further sharding those dims; correctness is pinned by
    tests/test_composite_mesh.py. The inner axes are innermost so their
    collectives stay within a data replica group on neighboring chips.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"Requested a {n_devices}-device mesh but only "
                f"{len(devices)} devices are visible"
            )
        devices = devices[:n_devices]
    if pipe_parallelism > 1 and (
        expert_parallelism > 1 or seq_parallelism > 1
    ):
        raise ValueError(
            "pipe_parallelism does not combine with expert/seq axes "
            "(the GPipe shard_map owns its schedule; only a data axis "
            "composes with it)"
        )
    n = len(devices)
    inner = (
        model_parallelism * expert_parallelism * seq_parallelism
        * pipe_parallelism
    )
    if n % inner != 0:
        raise ValueError(
            f"{n} devices not divisible by model_parallelism="
            f"{model_parallelism} x expert_parallelism="
            f"{expert_parallelism} x seq_parallelism={seq_parallelism}"
            f" x pipe_parallelism={pipe_parallelism}"
        )
    if pipe_parallelism > 1:
        grid = np.asarray(devices).reshape(
            n // inner, model_parallelism, pipe_parallelism
        )
        return Mesh(grid, ("data", "model", "pipe"))
    if expert_parallelism > 1 and seq_parallelism > 1:
        grid = np.asarray(devices).reshape(
            n // inner, model_parallelism, seq_parallelism,
            expert_parallelism,
        )
        return Mesh(grid, ("data", "model", "seq", "expert"))
    if expert_parallelism > 1:
        grid = np.asarray(devices).reshape(
            n // inner, model_parallelism, expert_parallelism
        )
        return Mesh(grid, ("data", "model", "expert"))
    if seq_parallelism > 1:
        grid = np.asarray(devices).reshape(
            n // inner, model_parallelism, seq_parallelism
        )
        return Mesh(grid, ("data", "model", "seq"))
    grid = np.asarray(devices).reshape(n // inner, model_parallelism)
    return Mesh(grid, ("data", "model"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, leading_axes: int = 0) -> NamedSharding:
    """Time-major [T, B, ...] arrays: shard the batch axis over `data`.

    `leading_axes` prepends unsharded axes — 1 for the superstep's
    [K, T, B, ...] batch stacks, where B is still the sharded axis.
    """
    return NamedSharding(mesh, P(*([None] * (leading_axes + 1)), "data"))


def state_sharding(mesh: Mesh, leading_axes: int = 0) -> NamedSharding:
    """Recurrent state [L, B, H]: shard the batch axis over `data`
    (`leading_axes=1` for [K, L, B, H] superstep stacks)."""
    return NamedSharding(mesh, P(*([None] * (leading_axes + 1)), "data"))
