"""Data-parallel learner: the jitted update step sharded over the mesh.

Replaces what the reference would have needed NCCL/torch.distributed for
(it has neither — single learner process, SURVEY.md §2.3). Design: params
and optimizer state live replicated on every chip; each learner batch
[T+1, B, ...] is sharded along B over the `data` axis; `jax.jit` with these
shardings makes XLA compute per-shard gradients and insert the ICI
all-reduce that keeps params replicated. No hand-written collectives — the
compiler lays them on the ICI rings.

Multi-host: call `initialize_distributed()` first (jax.distributed over
DCN), then build the mesh over `jax.devices()` (global). Each host feeds
its local shard of the batch via `make_global_batch` (device_put to local
addressable shards + jax.make_array_from_single_device_arrays).
"""

import logging
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from torchbeast_tpu import learner as learner_lib
from torchbeast_tpu.parallel import mesh as mesh_lib

log = logging.getLogger(__name__)


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """jax.distributed.initialize with env-var fallbacks.

    The DCN analog of the reference's "anything gRPC accepts works across
    machines" story (SURVEY.md §5.8): one coordinator address, N learner
    processes, each seeing its local TPU chips; collectives ride ICI within
    a host and DCN across.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "TORCHBEAST_COORDINATOR"
    )
    if coordinator_address is None:
        log.info("No coordinator configured; single-process mode.")
        return
    if num_processes is None:
        num_processes = int(os.environ.get("TORCHBEAST_NUM_PROCESSES", 1))
    if process_id is None:  # NB: 0 is a valid id — test None explicitly
        process_id = int(os.environ.get("TORCHBEAST_PROCESS_ID", 0))
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=int(num_processes),
        process_id=int(process_id),
    )


def fleet_strategy(backend: Optional[str] = None) -> str:
    """How a multi-host fleet composes its learner (ISSUE 17).

    "xla": the backend executes cross-process computations — TPU (DCN
    collectives) and GPU (NCCL). jax.distributed rendezvous, one global
    mesh whose `data` axis spans hosts, `make_parallel_update_step`
    compiles over it unchanged, `shard_batch` takes its
    make_array_from_process_local_data branch.

    "wire": CPU — XLA has no multiprocess CPU runtime (a jitted
    computation over a cross-host mesh fails at dispatch with
    "Multiprocess computations aren't implemented on the CPU backend"),
    so jax.distributed is never initialized; each host compiles over
    its LOCAL learner devices and the fleet coordinator's control plane
    composes parameters by synchronous averaging
    (fleet.FleetCoordinator.sync_params). This is the CI strategy: it
    exercises every fleet control surface (rendezvous, health folding,
    snapshot wire, telemetry) on forced-CPU hosts.

    Selection is by BACKEND, not a runtime probe: probing would require
    an irreversible jax.distributed.initialize before knowing whether
    the backend can use it.
    """
    backend = backend if backend is not None else jax.default_backend()
    return "xla" if backend in ("tpu", "gpu") else "wire"


def make_parallel_update_step(
    model, optimizer, hp: learner_lib.HParams, mesh, donate=True,
    param_shardings: Optional[Any] = None,
    opt_shardings: Optional[Any] = None,
    donate_batch: bool = False,
    superstep_k: int = 1,
):
    """Data/tensor-parallel version of learner.make_update_step.

    Same signature and semantics; gradients are averaged over the `data`
    axis implicitly by XLA's all-reduce (sum-reduced losses over a sharded
    batch == the reference's single-learner loss over the full batch).
    `donate` is a policy understood by learner.donate_argnums_for: True
    (params+opt, single-threaded drivers), "opt_only" (async drivers —
    the shared params stay undonated), or False. `donate_batch` enforces
    the consume-once staging contract on the batch/agent-state args
    (learner.consume_staged_inputs — host-side deletion after dispatch;
    the stock body has no batch-shaped outputs for XLA-level aliasing).

    `superstep_k > 1` builds the SAME scan wrapper the single-device
    learner.make_update_superstep uses (learner.superstep_body): one
    dispatch runs K scanned updates over a [K, T+1, B, ...] stack whose
    B axis is sharded over `data` — DP-sharded learners amortize
    dispatch overhead identically to single-device ones. The grad
    all-reduce happens inside every scan iteration (each scanned update
    consumes its own full global batch), so K scanned collective updates
    match K sequential parallel dispatches. The Sebulba device split
    (runtime/placement.py) compiles its learner superstep through this
    exact path over a mesh spanning only the split's learner devices
    (`create_mesh(devices=split.learner_devices)`) — K=1-vs-K=2 parity
    on a 2-device mesh is pinned by tests/test_sebulba.py. (A 1-device
    learner group deliberately does NOT come here: polybeast pins the
    plain-jit update by explicit placement instead — the SPMD
    partitioner costs ~1.7x on a partition-of-one.)

    Precision (--precision bf16_train, torchbeast_tpu/precision.py):
    the staged stack's float leaves may arrive bfloat16 — shardings are
    dtype-agnostic, shard_batch places whatever dtype the arena staged,
    and the shared update_body upcasts at point of use (f32-accumulate;
    grads and the all-reduce run f32). The compact optimizer state
    (hp.opt_state_dtype="bf16") flows in through the caller's
    make_optimizer, so opt_shardings derived by mapping leaf-wise rules
    over opt_state keep working; the FACTORED state (hp.opt_factored)
    does NOT mirror params leaf-wise — callers deriving EP/TP opt
    shardings must reject that combination (polybeast does).

    param_shardings (optional): a params-pytree of NamedShardings (see
    parallel/tp.py) to shard weights over the mesh's `model` axis;
    defaults to fully replicated params. Optimizer state follows the same
    sharding (optax state mirrors the params structure leaf-wise).
    """
    if superstep_k < 1:
        raise ValueError(f"superstep_k must be >= 1, got {superstep_k}")
    repl = mesh_lib.replicated(mesh)
    leading = 1 if superstep_k > 1 else 0
    bsh = mesh_lib.batch_sharding(mesh, leading_axes=leading)
    ssh = mesh_lib.state_sharding(mesh, leading_axes=leading)
    psh = repl if param_shardings is None else param_shardings

    # The exact single-device update body (incl. the entropy-anneal
    # schedule); only the jit wrapping — shardings + donation — differs.
    # superstep_k > 1 swaps in the K-scan superstep body, same sharing.
    if superstep_k > 1:
        update_step = learner_lib.superstep_body(model, optimizer, hp)
    else:
        update_step = learner_lib.update_body(model, optimizer, hp)

    # A single NamedSharding acts as a pytree prefix: it applies to every
    # leaf of the batch dict (all leaves are [T+1, B, ...]). Optimizer
    # state shardings: explicit when the caller derives them (donation
    # requires input placement == output placement, so donating drivers
    # must pin them — optax state mirrors the params leaf-wise, so
    # expert_param_shardings works on it directly); otherwise left to the
    # compiler when params are sharded.
    if opt_shardings is not None:
        opt_sh = opt_shardings
    else:
        opt_sh = repl if param_shardings is None else None
    # Batch/state args never reach donate_argnums: the body has no
    # batch-shaped outputs to alias (learner.consume_staged_inputs
    # documents the physics), so donate_batch is enforced host-side.
    donate_args = learner_lib.donate_argnums_for(donate, False)
    if opt_sh is None and 1 in donate_args:
        # Donation aliases the input buffer to the output, which requires
        # input placement == output sharding. With opt placement left to
        # the compiler, the output sharding it picks can disagree with
        # wherever the caller staged opt_state (XLA then fails with an
        # aliased-size mismatch at dispatch), so skip donating it.
        log.warning(
            "opt_state sharding left to the compiler with sharded params; "
            "disabling opt_state donation (pass opt_shardings to donate)."
        )
        donate_args = tuple(a for a in donate_args if a != 1)
    jitted = jax.jit(
        update_step,
        in_shardings=(psh, opt_sh, bsh, ssh),
        out_shardings=(psh, opt_sh, repl),
        donate_argnums=donate_args,
    )
    if donate_batch:
        return learner_lib.consume_staged_inputs(jitted)
    return jitted


def shard_batch(mesh, batch: Dict[str, np.ndarray], initial_agent_state: Any,
                leading_axes: int = 0):
    """Host -> device: place a batch with the DP shardings.

    Single-process: jax.device_put splits across local devices. Multi-host
    (jax.process_count() > 1): each process passes its LOCAL batch shard
    (local_batch_size = global / process_count) and
    jax.make_array_from_process_local_data assembles the global array —
    device_put with a global sharding would fail on non-addressable
    devices.

    `leading_axes=1` places [K, T+1, B, ...] superstep stacks (the B
    axis stays the sharded one) — must match the superstep_k the update
    step was jitted with.
    """
    bsh = mesh_lib.batch_sharding(mesh, leading_axes=leading_axes)
    ssh = mesh_lib.state_sharding(mesh, leading_axes=leading_axes)
    if jax.process_count() > 1:
        put_b = lambda v: jax.make_array_from_process_local_data(bsh, v)  # noqa: E731
        put_s = lambda v: jax.make_array_from_process_local_data(ssh, v)  # noqa: E731
    else:
        put_b = lambda v: jax.device_put(v, bsh)  # noqa: E731
        put_s = lambda v: jax.device_put(v, ssh)  # noqa: E731
    batch = {k: put_b(np.asarray(v)) for k, v in batch.items()}
    initial_agent_state = jax.tree_util.tree_map(
        lambda s: put_s(np.asarray(s)), initial_agent_state
    )
    return batch, initial_agent_state


def replicate(mesh, tree):
    """Place params/opt_state replicated on every mesh device."""
    return jax.device_put(tree, mesh_lib.replicated(mesh))
