"""Data-parallel learner: the jitted update step sharded over the mesh.

Replaces what the reference would have needed NCCL/torch.distributed for
(it has neither — single learner process, SURVEY.md §2.3). Design: params
and optimizer state live replicated on every chip; each learner batch
[T+1, B, ...] is sharded along B over the `data` axis; `jax.jit` with these
shardings makes XLA compute per-shard gradients and insert the ICI
all-reduce that keeps params replicated. No hand-written collectives — the
compiler lays them on the ICI rings.

Multi-host: call `initialize_distributed()` first (jax.distributed over
DCN), then build the mesh over `jax.devices()` (global). Each host feeds
its local shard of the batch via `make_global_batch` (device_put to local
addressable shards + jax.make_array_from_single_device_arrays).
"""

import logging
import os
from typing import Any, Dict, Optional

import jax
import numpy as np
import optax

from torchbeast_tpu import learner as learner_lib
from torchbeast_tpu.parallel import mesh as mesh_lib

log = logging.getLogger(__name__)


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """jax.distributed.initialize with env-var fallbacks.

    The DCN analog of the reference's "anything gRPC accepts works across
    machines" story (SURVEY.md §5.8): one coordinator address, N learner
    processes, each seeing its local TPU chips; collectives ride ICI within
    a host and DCN across.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "TORCHBEAST_COORDINATOR"
    )
    if coordinator_address is None:
        log.info("No coordinator configured; single-process mode.")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=int(
            num_processes or os.environ.get("TORCHBEAST_NUM_PROCESSES", 1)
        ),
        process_id=int(
            process_id or os.environ.get("TORCHBEAST_PROCESS_ID", 0)
        ),
    )


def make_parallel_update_step(model, optimizer, hp: learner_lib.HParams, mesh):
    """Data-parallel version of learner.make_update_step.

    Same signature and semantics; gradients are averaged over the `data`
    axis implicitly by XLA's all-reduce (sum-reduced losses over a sharded
    batch == the reference's single-learner loss over the full batch).
    """
    repl = mesh_lib.replicated(mesh)
    bsh = mesh_lib.batch_sharding(mesh)
    ssh = mesh_lib.state_sharding(mesh)

    def update_step(params, opt_state, batch, initial_agent_state):
        grads, stats = jax.grad(
            lambda p: learner_lib.compute_loss(
                model, p, batch, initial_agent_state, hp
            ),
            has_aux=True,
        )(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        stats["grad_norm"] = optax.global_norm(grads)
        return params, opt_state, stats

    # A single NamedSharding acts as a pytree prefix: it applies to every
    # leaf of the batch dict (all leaves are [T+1, B, ...]).
    return jax.jit(
        update_step,
        in_shardings=(repl, repl, bsh, ssh),
        out_shardings=(repl, repl, repl),
        donate_argnums=(0, 1),
    )


def shard_batch(mesh, batch: Dict[str, np.ndarray], initial_agent_state: Any):
    """Host -> device: place a host-global batch with the DP shardings.

    Single-process path: jax.device_put handles splitting across local
    devices. (The multi-host variant assembles a global array from each
    host's local shard; that lands with the distributed driver.)
    """
    bsh = mesh_lib.batch_sharding(mesh)
    ssh = mesh_lib.state_sharding(mesh)
    batch = {k: jax.device_put(v, bsh) for k, v in batch.items()}
    initial_agent_state = jax.tree_util.tree_map(
        lambda s: jax.device_put(s, ssh), initial_agent_state
    )
    return batch, initial_agent_state


def replicate(mesh, tree):
    """Place params/opt_state replicated on every mesh device."""
    return jax.device_put(tree, mesh_lib.replicated(mesh))
