"""Pipeline parallelism over a `pipe` mesh axis.

The reference has no pipeline parallelism (its nets are 3-block convs,
SURVEY.md §2.3) and the IMPALA trunks here don't need it either — but a
framework that scales deep uniform towers (transformer stacks) across
chips needs the schedule, so it is built first-class and validated in the
full-training-step multichip dryrun.

Design (TPU-idiomatic, compare Praxis/scaling-book pipelining rather than
torch RPC): every device holds ONE stage's parameters (a pytree whose
leaves carry a leading stage axis sharded over `pipe`); the batch is cut
into microbatches; a `lax.scan` runs the GPipe schedule — at tick t, stage
s processes microbatch t-s and hands its activations to stage s+1 via
`lax.ppermute` over ICI. Fill/drain bubbles compute on zeros and their
outputs are masked out, so autodiff through the scan yields exactly the
sequential gradients. The whole schedule lives inside one `shard_map`, so
XLA sees static shapes and a fixed collective ring.

Constraints (asserted): stage output shape == stage input shape (uniform
tower), batch divisible by the microbatch count, and a 1-D stage axis.

Why GPipe-in-scan and not 1F1B: autodiff through the scan already runs
the schedule in REVERSE for the backward — stage s's grads compute at
mirrored ticks, pipelined over the same ring — so the bubble fraction of
the combined fwd+bwd matches non-interleaved 1F1B at equal M
((S-1)/(S+M-1) per direction; raise n_microbatches to amortize). 1F1B's
remaining advantage is peak activation memory, and that lever exists
here as per-stage rematerialization (jax.checkpoint around stage_fn —
models/transformer_pp.py `remat`), which bounds live activations to one
microbatch per stage exactly like 1F1B's eager backward does, with none
of the hand-staged VJP machinery a manual schedule would need.
"""

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax>=0.4.35 exposes shard_map at the top level
    from jax import shard_map  # type: ignore
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


def default_n_microbatches(
    mesh: Mesh, axis: str = "pipe", n_microbatches: Optional[int] = None
) -> int:
    """The microbatch count pipeline_apply will actually use — the single
    source of truth for model-side divisibility checks and fallbacks
    (models/pipelined.py, models/transformer_pp.py)."""
    return (
        n_microbatches if n_microbatches is not None else mesh.shape[axis]
    )


def can_pipeline(
    mesh: Mesh,
    batch_rows: int,
    axis: str = "pipe",
    n_microbatches: Optional[int] = None,
    batch_axis: Optional[str] = None,
) -> bool:
    """Whether pipeline_apply accepts `batch_rows` — rows must divide
    into microbatches AND (on a composite mesh) each microbatch's rows
    must divide over `batch_axis`. The single gate the models' silent
    sequential fallback and the drivers' up-front validation both use,
    so they can never disagree with pipeline_apply's own checks."""
    M = default_n_microbatches(mesh, axis, n_microbatches)
    if batch_rows % M != 0:
        return False
    if batch_axis is not None and (
        (batch_rows // M) % mesh.shape[batch_axis] != 0
    ):
        return False
    return True


def stack_stages(per_stage_trees):
    """Stack a list of per-stage pytrees along a new leading stage axis
    (the layout pipeline_apply expects for `stage_params`)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_trees
    )


def stage_param_shardings(mesh: Mesh, stage_params: Any, axis: str = "pipe"):
    """params-pytree of NamedShardings: leading stage axis over `axis`."""
    return jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P(axis)), stage_params
    )


def pipeline_apply(
    stage_fn: Callable,
    stage_params: Any,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "pipe",
    n_microbatches: Optional[int] = None,
    stage_carry: Any = None,
    shared: Any = None,
    batch_axis: Optional[str] = None,
):
    """Run a uniform tower of S stages as a pipeline over `axis`.

    Args:
      stage_fn: `(params, x_mb, carry_mb, shared_mb) -> (y_mb, new_carry_mb)`
        applied per microbatch. `y_mb.shape == x_mb.shape` (activations
        rotate between stages, so the width is uniform).
      stage_params: pytree, every leaf `[S, ...]` — stage s's params at
        index s. Shard with `stage_param_shardings` (or leave unplaced;
        shard_map partitions logically either way).
      x: `[B, ...]` activations entering stage 0.
      n_microbatches: M; default S. `B % M == 0`.
      stage_carry: optional pytree, leaves `[S, B, ...]` — per-stage,
        per-example state (e.g. a KV cache per layer). Stays resident on
        its stage; never rotates.
      shared: optional pytree, leaves `[B, ...]` — inputs every stage
        reads for the microbatch it is processing (masks, segment ids).
      batch_axis: optional name of a DATA axis on the same mesh — each
        microbatch additionally shards its rows over it, so a
        (data x pipe) mesh runs an independent GPipe per data group
        (the cross-group gradient all-reduce comes from the params
        being replicated over `batch_axis`, inserted by XLA as usual).
        Requires B/M divisible by the axis size.

    Returns:
      `(y, new_stage_carry)`: y `[B, ...]` from the last stage (replicated
      over `axis`), new_stage_carry with the same `[S, B, ...]` layout.
    """
    S = mesh.shape[axis]
    B = x.shape[0]
    M = default_n_microbatches(mesh, axis, n_microbatches)
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by n_microbatches={M}")
    if batch_axis is not None and (B // M) % mesh.shape[batch_axis] != 0:
        raise ValueError(
            f"microbatch rows {B // M} not divisible by the "
            f"`{batch_axis}` axis size {mesh.shape[batch_axis]}"
        )
    for tree, what in ((stage_params, "stage_params"),
                       (stage_carry, "stage_carry")):
        for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
            if leaf.shape[0] != S:
                # shard_map would hand each device leading_dim/S stages
                # and the local `[0]` would silently drop all but the
                # first — wrong results, no error. Reject instead.
                raise ValueError(
                    f"{what} leaf {jax.tree_util.keystr(path)} has "
                    f"leading dim {leaf.shape[0]}; the pipeline needs "
                    f"exactly one stage per device on `{axis}` (= {S})"
                )
    mb = B // M

    def to_mb(leaf):  # [B, ...] -> [M, mb, ...]
        return leaf.reshape((M, mb) + leaf.shape[1:])

    def from_mb(leaf):  # [M, mb, ...] -> [B, ...]
        return leaf.reshape((M * mb,) + leaf.shape[2:])

    xs = to_mb(x)
    shared_mb = jax.tree_util.tree_map(to_mb, shared)
    # stage_carry [S, B, ...] -> [S, M, mb, ...]
    carry_mb = jax.tree_util.tree_map(
        lambda leaf: leaf.reshape((S, M, mb) + leaf.shape[2:]), stage_carry
    )

    # Microbatch rows shard over batch_axis (if any): [M, mb, ...] ->
    # P(None, batch_axis); the resident carry keeps its stage axis too.
    mb_spec = P(None, batch_axis) if batch_axis else P()
    pspec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    cspec = jax.tree_util.tree_map(
        lambda _: P(axis, None, batch_axis) if batch_axis else P(axis),
        carry_mb,
    )
    rspec = jax.tree_util.tree_map(lambda _: mb_spec, (xs, shared_mb))
    ring = [(i, (i + 1) % S) for i in range(S)]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(pspec, cspec, rspec[0], rspec[1]),
        out_specs=(mb_spec, cspec),
        check_vma=False,
    )
    def run(params, carry, xs, shared_mb):
        # Local leaves keep a leading stage axis of size 1 — drop it.
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        carry = jax.tree_util.tree_map(lambda c: c[0], carry)
        idx = lax.axis_index(axis)

        state = jnp.zeros_like(xs[0])
        out_acc = jnp.zeros_like(xs)

        def body(scan_carry, t):
            state, out_acc, carry = scan_carry
            # Stage `idx` processes microbatch j = t - idx at tick t.
            j = t - idx
            active = (j >= 0) & (j < M)
            jc = jnp.clip(j, 0, M - 1)
            inp = jnp.where(idx == 0, xs[jc], state)
            carry_in = jax.tree_util.tree_map(lambda c: c[jc], carry)
            shared_in = jax.tree_util.tree_map(
                lambda s: s[jc], shared_mb
            )
            out, carry_out = stage_fn(params, inp, carry_in, shared_in)
            # Persist this stage's new per-microbatch state (bubble ticks
            # write nothing — `where` keeps the old row).
            carry = jax.tree_util.tree_map(
                lambda c, new: c.at[jc].set(
                    jnp.where(
                        active.reshape((1,) * new.ndim), new, c[jc]
                    )
                ),
                carry,
                carry_out,
            )
            # The last stage's active outputs are the pipeline's outputs.
            take = active & (idx == S - 1)
            out_acc = out_acc.at[jc].set(
                jnp.where(take.reshape((1,) * out.ndim), out, out_acc[jc])
            )
            # Rotate activations one stage forward over the ICI ring.
            state = lax.ppermute(out, axis, ring)
            return (state, out_acc, carry), None

        (state, out_acc, carry), _ = lax.scan(
            body, (state, out_acc, carry), jnp.arange(S + M - 1)
        )
        # out_acc is non-zero only on the last stage; psum replicates it.
        y = lax.psum(out_acc, axis)
        carry = jax.tree_util.tree_map(lambda c: c[None], carry)
        return y, carry

    y, new_carry = run(stage_params, carry_mb, xs, shared_mb)
    new_carry = jax.tree_util.tree_map(
        lambda leaf: leaf.reshape((S, M * mb) + leaf.shape[3:]), new_carry
    )
    return from_mb(y), new_carry


def pipeline_apply_multi(
    stage_fn: Callable,
    stage_params: Any,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "pipe",
    n_microbatches: Optional[int] = None,
    stage_carry: Any = None,
    shared: Any = None,
    batch_axis: Optional[str] = None,
):
    """Pipeline S = k*P stages over P devices as k sequential passes of
    the P-stage GPipe schedule (a looped pipeline: device d runs global
    stages j*P + d for j in 0..k-1).

    Accepts the same `[S, ...]`-leading stage_params/stage_carry layout
    as `pipeline_apply` and reduces to it when S == P. Each pass pays its
    own fill/drain bubble — the simple schedule; an interleaved 1F1B
    would trade that for a much hairier program. Bubble cost is
    (P-1)/(M+P-1) per pass, so raise n_microbatches to amortize.
    """
    S_total = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    P_devices = mesh.shape[axis]
    if S_total == P_devices:
        return pipeline_apply(
            stage_fn, stage_params, x, mesh=mesh, axis=axis,
            n_microbatches=n_microbatches, stage_carry=stage_carry,
            shared=shared, batch_axis=batch_axis,
        )
    if S_total % P_devices != 0:
        raise ValueError(
            f"{S_total} stages not divisible by the `{axis}` axis size "
            f"{P_devices}"
        )
    k = S_total // P_devices

    def pass_slice(tree, j):
        return jax.tree_util.tree_map(
            lambda leaf: leaf.reshape(
                (k, P_devices) + leaf.shape[1:]
            )[j],
            tree,
        )

    new_carries = []
    for j in range(k):
        carry_j = None if stage_carry is None else pass_slice(
            stage_carry, j
        )
        x, new_c = pipeline_apply(
            stage_fn, pass_slice(stage_params, j), x, mesh=mesh,
            axis=axis, n_microbatches=n_microbatches,
            stage_carry=carry_j, shared=shared, batch_axis=batch_axis,
        )
        new_carries.append(new_c)
    if stage_carry is None:
        return x, None
    new_carry = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves).reshape(
            (S_total,) + leaves[0].shape[1:]
        ),
        *new_carries,
    )
    return x, new_carry
