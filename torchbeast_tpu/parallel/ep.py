"""Expert-parallel shardings over an `expert` mesh axis.

Companion to models/moe.py: the MoE layer keeps every expert-stacked
tensor (`w_in [E, d, ff]`, dispatched activations `[E, C, d]`) leading-axis
`E`; sharding that axis over `expert` places one slice of the experts per
chip and XLA lowers the dispatch/combine einsums into all-to-alls over ICI
— the canonical GShard layout, with zero hand-written collectives.

This module derives the param-pytree shardings (by the `[E, ...]` leading-
dim convention) so drivers and the multichip dryrun can place params
without knowing the model's internals.
"""

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def expert_param_shardings(
    mesh: Mesh, params: Any, axis: str = "expert"
) -> Any:
    """params-pytree of NamedShardings: the MoE expert kernels — leaves
    NAMED `w_in`/`w_out` (models/moe.py's convention) whose leading dim
    divides evenly over the `expert` axis — shard that dim; everything
    else replicated.

    Shape heuristics alone are deliberately not trusted: a `[d, E]`
    router kernel, an `[E, ff]` expert bias, or a `[H, hd, d]` attention
    out-projection with H == E would all false-positive. Leaf names alone
    are not either: PipelinedMLPNet's stage params reuse `w_in`/`w_out`
    with a `[S, d, ff]` layout that would silently shard over the wrong
    axis. So the rule additionally requires the leaf's scope to carry the
    MoEFFN structural signature — a sibling `router` submodule in the
    same dict (models/moe.py always pairs the expert kernels with their
    router; no other model family does). The biases stay replicated
    (tiny — replication is free; the activation sharding constraints in
    models/moe.py keep the expert compute sharded regardless).
    """
    E = mesh.shape[axis]
    expert_kernel_names = {"w_in", "w_out"}

    def tok(entry):
        # One tokenization for dict keys, namedtuple fields (optax
        # states), and sequence positions — used for BOTH scope
        # discovery and the rule below, so they cannot disagree.
        for attr in ("key", "name", "idx"):
            if hasattr(entry, attr):
                return getattr(entry, attr)
        return None

    # Scopes (path prefixes) that structurally look like a MoEFFN: they
    # contain a `router` entry alongside the expert kernels. Derived from
    # the flattened leaf paths (NOT a hand-rolled container walk) so the
    # signature is found at any nesting depth — including params-shaped
    # subtrees inside optax state tuples/namedtuples, which polybeast
    # places with this same rule for donation-safe opt_state sharding.
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    moe_scopes = set()
    for path, _leaf in flat:
        toks = tuple(tok(p) for p in path)
        for i, t in enumerate(toks):
            if t == "router":
                moe_scopes.add(toks[:i])

    def rule(path, leaf):
        name = tok(path[-1]) if path else None
        scope = tuple(tok(p) for p in path[:-1])
        if (
            E > 1
            and name in expert_kernel_names
            and scope in moe_scopes
            and hasattr(leaf, "ndim")
            and leaf.ndim >= 3
            and leaf.shape[0] % E == 0
        ):
            return NamedSharding(mesh, P(axis))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(rule, params)


def place_expert_params(mesh: Mesh, params: Any, axis: str = "expert"):
    shardings = expert_param_shardings(mesh, params, axis)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)
