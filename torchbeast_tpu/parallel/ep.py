"""Expert-parallel shardings over an `expert` mesh axis.

Companion to models/moe.py: the MoE layer keeps every expert-stacked
tensor (`w_in [E, d, ff]`, dispatched activations `[E, C, d]`) leading-axis
`E`; sharding that axis over `expert` places one slice of the experts per
chip and XLA lowers the dispatch/combine einsums into all-to-alls over ICI
— the canonical GShard layout, with zero hand-written collectives.

This module derives the param-pytree shardings (by the `[E, ...]` leading-
dim convention) so drivers and the multichip dryrun can place params
without knowing the model's internals.
"""

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def expert_param_shardings(
    mesh: Mesh, params: Any, axis: str = "expert"
) -> Any:
    """params-pytree of NamedShardings: the MoE expert kernels — leaves
    NAMED `w_in`/`w_out` (models/moe.py's convention) whose leading dim
    divides evenly over the `expert` axis — shard that dim; everything
    else replicated.

    Shape heuristics alone are deliberately not trusted: a `[d, E]`
    router kernel, an `[E, ff]` expert bias, or a `[H, hd, d]` attention
    out-projection with H == E would all false-positive. The biases stay
    replicated (tiny — replication is free; the activation sharding
    constraints in models/moe.py keep the expert compute sharded
    regardless).
    """
    E = mesh.shape[axis]
    expert_kernel_names = {"w_in", "w_out"}

    def rule(path, leaf):
        name = path[-1].key if path and hasattr(path[-1], "key") else None
        if (
            E > 1
            and name in expert_kernel_names
            and hasattr(leaf, "ndim")
            and leaf.ndim >= 3
            and leaf.shape[0] % E == 0
        ):
            return NamedSharding(mesh, P(axis))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(rule, params)


def place_expert_params(mesh: Mesh, params: Any, axis: str = "expert"):
    shardings = expert_param_shardings(mesh, params, axis)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)
