"""Tensor-parallel param shardings over the mesh's `model` axis.

The reference has no tensor parallelism (its nets are small conv+LSTM,
SURVEY.md §2.3) and these nets don't need it either — but the mesh carries
a `model` axis precisely so wider models can shard without changing the
training loop. This module derives a params-pytree of NamedShardings:
matrix kernels shard their OUTPUT dim over `model`; biases and conv
kernels stay replicated (conv channels here are far below MXU tile sizes).
XLA inserts the all-gathers/reduce-scatters implied by the shardings — no
hand-written collectives.

Used by make_parallel_update_step(..., param_shardings=...) and
demonstrated in __graft_entry__.dryrun_multichip on a (data x model) mesh.
"""

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def dense_kernel_shardings(mesh: Mesh, params: Any) -> Any:
    """params-pytree of NamedShardings: 2-D kernels -> P(None, "model"),
    everything else replicated."""
    model_size = mesh.shape["model"]

    def rule(leaf):
        if (
            model_size > 1
            and hasattr(leaf, "ndim")
            and leaf.ndim == 2
            and leaf.shape[1] % model_size == 0
        ):
            return NamedSharding(mesh, P(None, "model"))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, params)


def place_params(mesh: Mesh, params: Any, shardings: Any) -> Any:
    return jax.tree_util.tree_map(jax.device_put, params, shardings)
