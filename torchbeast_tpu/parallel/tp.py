"""Tensor-parallel param shardings over the mesh's `model` axis.

The reference has no tensor parallelism (its nets are small conv+LSTM,
SURVEY.md §2.3) and these nets don't need it either — but the mesh carries
a `model` axis precisely so wider models can shard without changing the
training loop. Two levels:

- `dense_kernel_shardings`: the generic rule — 2-D matrix kernels shard
  their OUTPUT dim over `model`, everything else replicated. Right for
  the conv+LSTM families (conv channels are far below MXU tile sizes);
  every sharded layer implies a gather, acceptable at their widths.
- `transformer_tp_shardings`: Megatron-style COLUMN/ROW pairing for the
  transformer tower — q/k/v projections and the FFN up-projection are
  column-parallel (heads / d_ff sharded), the attention out-projection
  and FFN down-projection are row-parallel, so within each block the
  activations stay sharded between the pair and XLA inserts exactly ONE
  all-reduce per attention and one per FFN (the canonical layout,
  shaped like Megatron-LM/praxis) instead of a gather per layer.

XLA inserts every collective implied by the shardings — no hand-written
collectives anywhere. Used by make_parallel_update_step(...,
param_shardings=...), polybeast's --tensor_parallel, and
__graft_entry__.dryrun_multichip on a (data x model) mesh.
"""

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def dense_kernel_shardings(mesh: Mesh, params: Any) -> Any:
    """params-pytree of NamedShardings: 2-D kernels -> P(None, "model"),
    everything else replicated."""
    model_size = mesh.shape["model"]

    def rule(leaf):
        if (
            model_size > 1
            and hasattr(leaf, "ndim")
            and leaf.ndim == 2
            and leaf.shape[1] % model_size == 0
        ):
            return NamedSharding(mesh, P(None, "model"))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, params)


def transformer_tp_shardings(
    mesh: Mesh, params: Any, axis: str = "model"
) -> Any:
    """Megatron-paired shardings for the TransformerNet param tree.

    Inside every `block_*` scope (models/transformer.py):
      q/k/v kernels [d, H, hd]  -> P(None, axis, None)   (column: heads)
      q/k/v biases  [H, hd]     -> P(axis, None)
      rel_bias      [H, M+1]    -> P(axis, None)         (per-head)
      out kernel    [H, hd, d]  -> P(axis, None, None)   (row: heads)
      FFN Dense_0   [d, ff]     -> P(None, axis), bias [ff] -> P(axis)
      FFN Dense_1   [ff, d]     -> P(axis, None)         (row)
    Everything else (LayerNorms, out/Dense_1 biases, encoder, extras,
    head, MoE leaves — EP owns those) replicated. Raises if the head
    count or FFN width does not divide the axis — a silently replicated
    half of a column/row pair would force per-layer resharding, the
    exact failure mode this layout exists to avoid.

    Works verbatim on matching trees (optax state) like the EP rule.
    """
    size = mesh.shape[axis]

    def tok(entry):
        for attr in ("key", "name", "idx"):
            if hasattr(entry, attr):
                return getattr(entry, attr)
        return None

    def check(dim, what, path):
        if dim % size != 0:
            raise ValueError(
                f"transformer_tp_shardings: {what} ({dim}) at "
                f"{jax.tree_util.keystr(path)} not divisible by the "
                f"`{axis}` axis size {size}"
            )

    def rule(path, leaf):
        toks = [tok(p) for p in path]
        in_block = any(
            isinstance(t, str) and t.startswith("block_") for t in toks
        )
        if size <= 1 or not in_block or not hasattr(leaf, "ndim"):
            return NamedSharding(mesh, P())
        name = toks[-1]
        parent = toks[-2] if len(toks) >= 2 else None
        if parent in ("q", "k", "v"):
            if name == "kernel" and leaf.ndim == 3:
                check(leaf.shape[1], "num_heads", path)
                return NamedSharding(mesh, P(None, axis, None))
            if name == "bias" and leaf.ndim == 2:
                check(leaf.shape[0], "num_heads", path)
                return NamedSharding(mesh, P(axis, None))
        if parent == "out" and name == "kernel" and leaf.ndim == 3:
            check(leaf.shape[0], "num_heads", path)
            return NamedSharding(mesh, P(axis, None, None))
        if name == "rel_bias" and leaf.ndim == 2:
            check(leaf.shape[0], "num_heads", path)
            return NamedSharding(mesh, P(axis, None))
        if parent == "Dense_0":  # FFN up-projection (column)
            if name == "kernel" and leaf.ndim == 2:
                check(leaf.shape[1], "d_ff", path)
                return NamedSharding(mesh, P(None, axis))
            if name == "bias" and leaf.ndim == 1:
                check(leaf.shape[0], "d_ff", path)
                return NamedSharding(mesh, P(axis))
        if parent == "Dense_1" and name == "kernel" and leaf.ndim == 2:
            check(leaf.shape[0], "d_ff", path)
            return NamedSharding(mesh, P(axis, None))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(rule, params)


def merge_param_shardings(*sharding_trees: Any) -> Any:
    """Leaf-wise union of sharding rules over ONE mesh: for each leaf, at
    most one input tree may be non-replicated (rules are expected to
    target disjoint leaves — e.g. the transformer TP pairing shards the
    attention/dense-FFN leaves while the EP rule shards the MoE expert
    kernels); a genuine conflict raises rather than silently picking.
    """

    def pick(path, *shardings):
        non_repl = [s for s in shardings if not s.is_fully_replicated]
        if len({s.spec for s in non_repl}) > 1:
            raise ValueError(
                "merge_param_shardings: conflicting non-replicated "
                f"shardings at {jax.tree_util.keystr(path)}: "
                f"{[s.spec for s in non_repl]}"
            )
        return non_repl[0] if non_repl else shardings[0]

    return jax.tree_util.tree_map_with_path(
        pick, sharding_trees[0], *sharding_trees[1:]
    )


def place_params(mesh: Mesh, params: Any, shardings: Any) -> Any:
    return jax.tree_util.tree_map(jax.device_put, params, shardings)
