"""Tensor-parallel param shardings over the mesh's `model` axis.

The reference has no tensor parallelism (its nets are small conv+LSTM,
SURVEY.md §2.3) and these nets don't need it either — but the mesh carries
a `model` axis precisely so wider models can shard without changing the
training loop. Two levels:

- `dense_kernel_shardings`: the generic rule — 2-D matrix kernels shard
  their INPUT dim over `model` (row-parallel: each chip holds the rows
  matching its activation shard, partial products all-reduce once per
  layer), everything else replicated. Right for the conv+LSTM families
  (conv channels are far below MXU tile sizes), and collective-wise the
  better generic rule than the column layout it replaced in ISSUE 13
  (one all-reduce vs a gather per layer).

KNOWN MISCOMPILATION (the five-PR test_dp_plus_tp numerics failure,
root-caused in ISSUE 13): this container's LEGACY GSPMD partitioner
miscompiles the grad path of a dense-TP'd RecurrentPolicyHead family —
a hidden-layer kernel sharded on the dim adjacent to the trunk
activation (either layout: column output-dim OR row input-dim) whose
activation feeds the head's uneven `concatenate([features, reward,
one_hot])`, under `jax.grad`, silently computes ~40%-wrong loss AND
gradients (forward-only programs are correct; the backward's
slice-of-concat cotangents confuse the propagation). The SHARDY
partitioner compiles the same programs correctly. Dense-TP consumers
therefore compile under `shardy_partitioner()` (below);
tests/jax_caps.py carries probes for both partitioners so the
workaround is visibly droppable when the container's XLA moves.
Megatron TP (`transformer_tp_shardings`) is unaffected: its row/column
pairs keep activations sharded between the pair and nothing concats on
a sharded dim.
- `transformer_tp_shardings`: Megatron-style COLUMN/ROW pairing for the
  transformer tower — q/k/v projections and the FFN up-projection are
  column-parallel (heads / d_ff sharded), the attention out-projection
  and FFN down-projection are row-parallel, so within each block the
  activations stay sharded between the pair and XLA inserts exactly ONE
  all-reduce per attention and one per FFN (the canonical layout,
  shaped like Megatron-LM/praxis) instead of a gather per layer.

XLA inserts every collective implied by the shardings — no hand-written
collectives anywhere. Used by make_parallel_update_step(...,
param_shardings=...), polybeast's --tensor_parallel, and
__graft_entry__.dryrun_multichip on a (data x model) mesh.
"""

import contextlib
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@contextlib.contextmanager
def shardy_partitioner():
    """Compile the programs traced/compiled inside this context under
    XLA's Shardy partitioner. Dense-TP update steps REQUIRE it on this
    container: the legacy GSPMD partitioner miscompiles their grad path
    (module docstring has the exact pattern; jax_caps probes both
    partitioners). Scoped — only compiles happening inside the context
    switch, so the rest of the process keeps the default partitioner.
    A jax without the knob is a no-op (its default partitioner is then
    whatever that jax ships)."""
    name = "jax_use_shardy_partitioner"
    if not hasattr(jax.config, name):  # pragma: no cover - future jax
        yield
        return
    old = getattr(jax.config, name)
    jax.config.update(name, True)
    try:
        yield
    finally:
        jax.config.update(name, old)


def dense_kernel_shardings(mesh: Mesh, params: Any) -> Any:
    """params-pytree of NamedShardings: 2-D kernels -> P("model", None)
    (row-parallel — see module docstring for why not column), everything
    else replicated."""
    model_size = mesh.shape["model"]

    def rule(leaf):
        if (
            model_size > 1
            and hasattr(leaf, "ndim")
            and leaf.ndim == 2
            and leaf.shape[0] % model_size == 0
        ):
            return NamedSharding(mesh, P("model", None))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, params)


def transformer_tp_shardings(
    mesh: Mesh, params: Any, axis: str = "model"
) -> Any:
    """Megatron-paired shardings for the TransformerNet param tree.

    Inside every `block_*` scope (models/transformer.py):
      q/k/v kernels [d, H, hd]  -> P(None, axis, None)   (column: heads)
      q/k/v biases  [H, hd]     -> P(axis, None)
      rel_bias      [H, M+1]    -> P(axis, None)         (per-head)
      out kernel    [H, hd, d]  -> P(axis, None, None)   (row: heads)
      FFN Dense_0   [d, ff]     -> P(None, axis), bias [ff] -> P(axis)
      FFN Dense_1   [ff, d]     -> P(axis, None)         (row)
    Everything else (LayerNorms, out/Dense_1 biases, encoder, extras,
    head, MoE leaves — EP owns those) replicated. Raises if the head
    count or FFN width does not divide the axis — a silently replicated
    half of a column/row pair would force per-layer resharding, the
    exact failure mode this layout exists to avoid.

    Works verbatim on matching trees (optax state) like the EP rule.
    """
    size = mesh.shape[axis]

    def tok(entry):
        for attr in ("key", "name", "idx"):
            if hasattr(entry, attr):
                return getattr(entry, attr)
        return None

    def check(dim, what, path):
        if dim % size != 0:
            raise ValueError(
                f"transformer_tp_shardings: {what} ({dim}) at "
                f"{jax.tree_util.keystr(path)} not divisible by the "
                f"`{axis}` axis size {size}"
            )

    def rule(path, leaf):
        toks = [tok(p) for p in path]
        in_block = any(
            isinstance(t, str) and t.startswith("block_") for t in toks
        )
        if size <= 1 or not in_block or not hasattr(leaf, "ndim"):
            return NamedSharding(mesh, P())
        name = toks[-1]
        parent = toks[-2] if len(toks) >= 2 else None
        if parent in ("q", "k", "v"):
            if name == "kernel" and leaf.ndim == 3:
                check(leaf.shape[1], "num_heads", path)
                return NamedSharding(mesh, P(None, axis, None))
            if name == "bias" and leaf.ndim == 2:
                check(leaf.shape[0], "num_heads", path)
                return NamedSharding(mesh, P(axis, None))
        if parent == "out" and name == "kernel" and leaf.ndim == 3:
            check(leaf.shape[0], "num_heads", path)
            return NamedSharding(mesh, P(axis, None, None))
        if name == "rel_bias" and leaf.ndim == 2:
            check(leaf.shape[0], "num_heads", path)
            return NamedSharding(mesh, P(axis, None))
        if parent == "Dense_0":  # FFN up-projection (column)
            if name == "kernel" and leaf.ndim == 2:
                check(leaf.shape[1], "d_ff", path)
                return NamedSharding(mesh, P(None, axis))
            if name == "bias" and leaf.ndim == 1:
                check(leaf.shape[0], "d_ff", path)
                return NamedSharding(mesh, P(axis))
        if parent == "Dense_1" and name == "kernel" and leaf.ndim == 2:
            check(leaf.shape[0], "d_ff", path)
            return NamedSharding(mesh, P(axis, None))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(rule, params)


def merge_param_shardings(*sharding_trees: Any) -> Any:
    """Leaf-wise union of sharding rules over ONE mesh: for each leaf, at
    most one input tree may be non-replicated (rules are expected to
    target disjoint leaves — e.g. the transformer TP pairing shards the
    attention/dense-FFN leaves while the EP rule shards the MoE expert
    kernels); a genuine conflict raises rather than silently picking.
    """

    def pick(path, *shardings):
        non_repl = [s for s in shardings if not s.is_fully_replicated]
        if len({s.spec for s in non_repl}) > 1:
            raise ValueError(
                "merge_param_shardings: conflicting non-replicated "
                f"shardings at {jax.tree_util.keystr(path)}: "
                f"{[s.spec for s in non_repl]}"
            )
        return non_repl[0] if non_repl else shardings[0]

    return jax.tree_util.tree_map_with_path(
        pick, sharding_trees[0], *sharding_trees[1:]
    )


def place_params(mesh: Mesh, params: Any, shardings: Any) -> Any:
    return jax.tree_util.tree_map(jax.device_put, params, shardings)
