from torchbeast_tpu.parallel.dp import (  # noqa: F401
    initialize_distributed,
    make_parallel_update_step,
    replicate,
    shard_batch,
)
from torchbeast_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    create_mesh,
    replicated,
    state_sharding,
)
from torchbeast_tpu.parallel.ep import (  # noqa: F401
    expert_param_shardings,
    place_expert_params,
)
from torchbeast_tpu.parallel.sebulba import (  # noqa: F401
    SebulbaServing,
    ShardedStateTables,
    SliceRouter,
    build_sebulba_serving,
)
from torchbeast_tpu.parallel.pp import (  # noqa: F401
    pipeline_apply,
    stack_stages,
    stage_param_shardings,
)
from torchbeast_tpu.parallel.tp import (  # noqa: F401
    dense_kernel_shardings,
    merge_param_shardings,
    place_params,
    shardy_partitioner,
    transformer_tp_shardings,
)
