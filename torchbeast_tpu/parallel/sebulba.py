"""Sebulba serving: per-slice pinned inference for the device split.

The Podracer Sebulba architecture (arXiv:2104.06272, PAPERS.md) splits a
pod into dedicated inference slices and a learner mesh. This module owns
the SERVING half for the async driver: given a resolved
`runtime.placement.DeviceSplit` and the learner's `PolicySnapshotStore`,
it builds one serving stack per inference slice —

- a `DynamicBatcher` of its own (telemetry series
  `inference.slice.<i>.*`), so a slow slice backs up its own queue
  instead of head-of-line-blocking the others;
- a `DeviceStateTable` PINNED to the slice device (the table buffer,
  slot ids, and env inputs are all committed there — zero cross-slice
  agent-state traffic, pinned by the transfer-guard test in
  tests/test_sebulba.py);
- `ReplicaServingHooks` pinned to the same device: every batch serves
  from the latest `PolicySnapshotStore` snapshot placed device-to-device
  via `latest_on` (no host round-trip), stamps the true `policy_lag`
  into the reply, and drives the health machine per slice
  (`slice<i>_lag` keys) when lag exceeds --max_policy_lag;
- an `inference_loop` body ready for the InferenceSupervisor.

Routing is the `SliceRouter`: a batcher-shaped facade the actor pool
talks to unchanged. Requests carrying a `slot` id (the device-resident
acting path) route by the split's STATIC hash-by-slot assignment — an
actor's slot lives on one slice for the life of the run, across
reconnects and serving restarts, so slot tables never migrate between
devices. Stateless requests (no slot, nothing resident to migrate)
round-robin for load balance.

Lag semantics under the split: unlike replica serving, there is no
central live-params path to fall back to — the live params live on the
learner mesh, and serving from them would put acting batches back on
learner chips (exactly what the split removes). A slice whose snapshot
exceeds the lag budget therefore keeps serving the NEWEST snapshot it
has while the health machine reports DEGRADED (keyed per slice) until a
fresh publish lands — same stamping, same budget, same recovery
transitions as the replica path.
"""

import logging
import threading
from typing import Any, Callable, List, Optional

import numpy as np

from torchbeast_tpu import telemetry
from torchbeast_tpu.runtime.inference import inference_loop
from torchbeast_tpu.runtime.placement import DeviceSplit

log = logging.getLogger(__name__)


class SliceStack:
    """One inference slice's serving resources."""

    def __init__(self, index: int, device, batcher, state_table, hooks,
                 loop_fn: Callable[[], None]):
        self.index = index
        self.device = device
        self.batcher = batcher
        self.state_table = state_table
        self.hooks = hooks
        self.loop_fn = loop_fn


class ShardedStateTables:
    """The actor-pool / supervisor / chaos view over per-slice tables.

    The pool reads boundary state (`read_slot`) and resets slots on
    (re)connect; the InferenceSupervisor rebuilds on poison; the chaos
    controller pokes `poison()`. Each call routes to (or fans out over)
    the per-slice tables by the split's static slot hash, so callers
    keep the single-table API they had before the split.
    """

    def __init__(self, split: DeviceSplit, tables: List):
        if len(tables) != split.n_slices:
            raise ValueError(
                f"{len(tables)} tables for {split.n_slices} slices"
            )
        self._split = split
        self._tables = list(tables)
        self.num_slots = tables[0].num_slots
        self.initial_state_host = tables[0].initial_state_host

    def table_for_slot(self, slot: int):
        return self._tables[self._split.slice_for_slot(slot)]

    @property
    def trash_slot(self) -> int:
        return self._tables[0].trash_slot

    def read_slot(self, slot: int) -> Any:
        return self.table_for_slot(slot).read_slot(slot)

    def reset(self, slots) -> None:
        # Group by owning slice: one reset dispatch per touched table.
        by_slice = {}
        for slot in np.asarray(slots).reshape(-1):
            by_slice.setdefault(
                self._split.slice_for_slot(int(slot)), []
            ).append(int(slot))
        for idx, group in by_slice.items():
            self._tables[idx].reset(group)

    @property
    def poisoned(self) -> bool:
        """Any slice poisoned: the supervisor rebuilds ALL of them as
        one recovery event (serving threads share one restart
        generation, so per-slice rebuilds would double-count)."""
        return any(t.poisoned for t in self._tables)

    def poison(self) -> None:
        """Chaos hook: one poison event poisons every slice (the
        supervisor's rebuild is all-or-nothing either way)."""
        for t in self._tables:
            t.poison()

    def rebuild(self) -> None:
        for t in self._tables:
            if t.poisoned:
                t.rebuild()


class SliceRouter:
    """Batcher-shaped facade routing actor requests to their slice.

    Shaped like a DynamicBatcher from the actor pool's side
    (compute/size/is_closed), same as serving.ReplicaRouter. Requests
    with a `slot` leaf route by the split's static hash; slot-less
    (stateless-model) requests round-robin — they carry no resident
    state, so there is nothing to keep pinned.
    """

    def __init__(self, split: DeviceSplit, stacks: List[SliceStack],
                 registry=None):
        self._split = split
        self._stacks = stacks
        self._rr_lock = threading.Lock()
        self._rr = 0  # guarded-by: self._rr_lock
        reg = registry if registry is not None else telemetry.get_registry()
        self._c_requests = [
            reg.counter(f"inference.slice.{s.index}.requests")
            for s in stacks
        ]

    def _slice_for(self, inputs) -> int:
        if isinstance(inputs, dict) and "slot" in inputs:
            slot = int(np.asarray(inputs["slot"]).reshape(-1)[0])
            return self._split.slice_for_slot(slot)
        with self._rr_lock:
            self._rr = (self._rr + 1) % len(self._stacks)
            return self._rr

    def compute(self, inputs, trace=None):
        idx = self._slice_for(inputs)
        stack = self._stacks[idx]
        # Per-request lag gate: with a dedicated slice there is no
        # fresher fallback than the newest snapshot, so the return
        # value is advisory — the call's job is driving the health
        # machine's per-slice keyed degradation/recovery transitions.
        if stack.hooks is not None:
            stack.hooks.serving_ok()
        self._c_requests[idx].inc()
        if trace is not None:
            out = stack.batcher.compute(inputs, trace=trace)
        else:
            out = stack.batcher.compute(inputs)
        return out

    def size(self) -> int:
        return sum(s.batcher.size() for s in self._stacks)

    def is_closed(self) -> bool:
        return self._stacks[0].batcher.is_closed()

    def close_all(self) -> None:
        for s in self._stacks:
            try:
                s.batcher.close()
            except RuntimeError:
                pass  # already closed


class SebulbaServing:
    """The assembled serving side of a device split."""

    def __init__(self, split: DeviceSplit, stacks: List[SliceStack],
                 router: SliceRouter,
                 state_tables: Optional[ShardedStateTables]):
        self.split = split
        self.stacks = stacks
        self.router = router
        self.state_tables = state_tables

    @property
    def loop_fns(self) -> List[Callable[[], None]]:
        return [s.loop_fn for s in self.stacks]

    def gauge_tick(self, registry=None) -> Callable[[], None]:
        """A DriverTelemetry tick callback keeping the per-slice depth
        gauges fresh on every exported line."""
        reg = (
            registry if registry is not None else telemetry.get_registry()
        )
        pairs = [
            (reg.gauge(f"inference.slice.{s.index}.depth"), s.batcher)
            for s in self.stacks
        ]

        def tick():
            for gauge, batcher in pairs:
                gauge.set(batcher.size())

        return tick


def slice_gauge_snapshot(registry=None, prefix: str = "inference.slice."):
    """{name: value} of the per-slice serving instruments — the fleet
    heartbeat payload (fleet/coordinator.py `set_gauges_source`): a
    remote host ships its `inference.slice.<i>.*` gauges and counters
    to the lead every heartbeat, where NativeTelemetryFolder re-exports
    them as `host<r>.inference.slice.<i>.*`. Histograms are skipped —
    heartbeats carry scalars, not bucket dicts."""
    reg = registry if registry is not None else telemetry.get_registry()
    out = {}
    for name, inst in reg.instruments().items():
        if not name.startswith(prefix):
            continue
        value = getattr(inst, "value", None)
        if callable(value):  # Counter / Gauge; Histogram has no value()
            out[name] = float(value())
    return out


def build_sebulba_serving(
    split: DeviceSplit,
    store,
    *,
    num_slots: int,
    max_batch_size: int,
    timeout_ms: float,
    max_policy_lag: int,
    rng_seed: int = 0,
    initial_state: Any = None,
    table_act_fn: Optional[Callable] = None,
    legacy_act_fn: Optional[Callable] = None,
    input_filter: Optional[Callable] = None,
    health=None,
    registry=None,
    admission=None,
    throttle_fn: Optional[Callable] = None,
    pipelined: bool = False,
    batch_dim: int = 1,
    batcher_factory: Optional[Callable] = None,
) -> SebulbaServing:
    """Assemble one serving stack per inference slice.

    `batcher_factory(i, name)` overrides per-slice batcher
    construction — the native serving plane (ISSUE 16) passes a
    factory returning C++ `_tbt_core.DynamicBatcher`s so the actor
    pool's C++ SliceRouter fans out without touching Python, while
    the Python serving loops (and the state tables, hooks, and
    telemetry prefixes built here) stay identical.

    `initial_state` + `table_act_fn`: the device-resident path — one
    pinned DeviceStateTable per slice, context (snapshot params, rng)
    provided per batch by the slice's hooks. With `initial_state=None`
    the legacy path serves instead: `legacy_act_fn(env, state,
    batch_size, ctx)` receives the hook ctx as its 4th argument (the
    replica act-path shape).

    One shared `admission` controller gates every slice's batcher (the
    serving.* counters aggregate; the depth bound applies per queue).

    Known trade-off: every slice's table allocates the FULL
    `num_slots`+1 rows although the static hash routes only
    ~1/n_slices of the slots to it — slot ids stay GLOBAL, so the
    pool, the facade, and the trash-slot padding all share one id
    space with no remap layer. At recurrent-state sizes (KBs/slot)
    the duplication is noise; if a future model carries MBs of state
    per slot, size tables per owned-slot-count with a
    slice_for_slot-derived row remap (its own change: the remap
    touches every slot-framing consumer).
    """
    from torchbeast_tpu.runtime.queues import DynamicBatcher

    reg = registry if registry is not None else telemetry.get_registry()
    stateful = initial_state is not None
    if stateful and table_act_fn is None:
        raise ValueError("stateful slices need table_act_fn")
    if not stateful and legacy_act_fn is None:
        raise ValueError("stateless slices need legacy_act_fn")

    stacks = []
    tables = []
    for i, device in enumerate(split.inference_devices):
        name = f"inference.slice.{i}"
        if batcher_factory is not None:
            batcher = batcher_factory(i, name)
        else:
            batcher = DynamicBatcher(
                batch_dim=batch_dim,
                minimum_batch_size=1,
                maximum_batch_size=max_batch_size,
                timeout_ms=timeout_ms,
                telemetry_name=name,
                admission=admission,
            )
        hooks = None
        if store is not None:
            from torchbeast_tpu.serving import ReplicaServingHooks

            hooks = ReplicaServingHooks(
                store,
                max_policy_lag=max_policy_lag,
                rng_seed=rng_seed + 7919 * (i + 1),
                health=health,
                batch_dim=batch_dim,
                registry=reg,
                device=device,
                health_key=f"slice{i}_lag",
            )
        table = None
        if stateful:
            from torchbeast_tpu.runtime.state_table import (
                DeviceStateTable,
            )

            table = DeviceStateTable(
                initial_state,
                num_slots=num_slots,
                act_fn=table_act_fn,
                context_fn=None,  # hooks provide ctx per batch
                batch_dim=batch_dim,
                input_filter=input_filter,
                device=device,
            )
            tables.append(table)

        def loop_fn(batcher=batcher, table=table, hooks=hooks, name=name):
            inference_loop(
                batcher,
                None if table is not None else legacy_act_fn,
                max_batch_size,
                batch_dim=batch_dim,
                lock=None,
                pipelined=pipelined,
                state_table=table,
                serving_hooks=hooks,
                throttle_fn=throttle_fn,
                telemetry_prefix=name,
            )

        stacks.append(
            SliceStack(i, device, batcher, table, hooks, loop_fn)
        )

    state_tables = (
        ShardedStateTables(split, tables) if stateful else None
    )
    router = SliceRouter(split, stacks, registry=reg)
    return SebulbaServing(split, stacks, router, state_tables)
