"""Device placement for the Sebulba actor/learner split (ROADMAP item 2).

The Podracer paper's Sebulba architecture (arXiv:2104.06272, PAPERS.md)
realizes IMPALA's acting/learning decoupling ON the accelerator
topology: a pod's chips are partitioned into dedicated INFERENCE slices
(each serving acting requests from a pinned policy snapshot) and a
LEARNER mesh that owns the update step — so a big update dispatch never
time-shares a chip with latency-sensitive acting batches. This module is
the partitioning half of that story: `resolve_device_split` turns the
`--device_split` flag into a `DeviceSplit` over `jax.devices()`, and the
split carries the STATIC actor->slice assignment (hash-by-slot) that
keeps each actor's device-resident state-table slot on one slice for the
life of the run.

Deliberately jax-free: callers pass the device list in (the drivers pass
`jax.devices()`, tests pass whatever they like), so parsing/validation
is unit-testable without a backend and importing this module can never
initialize one.

Spec grammar (`--device_split`):

- `""` / unset      -> no split: today's time-shared path.
- `auto`            -> 1 of every AUTO_INFERENCE_FRACTION devices (at
                       least one) serves inference, the rest learn;
                       a single-device process degrades to time-shared.
- `inf=K,learn=rest`-> K single-device inference slices, every
                       remaining device in the learner mesh.
- `inf=K,learn=M`   -> K inference slices, exactly M learner devices
                       (K + M <= device count; surplus devices idle).

Each inference device is ONE slice: acting models are small and
replicated, so a slice never needs more than a chip, and one
DeviceStateTable + serving loop per slice keeps the pinning story
trivially checkable (every table leaf lives on exactly its slice's
device — pinned by tests/test_sebulba.py under jax.transfer_guard).
"""

import dataclasses
import logging
from typing import Optional, Sequence, Tuple

log = logging.getLogger(__name__)

# `auto` pins 1 of every 4 devices to inference (floor, min 1) — the
# Sebulba paper's starting ratio for small acting models; explicit
# `inf=K` specs override it per topology.
AUTO_INFERENCE_FRACTION = 4


def _mix64(x: int) -> int:
    """splitmix64 finalizer: a deterministic, process-stable integer
    hash (Python's builtin hash() is salted per process, which would
    re-shuffle the actor->slice map on every restart)."""
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


# Salt for the SECOND splitmix64 stage of the fleet's actor->host
# assignment (fleet/topology.py): host = _mix64(slot ^ salt) % n_hosts
# while slice = _mix64(slot) % n_slices. The salt decorrelates the two
# draws — without it every slot on host h would also share slice
# h % n_slices whenever n_hosts == n_slices. Any fixed odd constant
# works; this one is the splitmix64 gamma rotated left by 1 (documented
# so nobody "fixes" it to the gamma itself, which would correlate the
# host draw with the slice draw's first addition).
FLEET_HOST_SALT = 0x3C6EF372FE94F82B


def fleet_host_for_slot(slot: int, num_hosts: int) -> int:
    """STATIC slot -> host assignment for multi-host fleets
    (fleet/topology.py): the same process-stable splitmix64 family as
    `DeviceSplit.slice_for_slot`, salted so the host draw and the
    slice draw are uncorrelated. A slot's (host, slice) pair therefore
    never migrates across actor reconnects or host restarts."""
    if num_hosts < 1:
        raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
    return _mix64(int(slot) ^ FLEET_HOST_SALT) % num_hosts


@dataclasses.dataclass(frozen=True)
class DeviceSplit:
    """A resolved device partition: N single-device inference slices +
    the learner device group."""

    spec: str
    inference_devices: Tuple
    learner_devices: Tuple

    def __post_init__(self):
        if not self.inference_devices or not self.learner_devices:
            raise ValueError(
                "a DeviceSplit needs at least one inference device and "
                "one learner device (use no split for single-device)"
            )

    @property
    def n_slices(self) -> int:
        return len(self.inference_devices)

    def slice_for_slot(self, slot: int) -> int:
        """STATIC slot -> slice assignment: a deterministic hash of the
        slot id (== actor index == connection identity in the pool), so
        an actor's table slot lives on one slice for the whole run —
        across reconnects, serving-thread restarts, and process
        restarts — and slot state never migrates between devices."""
        return _mix64(int(slot)) % self.n_slices

    def device_for_slot(self, slot: int):
        return self.inference_devices[self.slice_for_slot(slot)]

    def describe(self) -> dict:
        """JSON-serializable summary (the `device_split` telemetry
        static)."""
        return {
            "spec": self.spec,
            "inference_slices": self.n_slices,
            "learner_devices": len(self.learner_devices),
            "inference_device_ids": [
                getattr(d, "id", i)
                for i, d in enumerate(self.inference_devices)
            ],
            "learner_device_ids": [
                getattr(d, "id", i)
                for i, d in enumerate(self.learner_devices)
            ],
        }


def parse_device_split(spec: Optional[str]) -> Optional[dict]:
    """Validate the flag grammar without touching devices.

    Returns None (no split), or {"inf": int | "auto", "learn":
    int | "rest"}. Raises ValueError on a malformed spec — at flag
    parse time, before any side effects.
    """
    if spec is None:
        return None
    spec = spec.strip()
    if not spec:
        return None
    if spec == "auto":
        return {"inf": "auto", "learn": "rest"}
    parts = dict()
    for piece in spec.split(","):
        if "=" not in piece:
            raise ValueError(
                f"--device_split piece {piece!r} is not key=value "
                "(expected 'auto' or 'inf=K,learn=rest|M')"
            )
        key, _, value = piece.partition("=")
        key = key.strip()
        if key not in ("inf", "learn"):
            raise ValueError(
                f"--device_split key {key!r} unknown (inf/learn)"
            )
        if key in parts:
            raise ValueError(f"--device_split repeats {key!r}")
        parts[key] = value.strip()
    if "inf" not in parts:
        raise ValueError("--device_split needs inf=K")
    try:
        n_inf = int(parts["inf"])
    except ValueError:
        raise ValueError(
            f"--device_split inf={parts['inf']!r} is not an integer"
        ) from None
    if n_inf < 1:
        raise ValueError(f"--device_split inf={n_inf} must be >= 1")
    learn = parts.get("learn", "rest")
    if learn != "rest":
        try:
            learn = int(learn)
        except ValueError:
            raise ValueError(
                f"--device_split learn={learn!r} is neither 'rest' nor "
                "an integer"
            ) from None
        if learn < 1:
            raise ValueError(
                f"--device_split learn={learn} must be >= 1"
            )
    return {"inf": n_inf, "learn": learn}


def validate_split_composition(
    flags, split: Optional[DeviceSplit],
    parallel_flags: Sequence[str],
) -> None:
    """The composition rules BOTH drivers enforce before any side
    effects (one definition so a rule added for one driver cannot
    silently be missing from the other): no inner-parallelism flags
    alongside the split, --num_learner_devices must agree with the
    split's learner group when both are given, and the batch must
    divide over the learner devices. Driver-specific rules (poly's
    multi-host/native rejections, mono's pallas-tail check) stay at
    their call sites."""
    if split is None:
        return
    for f in parallel_flags:
        if (getattr(flags, f, 0) or 0) > 1:
            raise ValueError(
                f"--device_split does not compose with --{f} yet "
                "(the split's learner mesh is plain DP over the "
                "learner devices)"
            )
    n_learn = len(split.learner_devices)
    n_dev = getattr(flags, "num_learner_devices", 1) or 1
    if n_dev > 1 and n_dev != n_learn:
        raise ValueError(
            f"--num_learner_devices {n_dev} conflicts with "
            f"--device_split's {n_learn} learner devices (drop the "
            "flag: the split sizes the mesh)"
        )
    if flags.batch_size % n_learn != 0:
        raise ValueError(
            f"--batch_size {flags.batch_size} not divisible by the "
            f"split's {n_learn} learner devices"
        )


def resolve_device_split(
    spec: Optional[str], devices: Sequence
) -> Optional[DeviceSplit]:
    """Resolve the flag against a concrete device list.

    Returns None for no-split AND for the single-device degradation:
    on one device there is nothing to partition, so any spec (auto or
    explicit) falls back to today's time-shared path with a log line —
    the same binary runs laptop and pod.
    """
    parsed = parse_device_split(spec)
    if parsed is None:
        return None
    n = len(devices)
    if n < 2:
        log.warning(
            "--device_split %s on a single visible device: degrading "
            "to the time-shared serving path (the split needs >= 2 "
            "devices).", spec,
        )
        return None
    if parsed["inf"] == "auto":
        n_inf = max(1, n // AUTO_INFERENCE_FRACTION)
    else:
        n_inf = parsed["inf"]
    if n_inf >= n and parsed["learn"] == "rest":
        raise ValueError(
            f"--device_split inf={n_inf} leaves no learner device "
            f"({n} visible)"
        )
    if parsed["learn"] == "rest":
        n_learn = n - n_inf
    else:
        n_learn = parsed["learn"]
        if n_inf + n_learn > n:
            raise ValueError(
                f"--device_split inf={n_inf},learn={n_learn} needs "
                f"{n_inf + n_learn} devices; {n} visible"
            )
    split = DeviceSplit(
        spec=str(spec).strip(),
        inference_devices=tuple(devices[:n_inf]),
        learner_devices=tuple(devices[n_inf:n_inf + n_learn]),
    )
    log.info(
        "Device split: %d inference slice(s) %s + %d learner device(s) "
        "%s", split.n_slices,
        [getattr(d, "id", "?") for d in split.inference_devices],
        len(split.learner_devices),
        [getattr(d, "id", "?") for d in split.learner_devices],
    )
    return split
