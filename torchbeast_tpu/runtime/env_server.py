"""Environment server: hosts environments behind a streaming socket.

The reference's gRPC `EnvServer` (/root/reference/src/cc/rpcenv.cc:36-211,
driven by polybeast_env.py:61-77) re-designed over the framed-socket wire
protocol: each incoming connection gets a FRESH environment instance
(reference rpcenv.cc:72), the server sends the initial Step, then loops
recv(Action) -> env.step -> send(Step). Episode accounting and auto-reset
live in the Environment adapter (envs/environment.py), matching the
reference's server-side bookkeeping (rpcenv.cc:106-119).

Env exceptions are reported to the client as an error message frame (the
reference surfaces them as grpc INTERNAL status, rpcenv.cc:76-81).

Addresses: "unix:/path" or "host:port" (same convention as the reference's
pipes_basename, polybeast_learner.py:40-42).
"""

import logging
import os
import socket
import threading
import time
from typing import Callable

import numpy as np

from torchbeast_tpu import telemetry
from torchbeast_tpu.envs.environment import Environment
from torchbeast_tpu.runtime import wire

log = logging.getLogger(__name__)


def parse_address(address: str):
    if address.startswith("unix:"):
        return socket.AF_UNIX, address[len("unix:") :]
    host, _, port = address.rpartition(":")
    return socket.AF_INET, (host or "127.0.0.1", int(port))


def _step_to_message(step) -> dict:
    # 0-d arrays (not python scalars) so dtypes survive the wire exactly:
    # reward stays float32, done bool, counters int32.
    return {"type": "step", **{k: np.asarray(v) for k, v in step.items()}}


class EnvServer:
    """Serve env streams; one thread per connection."""

    def __init__(self, env_init: Callable, address: str):
        self._env_init = env_init
        self._address = address
        self._family, self._target = parse_address(address)
        self._sock = None
        self._threads = []
        self._conns = []
        self._conns_lock = threading.Lock()
        self._running = False
        # NB: env servers usually run as separate processes, so these
        # land in each server's OWN process registry (the learner-side
        # mirror lives in ActorPool's wire.bytes_* counters).
        reg = telemetry.get_registry()
        self._tm_conns = reg.gauge("env_server.connections")
        self._tm_bytes_in = reg.counter("env_server.bytes_in")
        self._tm_bytes_out = reg.counter("env_server.bytes_out")
        self._tm_step_s = reg.histogram("env_server.env_step_s")

    def run(self):
        """Bind and serve until stop() (reference Server.run blocks too,
        rpcenv.cc:142-156)."""
        self._sock = socket.socket(self._family, socket.SOCK_STREAM)
        if self._family == socket.AF_UNIX:
            try:
                os.unlink(self._target)
            except FileNotFoundError:
                pass
        else:
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(self._target)
        self._sock.listen(16)
        self._running = True
        log.info("EnvServer listening on %s", self._address)
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break  # socket closed by stop()
            # Register the conn BEFORE spawning its thread so a concurrent
            # stop() can never miss a just-accepted stream.
            with self._conns_lock:
                if not self._running:
                    conn.close()
                    break
                self._conns.append(conn)
            t = threading.Thread(
                target=self._serve_stream, args=(conn,), daemon=True
            )
            t.start()
            # Prune finished stream threads so reconnect-heavy workloads
            # don't grow this list unboundedly.
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def start(self):
        """Non-blocking run() in a daemon thread."""
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self):
        with self._conns_lock:
            self._running = False
        if self._sock is not None:
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self._sock.close()
        # Sever live streams too — stop() means stop, and clients with
        # reconnect enabled treat the cut as a transport failure.
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()
        if self._family == socket.AF_UNIX:
            try:
                os.unlink(self._target)
            except FileNotFoundError:
                pass

    def _serve_stream(self, conn: socket.socket):
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # unix sockets
        raw_env = self._env_init()
        env = Environment(raw_env)
        try:
            # The initial Step doubles as the env spec: remote learners
            # probe num_actions/frame shape from it instead of having to
            # build the env locally (split deployments may not have the
            # env deps on the learner host).
            from torchbeast_tpu.envs import num_actions_of

            initial = _step_to_message(env.initial())
            initial["num_actions"] = num_actions_of(raw_env)
            with self._conns_lock:
                self._tm_conns.set(len(self._conns))
            self._tm_bytes_out.inc(wire.send_message(conn, initial))
            while True:
                msg, nbytes = wire.recv_message_sized(conn)
                if msg is None:
                    break  # client hung up
                self._tm_bytes_in.inc(nbytes)
                if msg.get("type") != "action":
                    raise wire.WireError(f"Expected action, got {msg!r}")
                t0 = time.perf_counter()
                step = env.step(int(msg["action"]))
                self._tm_step_s.observe(time.perf_counter() - t0)
                self._tm_bytes_out.inc(
                    wire.send_message(conn, _step_to_message(step))
                )
        except (wire.WireError, ConnectionError, BrokenPipeError) as e:
            log.debug("Stream ended: %s", e)
        except Exception as e:  # env raised: report to client, drop stream
            log.exception("Environment raised")
            try:
                wire.send_message(
                    conn, {"type": "error", "message": f"{type(e).__name__}: {e}"}
                )
            except OSError:
                pass
        finally:
            env.close()
            conn.close()
            with self._conns_lock:
                if conn in self._conns:
                    self._conns.remove(conn)
                self._tm_conns.set(len(self._conns))


def serve_once(env_init: Callable, address: str):
    """Convenience: construct and run (blocking)."""
    EnvServer(env_init, address).run()
