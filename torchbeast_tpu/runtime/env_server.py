"""Environment server: hosts environments behind a streaming socket.

The reference's gRPC `EnvServer` (/root/reference/src/cc/rpcenv.cc:36-211,
driven by polybeast_env.py:61-77) re-designed over the framed-socket wire
protocol: each incoming connection gets a FRESH environment instance
(reference rpcenv.cc:72), the server sends the initial Step, then loops
recv(Action) -> env.step -> send(Step). Episode accounting and auto-reset
live in the Environment adapter (envs/environment.py), matching the
reference's server-side bookkeeping (rpcenv.cc:106-119).

Env exceptions are reported to the client as an error message frame (the
reference surfaces them as grpc INTERNAL status, rpcenv.cc:76-81).

Addresses: "unix:/path", "host:port" (same convention as the reference's
pipes_basename, polybeast_learner.py:40-42), or "shm:/path" — shared-
memory rings with a unix doorbell socket at /path, for env servers
co-located with the learner process (runtime/transport.py): obs/action
frames skip the socket data plane entirely.
"""

import logging
import os
import socket
import threading
import time
from typing import Callable, Optional

import numpy as np

from torchbeast_tpu import telemetry
from torchbeast_tpu.envs.environment import Environment
from torchbeast_tpu.runtime import transport as transport_lib
from torchbeast_tpu.runtime import wire

# Re-exported: parse_address lived here before the transport module
# existed and tests/drivers import it from this path.
from torchbeast_tpu.runtime.transport import parse_address  # noqa: F401

log = logging.getLogger(__name__)


def _step_to_message(step) -> dict:
    # 0-d arrays (not python scalars) so dtypes survive the wire exactly:
    # reward stays float32, done bool, counters int32.
    return {"type": "step", **{k: np.asarray(v) for k, v in step.items()}}


class EnvServer:
    """Serve env streams; one thread per connection."""

    def __init__(self, env_init: Callable, address: str,
                 max_frame_bytes: Optional[int] = None,
                 obs_ring_bytes: int = transport_lib.DEFAULT_OBS_RING_BYTES,
                 act_ring_bytes: int = transport_lib.DEFAULT_ACT_RING_BYTES):
        self._env_init = env_init
        self._address = address
        self._shm = transport_lib.is_shm_address(address)
        self._max_frame_bytes = max_frame_bytes
        self._obs_ring_bytes = obs_ring_bytes
        self._act_ring_bytes = act_ring_bytes
        self._family, self._target = parse_address(address)
        # Control fields shared between run() (its own thread under
        # start()), the per-stream threads, and stop() (caller thread):
        # all guarded by the conns lock (RACE burn-down, ISSUE 7).
        self._sock = None  # guarded-by: self._conns_lock
        self._threads = []  # guarded-by: self._conns_lock
        # Permanent stop latch: a stop() that wins the race against a
        # just-starting run() (before the listener is published) must
        # still stop it — run() re-checks this at publish time.
        self._stopped = False  # guarded-by: self._conns_lock
        self._conns = []
        # conn -> (shm segment names) for live shm streams: stop()'s
        # owner-side sweep unlinks whatever a stream thread didn't get
        # to (ISSUE 6 — SIGKILL chaos must not grow /dev/shm).
        self._ring_names = {}  # guarded-by: self._conns_lock
        self._conns_lock = threading.Lock()
        self._running = False  # guarded-by: self._conns_lock
        # NB: env servers usually run as separate processes, so these
        # land in each server's OWN process registry (the learner-side
        # mirror lives in ActorPool's wire.bytes_* counters).
        reg = telemetry.get_registry()
        self._tm_conns = reg.gauge("env_server.connections")
        self._tm_bytes_in = reg.counter("env_server.bytes_in")
        self._tm_bytes_out = reg.counter("env_server.bytes_out")
        self._tm_step_s = reg.histogram("env_server.env_step_s")

    def run(self):
        """Bind and serve until stop() (reference Server.run blocks too,
        rpcenv.cc:142-156)."""
        sock = socket.socket(self._family, socket.SOCK_STREAM)
        if self._family == socket.AF_UNIX:
            try:
                os.unlink(self._target)
            except FileNotFoundError:
                pass
        else:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind(self._target)
        sock.listen(16)
        # Publish the listener + running flag under the lock: a stop()
        # racing a just-starting run() either already latched _stopped
        # (we tear down here and never serve) or sees the published
        # socket and closes it.
        with self._conns_lock:
            if self._stopped:
                sock.close()
                if self._family == socket.AF_UNIX:
                    try:
                        os.unlink(self._target)
                    except FileNotFoundError:
                        pass
                return
            self._sock = sock
            self._running = True
        log.info("EnvServer listening on %s", self._address)
        while True:
            with self._conns_lock:
                if not self._running:
                    break
            try:
                conn, _ = sock.accept()
            except OSError:
                break  # socket closed by stop()
            # Register the conn BEFORE spawning its thread so a concurrent
            # stop() can never miss a just-accepted stream.
            with self._conns_lock:
                if not self._running:
                    conn.close()
                    break
                self._conns.append(conn)
            t = threading.Thread(
                target=self._serve_stream, args=(conn,), daemon=True
            )
            t.start()
            # Prune finished stream threads so reconnect-heavy workloads
            # don't grow this list unboundedly.
            with self._conns_lock:
                self._threads = [
                    x for x in self._threads if x.is_alive()
                ] + [t]

    def start(self):
        """Non-blocking run() in a daemon thread."""
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        with self._conns_lock:
            self._threads.append(t)

    def stop(self):
        with self._conns_lock:
            self._stopped = True
            self._running = False
            sock = self._sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            sock.close()
        # Sever live streams too — stop() means stop, and clients with
        # reconnect enabled treat the cut as a transport failure.
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()
        # Owner-side shm sweep: give the stream threads a moment to
        # close their rings (which unlinks them), then unlink whatever
        # is left. A thread wedged past the join window must not strand
        # segments in /dev/shm — unlink is safe under live mappings.
        # (Joins happen OUTSIDE the conns lock: a stream thread's
        # teardown takes it to deregister.)
        with self._conns_lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=2)
        with self._conns_lock:
            leftovers = [
                name
                for names in self._ring_names.values()
                for name in names
            ]
            self._ring_names.clear()
        for name in leftovers:
            if transport_lib.unlink_segment(name):
                log.warning(
                    "EnvServer stop(): swept leaked shm segment %s", name
                )
        if self._family == socket.AF_UNIX:
            try:
                os.unlink(self._target)
            except FileNotFoundError:
                pass

    def _serve_stream(self, conn: socket.socket):
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # unix sockets
        stream = None
        env = None
        msg = None
        try:
            # For shm addresses this creates the per-connection rings and
            # completes the handshake BEFORE the env is built, so a
            # client that never acks can't leak an env instance.
            stream = transport_lib.server_transport(
                conn, shm=self._shm,
                obs_ring_bytes=self._obs_ring_bytes,
                act_ring_bytes=self._act_ring_bytes,
                max_frame_bytes=self._max_frame_bytes,
            )
            if self._shm:
                with self._conns_lock:
                    self._ring_names[conn] = stream.segment_names
            raw_env = self._env_init()
            env = Environment(raw_env)
            # The initial Step doubles as the env spec: remote learners
            # probe num_actions/frame shape from it instead of having to
            # build the env locally (split deployments may not have the
            # env deps on the learner host).
            from torchbeast_tpu.envs import num_actions_of

            initial = _step_to_message(env.initial())
            initial["num_actions"] = num_actions_of(raw_env)
            with self._conns_lock:
                self._tm_conns.set(len(self._conns))
            self._tm_bytes_out.inc(stream.send(initial))
            while True:
                msg, nbytes = stream.recv_sized()
                if msg is None:
                    break  # client hung up
                self._tm_bytes_in.inc(nbytes)
                if msg.get("type") != "action":
                    raise wire.WireError(f"Expected action, got {msg!r}")
                t0 = time.perf_counter()
                step = env.step(int(msg["action"]))
                self._tm_step_s.observe(time.perf_counter() - t0)
                self._tm_bytes_out.inc(stream.send(_step_to_message(step)))
        except (wire.WireError, ConnectionError, BrokenPipeError,
                TimeoutError) as e:
            log.debug("Stream ended: %s", e)
        except Exception as e:  # env raised: report to client, drop stream
            log.exception("Environment raised")
            try:
                if stream is not None:
                    stream.send({
                        "type": "error",
                        "message": f"{type(e).__name__}: {e}",
                    })
            except (OSError, wire.WireError):
                pass
        finally:
            msg = None  # drop transport-buffer views before close
            if env is not None:
                env.close()
            if stream is not None:
                stream.close()  # closes conn and, for shm, the rings
            else:
                conn.close()
            with self._conns_lock:
                if conn in self._conns:
                    self._conns.remove(conn)
                # stream.close() unlinked the rings; drop them from the
                # stop() sweep's ledger.
                self._ring_names.pop(conn, None)
                self._tm_conns.set(len(self._conns))


def serve_once(env_init: Callable, address: str):
    """Convenience: construct and run (blocking)."""
    EnvServer(env_init, address).run()
