"""Transports: framed wire messages over sockets or shared-memory rings.

One abstraction, two data planes (ISSUE 3 tentpole):

- `SocketTransport` — the classic framed stream (tcp/unix), upgraded with
  per-connection SendBuffer/RecvBuffer so steady-state sends are
  scatter-gather (`socket.sendmsg` straight from numpy buffers) and
  receives are allocation-free (`recv_into` into a grow-only buffer).

- `ShmTransport` — for co-located env servers (`shm://` addresses): obs
  and action frames are written *in place* into a single-producer/
  single-consumer ring over `multiprocessing.shared_memory`, with the
  same payload encoding as the socket framing. A lightweight socket
  doorbell (1 control byte per frame) provides blocking flow control and
  crash detection: the peer dying closes the socket, which surfaces as
  the same ConnectionError/WireError teardown contract the socket
  transport has. Frames too large for the ring ride the doorbell socket
  inline (escape hatch, same framing), so correctness never depends on
  the ring capacity.

Address schemes (parse_address): "unix:/path", "host:port", and
"shm:/path" (also "shm:///path") — for shm the path names the unix
doorbell socket; the ring segments are created by the server per
connection with kernel-generated names exchanged in a handshake.

Both transports share the wire module's frame format and the
buffer-reuse lifetime rule: a decoded nest must be consumed before the
next recv on the same transport (ring frames are released, and the
RecvBuffer is overwritten, at the next recv call).
"""

# beastlint: hot-module — send/recv run per env step per connection.
# (No locks here by design: each transport is single-threaded per
# connection, so LOCK-DISCIPLINE has nothing to guard.)

import logging
import socket
import struct
import time
from typing import Any, Optional, Tuple

from torchbeast_tpu.runtime import wire

log = logging.getLogger(__name__)

# Per-direction ring capacities. Obs frames (server -> client) are the
# big ones (Atari-sized frames + scalars); actions are tiny. Capacity
# must hold >= 2 frames for the alternating env protocol to never block
# on ring space; oversized frames fall back to the doorbell socket.
DEFAULT_OBS_RING_BYTES = 4 * 1024 * 1024
DEFAULT_ACT_RING_BYTES = 256 * 1024

# Doorbell control bytes (client and server only ever *read* doorbells
# for their incoming direction, so there is no demux state). Doorbells
# are WAKEUPS, not per-frame tokens: the sender rings only when the
# ring-header waiting flag says the reader is blocked (futex-style), so
# a busy reader consumes frames with no syscalls on either side. All
# frame ORDERING lives in the ring — an oversized message leaves an
# inline marker at its ring position and its bytes follow the 0x02 byte
# on the socket, so mixed ring/inline traffic still arrives in order.
_DOORBELL_WAKE = b"\x01"  # stale ones are skipped wherever they appear
_DOORBELL_INLINE = b"\x02"  # one framed message follows on the socket

# The reader's blocking wait re-checks the ring at this period: the
# waiting-flag handshake has a (tiny) lost-wakeup window — CPython emits
# no store-load fence between the sender's head publish and its
# waiting-flag load — and the periodic re-check bounds that stall.
# 20ms (not the original 500ms): on an oversubscribed box the
# doorbell hop itself can be late or lost under scheduler pressure, and
# e2e runs showed the system settling into a degraded mode where a
# visible fraction of waits ride the recheck — a tight bound caps each
# such stall at one scheduling quantum instead of half a second, while
# an idle connection still costs only 50 wakeups/s.
# This is the INITIAL bound: per connection, AdaptiveRecheck walks it
# within [_RECHECK_MIN_MS, _RECHECK_MAX_MS] below (ISSUE 12).
_WAKE_RECHECK_S = 0.02

# Adaptive recheck policy (ISSUE 12): the fixed bound trades idle
# wakeup cost against lost-wakeup stall cost at ONE operating point,
# but the ring.doorbell_waits / ring.recheck_wakeups counters (PR 10)
# measure which regime a connection is actually in. Per window of
# _RECHECK_WINDOW armed waits: >= _RECHECK_TIGHTEN ended by the
# timeout (doorbells being lost/late — the ROADMAP metastability
# signature) HALVES the bound, floor _RECHECK_MIN_MS, so each stall
# costs less exactly when stalls are frequent; <= _RECHECK_RELAX
# (healthy byte-woken pair) DOUBLES it, cap _RECHECK_MAX_MS, back
# toward idle cheapness. All five constants are pinned cross-language
# against csrc/shm.h AND analysis/protocol.py by the ATOMIC-ORDER
# recheck check; the model checker's timeout transition covers any
# bound in the range (no-wedge only needs the recheck to stay FINITE,
# i.e. _RECHECK_MIN_MS > 0).
_RECHECK_MIN_MS = 5
_RECHECK_MAX_MS = 100
_RECHECK_WINDOW = 32
_RECHECK_TIGHTEN = 16
_RECHECK_RELAX = 4


class AdaptiveRecheck:
    """Per-connection adaptive recheck bound (single-threaded, like the
    transport that owns it). `record(True)` = a wait ended by the
    bounded timeout instead of a doorbell byte."""

    __slots__ = ("_bound_ms", "_waits", "_rechecks")

    def __init__(self):
        self._bound_ms = int(_WAKE_RECHECK_S * 1000)
        self._waits = 0
        self._rechecks = 0

    @property
    def bound_ms(self) -> int:
        return self._bound_ms

    def timeout_s(self) -> float:
        return self._bound_ms / 1000.0

    def record(self, recheck: bool) -> None:
        self._waits += 1
        if recheck:
            self._rechecks += 1
        if self._waits < _RECHECK_WINDOW:
            return
        if self._rechecks >= _RECHECK_TIGHTEN:
            self._bound_ms = max(_RECHECK_MIN_MS, self._bound_ms // 2)
        elif self._rechecks <= _RECHECK_RELAX:
            self._bound_ms = min(_RECHECK_MAX_MS, self._bound_ms * 2)
        self._waits = self._rechecks = 0

# Doorbell-wait observability (ISSUE 10 satellite; same lazy-resolve
# idiom as wire._instruments so --no_telemetry runs get no-ops):
# ring.doorbell_waits counts every armed+blocked doorbell wait,
# ring.recheck_wakeups the subset ended by the bounded recheck instead
# of a doorbell byte. The ratio is the ROADMAP metastability hunt's
# signal — a healthy pair wakes on bytes, a degraded one rides the
# recheck.
_tm_doorbell_waits = None
_tm_recheck_wakeups = None


def _ring_instruments():
    global _tm_doorbell_waits, _tm_recheck_wakeups
    if _tm_doorbell_waits is None:
        from torchbeast_tpu import telemetry

        reg = telemetry.get_registry()
        # beastlint: disable=RACE  benign double-init: the registry's get-or-create is idempotent, so racing threads store the SAME instrument object; each store is GIL-atomic
        _tm_doorbell_waits = reg.counter("ring.doorbell_waits")
        # beastlint: disable=RACE  same idempotent lazy-init as _tm_doorbell_waits above
        _tm_recheck_wakeups = reg.counter("ring.recheck_wakeups")
    return _tm_doorbell_waits, _tm_recheck_wakeups

# Before arming the waiting flag, the reader spins on the head counter
# for this long: a producer running at a similar cadence lands its next
# frame inside the spin window, keeping BOTH sides syscall-free. Without
# it, a matched producer/consumer pair oscillates around an empty ring
# and pays wake+block syscalls per frame (measured: halves large-frame
# throughput on this sandbox, whose emulated syscalls cost ~20-70us).
_EMPTY_SPIN_S = 100e-6


def parse_address(address: str):
    """Address -> (socket family, connect/bind target). shm addresses
    resolve to their unix doorbell socket."""
    if address.startswith("unix:"):
        return socket.AF_UNIX, address[len("unix:") :]
    if address.startswith("shm:"):
        return socket.AF_UNIX, shm_socket_path(address)
    host, _, port = address.rpartition(":")
    return socket.AF_INET, (host or "127.0.0.1", int(port))


def is_shm_address(address: str) -> bool:
    return address.startswith("shm:")


def shm_socket_path(address: str) -> str:
    """shm:/tmp/x and shm:///tmp/x -> /tmp/x (the doorbell socket path)."""
    path = address[len("shm:") :]
    if path.startswith("//"):
        path = path[2:]
    if not path:
        raise ValueError(f"Empty shm address: {address!r}")
    return path


def _tracker(action: str, shm) -> None:
    """register/unregister a SharedMemory segment with this process's
    multiprocessing.resource_tracker (best-effort: tracker internals are
    private and have moved between Python versions)."""
    try:
        from multiprocessing import resource_tracker

        getattr(resource_tracker, action)(
            getattr(shm, "_name", shm.name), "shared_memory"
        )
    except Exception:  # pragma: no cover
        log.debug("resource_tracker %s failed", action, exc_info=True)


def unlink_segment(name: str) -> bool:
    """Best-effort unlink of a SharedMemory segment by name — the crash
    sweep for segments whose owner died without cleanup (a SIGKILL'd
    env server). Returns True when this call removed the segment; False
    when it was already gone (the owner, or another sweeper, got there
    first)."""
    from multiprocessing import shared_memory

    try:
        seg = shared_memory.SharedMemory(name=name)
    except (FileNotFoundError, OSError):
        return False
    try:
        seg.unlink()  # unregisters the attach's tracker entry too
        return True
    except FileNotFoundError:
        _tracker("unregister", seg)  # nothing unlinked: rebalance
        return False
    finally:
        try:
            seg.close()
        except BufferError:  # pragma: no cover
            log.debug("sweep close of %s kept a view alive", name)


class SocketTransport:
    """Framed messages over a connected stream socket, with reusable
    per-connection encode/receive buffers."""

    def __init__(self, sock: socket.socket,
                 max_frame_bytes: Optional[int] = None,
                 recv_timeout_s: Optional[float] = None):
        self._sock = sock
        self._max_frame_bytes = max_frame_bytes
        if recv_timeout_s is not None:
            # Bounded receives (spec probes): a peer that accepts but
            # never sends surfaces as socket.timeout (an OSError), not
            # a hang.
            sock.settimeout(recv_timeout_s)
        self._send_buf = wire.SendBuffer()
        self._recv_buf = wire.RecvBuffer()

    def send(self, value: Any) -> int:
        return wire.send_message(self._sock, value, buf=self._send_buf)

    def recv_sized(self) -> Tuple[Any, int]:
        return wire.recv_message_sized(
            self._sock, buf=self._recv_buf,
            max_frame_bytes=self._max_frame_bytes,
        )

    def recv(self) -> Any:
        return self.recv_sized()[0]

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class ShmRing:
    """Single-producer single-consumer byte ring over a SharedMemory
    segment.

    Layout: [0:8) head, [8:16) tail, [16:24) capacity, [24:32) the
    consumer's waiting flag (all u64le), data at [64, 64+capacity).
    head/tail are monotonic byte counters (head producer-owned, tail
    consumer-owned); free = capacity-(head-tail). Frames are contiguous
    [u32 length][payload]; when a frame would straddle the end, a u32
    0xFFFFFFFF wrap marker (or <4 bytes of tail room) skips the
    remainder; a u32 0xFFFFFFFE entry marks a message that rides the
    doorbell socket inline instead (too big for the ring). Aligned
    8-byte counter stores through a cast memoryview are single stores;
    x86 store ordering makes the data-then-head publish safe without
    fences.
    """

    HEADER_BYTES = 64
    _WRAP = 0xFFFFFFFF
    _INLINE = 0xFFFFFFFE
    _HEAD, _TAIL, _CAP, _WAITING = 0, 1, 2, 3

    def __init__(self, shm, capacity: int, owner: bool,
                 close_shm: bool = True):
        self._shm = shm
        self._owner = owner
        # False for in-process ring pairs sharing one mapping (shm_pipe):
        # only one end may unmap/unlink.
        self._close_shm = close_shm
        self._capacity = capacity
        self._publish_head = 0
        self._u64 = shm.buf[:32].cast("Q")
        self._data = shm.buf[self.HEADER_BYTES : self.HEADER_BYTES + capacity]

    # -- construction -----------------------------------------------------
    @classmethod
    def create(cls, capacity: int) -> "ShmRing":
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(
            create=True, size=cls.HEADER_BYTES + capacity
        )
        ring = cls(shm, capacity, owner=True)
        ring._u64[cls._HEAD] = 0
        ring._u64[cls._TAIL] = 0
        ring._u64[cls._CAP] = capacity
        ring._u64[cls._WAITING] = 0
        return ring

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(name=name)
        # The creator owns the unlink; detach this process's
        # resource_tracker registration so client exit doesn't try to
        # unlink (and warn about) segments it merely attached to. (The
        # owner re-registers before its unlink, so the create+attach-in-
        # one-process case stays balanced too — see close().)
        _tracker("unregister", shm)
        capacity = shm.buf[:32].cast("Q")[cls._CAP]
        if capacity <= 0 or cls.HEADER_BYTES + capacity > shm.size:
            shm.close()
            raise wire.WireError(
                f"shm ring {name}: bad capacity {capacity}"
            )
        return cls(shm, int(capacity), owner=False)

    @property
    def name(self) -> str:
        return self._shm.name

    @property
    def capacity(self) -> int:
        return self._capacity

    def max_frame_bytes(self) -> int:
        """Largest frame the transport routes through the ring. Frames
        never wrap mid-frame, so placing one at position `pos` may
        require skipping `capacity - pos` tail bytes first; only frames
        <= capacity/2 are placeable at EVERY position (skip + frame <=
        capacity, the most free space a drained ring can offer). Bigger
        frames would be position-dependently unplaceable — a permanent
        _wait_free stall — so they ride the inline socket path instead."""
        return self._capacity // 2 - 4

    # -- producer ---------------------------------------------------------
    def write_frame(self, views, total: int,
                    timeout_s: float = 120.0, peer_check=None) -> None:
        """Write one frame ([u32 total][views...]) into the ring. Blocks
        (polling) while the ring lacks space; a stalled reader surfaces
        as WireError after timeout_s, and a DEAD one promptly via
        peer_check (called periodically during the wait — ShmTransport
        passes a doorbell-socket probe so crash detection stays fast
        even for a writer that never touches the socket)."""
        cap = self._capacity
        need = 4 + total
        if need > cap:
            raise wire.WireError(
                f"Frame of {total} bytes exceeds ring capacity {cap}"
            )
        pos = self._reserve(need, timeout_s, peer_check)
        data = self._data
        struct.pack_into("<I", data, pos, total)
        off = pos + 4
        for v in views:
            n = len(v)
            data[off : off + n] = v
            off += n
        # Publish after the payload bytes are in place.
        self._u64[self._HEAD] = self._publish_head

    def write_inline_marker(self, timeout_s: float = 120.0,
                            peer_check=None) -> None:
        """Reserve this message's ORDER SLOT in the ring while its bytes
        ride the doorbell socket (too big for the ring): the reader hits
        the marker at the right position in the stream and switches to
        the socket for one message."""
        pos = self._reserve(4, timeout_s, peer_check)
        struct.pack_into("<I", self._data, pos, self._INLINE)
        self._u64[self._HEAD] = self._publish_head

    def _reserve(self, need: int, timeout_s: float, peer_check=None) -> int:
        """Wait for `need` contiguous bytes at head (writing a wrap
        marker if the tail room is short); returns the data offset to
        write at and stages the post-publish head in _publish_head."""
        cap = self._capacity
        head = self._u64[self._HEAD]
        pos = head % cap
        tail_room = cap - pos
        if need > tail_room:
            self._wait_free(head, tail_room + need, timeout_s, peer_check)
            if tail_room >= 4:
                struct.pack_into("<I", self._data, pos, self._WRAP)
            head += tail_room
            pos = 0
        else:
            self._wait_free(head, need, timeout_s, peer_check)
        self._publish_head = head + need
        return pos

    def _wait_free(self, head: int, need: int, timeout_s: float,
                   peer_check=None) -> None:
        deadline = None
        ticks = 0
        while self._capacity - (head - self._u64[self._TAIL]) < need:
            if deadline is None:
                deadline = time.monotonic() + timeout_s
            elif time.monotonic() > deadline:
                raise wire.WireError(
                    f"shm ring full for {timeout_s}s (reader stalled?)"
                )
            ticks += 1
            if peer_check is not None and ticks % 200 == 0:  # ~every 20ms
                peer_check()
            time.sleep(0.0001)

    def reader_waiting(self) -> bool:
        return self._u64[self._WAITING] != 0

    def poke(self, pos: int, data: bytes) -> None:
        """Write raw bytes into the DATA region at offset `pos` — the
        chaos-injection/corruption-test hook (resilience/chaos.py,
        tests/test_shm_transport.py). Never called on a healthy path."""
        self._data[pos : pos + len(data)] = data

    def unlink(self) -> None:
        """Best-effort unlink regardless of ownership — the crash sweep
        for a dead owner. Safe against a live peer: segments are
        per-connection and never re-attached, so unlinking early only
        turns the owner's own later unlink into a FileNotFoundError
        no-op (existing mappings stay valid until unmapped)."""
        _tracker("register", self._shm)  # balance unlink's unregister
        try:
            self._shm.unlink()
        except FileNotFoundError:
            _tracker("unregister", self._shm)  # nothing was unlinked

    # -- consumer ---------------------------------------------------------
    def has_frame(self) -> bool:
        return self._u64[self._HEAD] != self._u64[self._TAIL]

    def set_waiting(self, value: bool) -> None:
        self._u64[self._WAITING] = 1 if value else 0

    def read_frame(self) -> Tuple[Optional[memoryview], int]:
        """(read-only payload view, advance) for the frame at tail — the
        view is None for an inline marker (the message bytes follow on
        the doorbell socket). The caller must know a frame is available
        (has_frame()) and call release(advance) once the frame is
        consumed. Corrupt ring state surfaces as WireError."""
        cap = self._capacity
        tail = self._u64[self._TAIL]
        head = self._u64[self._HEAD]
        if head - tail < 4:
            raise wire.WireError("shm ring: read without a frame")
        pos = tail % cap
        skipped = 0
        tail_room = cap - pos
        if tail_room < 4:
            skipped = tail_room
            pos = 0
        else:
            (length,) = struct.unpack_from("<I", self._data, pos)
            if length == self._WRAP:
                skipped = tail_room
                pos = 0
        if skipped:
            (length,) = struct.unpack_from("<I", self._data, pos)
        if length == self._INLINE:
            return None, skipped + 4
        if length > cap - 4 or skipped + 4 + length > head - tail:
            raise wire.WireError(
                f"shm ring: bad frame length {length} at {pos}"
            )
        view = self._data[pos + 4 : pos + 4 + length].toreadonly()
        return view, skipped + 4 + length

    def release(self, advance: int) -> None:
        self._u64[self._TAIL] = self._u64[self._TAIL] + advance

    # -- teardown ---------------------------------------------------------
    def close(self):
        """Unmap (and unlink, if this end created the segment). Decoded
        views must be dropped first; a racing lingering view only skips
        the unmap, never crashes teardown."""
        for mv in (self._u64, self._data):
            try:
                mv.release()
            except (BufferError, ValueError):  # caller kept a frame view
                pass
        if not self._close_shm:
            return
        try:
            self._shm.close()
        except BufferError:  # pragma: no cover
            pass
        if self._owner:
            # Balance the tracker set before unlink's unregister: if an
            # in-process client attach()ed this segment, its unregister
            # already removed the creation entry (registration is a set,
            # so this is a no-op otherwise).
            _tracker("register", self._shm)
            try:
                self._shm.unlink()
            except FileNotFoundError:
                # A crash sweep (unlink_segment / ring.unlink) got here
                # first: rebalance so the tracker doesn't warn about a
                # "leaked" segment at process exit.
                _tracker("unregister", self._shm)


class ShmTransport:
    """Framed messages over a pair of shm rings with a socket doorbell.

    The socket is the blocking primitive, the crash detector (peer death
    closes it), and the carrier for oversized messages; the rings are
    the data plane AND the ordering authority. Doorbell wakeups are
    coalesced futex-style: the sender rings only when the ring header's
    waiting flag says the reader is blocked, so a busy reader (frames
    already queued) moves messages with zero syscalls on both sides,
    while a sleeping reader costs one 1-byte send. Payload bytes never
    cross the socket in the common case — `send` encodes scatter-gather
    straight into the ring; `recv_sized` decodes zero-copy views out of
    it.

    Lifetime: the previous frame's ring space is released at the next
    recv_sized call — consume (copy out of) a decoded nest before
    receiving the next message, same rule as wire.RecvBuffer.
    """

    def __init__(self, sock: socket.socket, send_ring: ShmRing,
                 recv_ring: ShmRing,
                 max_frame_bytes: Optional[int] = None,
                 recv_timeout_s: Optional[float] = None):
        self._sock = sock
        self._send_ring = send_ring
        self._recv_ring = recv_ring
        self._max_frame_bytes = max_frame_bytes
        self._recv_timeout_s = recv_timeout_s
        self._send_buf = wire.SendBuffer()
        self._recv_buf = wire.RecvBuffer()  # inline-fallback receives
        self._pending_release = 0
        self._inline_consumed = False
        self._doorbell = bytearray(1)
        self._doorbell_mv = memoryview(self._doorbell)
        self._recheck = AdaptiveRecheck()

    def send(self, value: Any) -> int:
        views, total = wire._timed_encode_into(value, self._send_buf)
        ring = self._send_ring
        if total <= ring.max_frame_bytes():
            ring.write_frame(views, total, peer_check=self._peer_check)
            if ring.reader_waiting():
                self._sock.sendall(_DOORBELL_WAKE)
        else:
            ring.write_inline_marker(peer_check=self._peer_check)
            if ring.reader_waiting():
                self._sock.sendall(_DOORBELL_WAKE)
            self._sock.sendall(_DOORBELL_INLINE)
            wire._sendmsg_all(self._sock, views, total)
        return total

    def _peer_check(self):
        """Probe the doorbell socket while a send is blocked on ring
        space: a peer that DIED (vs merely stalled) must fail the send
        promptly, like a socket send would, instead of burning the full
        ring-wait timeout. Queued stale WAKE bytes are consumed so they
        can't mask the EOF behind them — safe because wakeups are only
        *needed* while this end is blocked inside _wait_for_frame (the
        transport is single-threaded per connection, so any 0x01 queued
        during a send is stale by definition); an inline 0x02 is never
        consumed (it belongs to recv_sized)."""
        # A consumed 0x02 whose frame bytes are still queued proves the
        # peer alive AND makes the socket head payload, not doorbell —
        # probing now could eat a payload byte that happens to be 0x01.
        if self._inline_consumed:
            return
        while True:
            try:
                data = self._sock.recv(
                    1, socket.MSG_PEEK | socket.MSG_DONTWAIT
                )
            except (BlockingIOError, InterruptedError):
                return  # alive; nothing queued
            except OSError as e:
                raise ConnectionError(
                    f"shm peer connection lost during ring wait: {e}"
                ) from e
            if data == b"":
                raise ConnectionError("shm peer closed during ring wait")
            if data == _DOORBELL_WAKE:
                try:
                    self._sock.recv(1, socket.MSG_DONTWAIT)
                except OSError:
                    pass
                continue  # re-probe: EOF may hide behind stale wakeups
            return  # inline traffic queued: peer alive, leave it alone

    def _wait_for_frame(self) -> bool:
        """Block until the recv ring has a frame; False on clean EOF.
        The waiting-flag dance makes the sender ring the doorbell only
        when we are actually asleep; the periodic re-check bounds the
        (fence-less) lost-wakeup race."""
        ring = self._recv_ring
        sock = self._sock
        mv = self._doorbell_mv
        waits, rechecks = _ring_instruments()
        deadline = (
            None if self._recv_timeout_s is None
            else time.monotonic() + self._recv_timeout_s
        )
        while True:
            if ring.has_frame():
                return True
            spin_until = time.perf_counter() + _EMPTY_SPIN_S
            while time.perf_counter() < spin_until:
                if ring.has_frame():
                    return True
            if deadline is not None and time.monotonic() > deadline:
                raise socket.timeout(
                    f"shm recv timed out after {self._recv_timeout_s}s"
                )
            ring.set_waiting(True)
            try:
                if ring.has_frame():
                    continue
                waits.inc()
                # Adaptive bound (ISSUE 12): recheck-heavy windows
                # tighten it, quiescent ones relax it (AdaptiveRecheck).
                sock.settimeout(self._recheck.timeout_s())
                try:
                    n = sock.recv_into(mv, 1)
                except socket.timeout:
                    rechecks.inc()
                    self._recheck.record(True)
                    continue  # re-check the ring (lost-wakeup guard)
                finally:
                    sock.settimeout(None)
                if n == 0:
                    # Peer closed. Frames already in the ring are still
                    # deliverable; EOF surfaces once it drains.
                    return ring.has_frame()
                self._recheck.record(False)  # a byte ended this wait
                kind = bytes(mv)
                if kind == _DOORBELL_INLINE:
                    # Normally the inline marker is consumed from the
                    # ring before this byte is read — but the fence-less
                    # waiting-flag race can skip the WAKE byte (sender
                    # saw waiting=0) and land the inline byte on a
                    # blocked reader. The sendmsg syscall fences the
                    # sender's marker publish, so the marker must be
                    # visible by now; remember the byte is consumed and
                    # deliver through the marker path.
                    if not ring.has_frame():
                        raise wire.WireError(
                            "shm: inline byte with an empty ring"
                        )
                    self._inline_consumed = True
                    return True
                if kind != _DOORBELL_WAKE:
                    raise wire.WireError(f"Bad doorbell byte {kind!r}")
                # Stale wakeup: loop and re-check the ring.
            finally:
                ring.set_waiting(False)

    def _recv_inline_frame(self):
        """The ring said the next message rides the socket: skip stale
        wakeup bytes up to the 0x02 byte (unless _wait_for_frame already
        consumed it), then read one framed message. recv_timeout_s
        bounds these socket reads too (a peer that stalls mid-inline
        must surface as socket.timeout, keeping connect_transport's
        'bounds every receive' contract)."""
        mv = self._doorbell_mv
        if self._recv_timeout_s is not None:
            self._sock.settimeout(self._recv_timeout_s)
        try:
            while not self._inline_consumed:
                if not wire._recv_into_exact(
                    self._sock, mv, 1, eof_ok=True
                ):
                    raise wire.WireError(
                        "Connection closed before inline frame"
                    )
                kind = bytes(mv)
                if kind == _DOORBELL_INLINE:
                    break
                if kind != _DOORBELL_WAKE:
                    raise wire.WireError(f"Bad doorbell byte {kind!r}")
            self._inline_consumed = False
            value, nbytes = wire.recv_message_sized(
                self._sock, buf=self._recv_buf,
                max_frame_bytes=self._max_frame_bytes,
            )
        finally:
            if self._recv_timeout_s is not None:
                self._sock.settimeout(None)
        if value is None:
            raise wire.WireError("Connection closed mid-frame")
        return value, nbytes

    def recv_sized(self) -> Tuple[Any, int]:
        ring = self._recv_ring
        if self._pending_release:
            ring.release(self._pending_release)
            self._pending_release = 0
        if not self._wait_for_frame():
            return None, 0  # clean EOF at a frame boundary
        view, advance = ring.read_frame()
        self._pending_release = advance
        if view is None:  # inline marker: the bytes ride the socket
            return self._recv_inline_frame()
        if len(view) < 4:
            raise wire.WireError("shm ring: truncated frame header")
        (payload_len,) = struct.unpack_from("<I", view, 0)
        if payload_len != len(view) - 4:
            raise wire.WireError(
                f"shm ring: header says {payload_len}, "
                f"frame has {len(view) - 4}"
            )
        limit = wire._frame_limit(self._max_frame_bytes)
        if payload_len > limit:
            raise wire.WireError(
                f"Frame length {payload_len} exceeds max_frame_bytes "
                f"{limit}"
            )
        return wire._timed_decode(view[4:]), len(view)

    def recv(self) -> Any:
        return self.recv_sized()[0]

    @property
    def segment_names(self) -> Tuple[str, str]:
        """(send ring, recv ring) SharedMemory names — what a teardown
        sweep needs to unlink if this connection's owner is gone."""
        return self._send_ring.name, self._recv_ring.name

    def unlink_segments(self) -> None:
        """Crash sweep: unlink both ring segments regardless of which
        end owns them. The actor pool calls this on every shm
        connection teardown — a SIGKILL'd env server can't clean up its
        own segments, and for a live server the sweep only pre-empts
        the unlink its stream teardown would do anyway."""
        self._send_ring.unlink()
        self._recv_ring.unlink()

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
        self._send_ring.close()
        self._recv_ring.close()


def server_transport(conn: socket.socket, shm: bool = False,
                     obs_ring_bytes: int = DEFAULT_OBS_RING_BYTES,
                     act_ring_bytes: int = DEFAULT_ACT_RING_BYTES,
                     max_frame_bytes: Optional[int] = None,
                     handshake_timeout_s: float = 30.0):
    """Wrap a server-accepted connection. For shm, creates the per-
    connection rings (server->client sized obs_ring_bytes, client->server
    act_ring_bytes), sends the handshake, and waits for the client's ack
    so segment ownership is never ambiguous."""
    if not shm:
        return SocketTransport(conn, max_frame_bytes=max_frame_bytes)
    s2c = ShmRing.create(obs_ring_bytes)
    try:
        c2s = ShmRing.create(act_ring_bytes)
    except BaseException:
        s2c.close()
        raise
    try:
        prev_timeout = conn.gettimeout()
        conn.settimeout(handshake_timeout_s)
        wire.send_message(conn, {
            "type": "shm_handshake", "version": 1,
            "s2c": s2c.name, "c2s": c2s.name,
        })
        reply = wire.recv_message(conn)
        if not isinstance(reply, dict) or reply.get("type") != "shm_ok":
            raise wire.WireError(f"Bad shm handshake ack: {reply!r}")
        conn.settimeout(prev_timeout)
    except BaseException:
        s2c.close()
        c2s.close()
        raise
    return ShmTransport(conn, send_ring=s2c, recv_ring=c2s,
                        max_frame_bytes=max_frame_bytes)


def _client_handshake(sock: socket.socket, address: str,
                      max_frame_bytes: Optional[int],
                      recv_timeout_s: Optional[float] = None):
    hs = wire.recv_message(sock)
    if not isinstance(hs, dict) or hs.get("type") != "shm_handshake":
        raise wire.WireError(
            f"Expected shm handshake from {address}, got {hs!r}"
        )
    s2c = ShmRing.attach(hs["s2c"])
    try:
        c2s = ShmRing.attach(hs["c2s"])
    except BaseException:
        s2c.close()
        raise
    try:
        wire.send_message(sock, {"type": "shm_ok"})
    except BaseException:
        s2c.close()
        c2s.close()
        raise
    return ShmTransport(sock, send_ring=c2s, recv_ring=s2c,
                        max_frame_bytes=max_frame_bytes,
                        recv_timeout_s=recv_timeout_s)


def connect_transport(address: str, timeout_s: float = 600,
                      max_frame_bytes: Optional[int] = None,
                      recv_timeout_s: Optional[float] = None):
    """Connect with retries until the deadline (the reference's 10-minute
    WaitForConnected semantics, actorpool.cc:354-372): env servers may
    still be starting up — a refused/missing socket is a reason to retry,
    not to die. Returns a SocketTransport or, for shm:// addresses, a
    fully handshaken ShmTransport. recv_timeout_s bounds every receive
    on the returned transport (spec probes: a server that accepts but
    never sends must raise socket.timeout, not hang)."""
    family, target = parse_address(address)
    deadline = time.monotonic() + timeout_s
    last_error = None
    while time.monotonic() < deadline:
        sock = socket.socket(family, socket.SOCK_STREAM)
        sock.settimeout(max(0.1, deadline - time.monotonic()))
        try:
            sock.connect(target)
        except OSError as e:
            sock.close()
            last_error = e
            time.sleep(0.1)
            continue
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # unix sockets
        if is_shm_address(address):
            try:
                transport = _client_handshake(
                    sock, address, max_frame_bytes,
                    recv_timeout_s=recv_timeout_s,
                )
            except BaseException:
                sock.close()
                raise
            sock.settimeout(None)
            return transport
        sock.settimeout(None)
        return SocketTransport(sock, max_frame_bytes=max_frame_bytes,
                               recv_timeout_s=recv_timeout_s)
    raise TimeoutError(
        f"WaitForConnected() timed out for {address}: {last_error}"
    )


def dial_transport(address: str, deadline_s: float,
                   attempt_timeout_s: float = 2.0,
                   base_s: float = 0.2, cap_s: float = 2.0,
                   rng=None, **transport_kwargs):
    """Bounded-retry dial under jittered exponential backoff (the fleet
    control plane's rendezvous discipline, fleet/coordinator.py).

    `connect_transport` already retries on a FIXED 0.1s cadence — right
    for an env server known to be coming up on the same box, wrong for
    a peer HOST that may be seconds behind in its own startup: a fleet
    of remotes hammering the lead's listen queue in lockstep is exactly
    the thundering herd `Backoff`'s jitter exists to break up. Each
    attempt gets `attempt_timeout_s`; attempts repeat under backoff
    until `deadline_s` total, then the last error surfaces as
    TimeoutError. `transport_kwargs` pass through to the per-attempt
    `connect_transport` (max_frame_bytes, recv_timeout_s).
    """
    from torchbeast_tpu.resilience.backoff import Backoff, BackoffDeadline

    backoff = Backoff(
        base_s=base_s, cap_s=cap_s, deadline_s=deadline_s, rng=rng
    )
    while True:
        try:
            return connect_transport(
                address, timeout_s=attempt_timeout_s, **transport_kwargs
            )
        except (OSError, TimeoutError) as e:
            try:
                backoff.sleep()
            except BackoffDeadline:
                raise TimeoutError(
                    f"dial_transport: could not reach {address} within "
                    f"{deadline_s}s ({backoff.attempts} attempts): {e}"
                ) from e


def shm_pipe(obs_ring_bytes: int = DEFAULT_OBS_RING_BYTES,
             act_ring_bytes: int = DEFAULT_ACT_RING_BYTES,
             max_frame_bytes: Optional[int] = None):
    """In-process ShmTransport pair over a socketpair — the test/bench
    harness for the ring data plane without a listening server.
    Returns (server_end, client_end)."""
    a, b = socket.socketpair()
    try:
        s2c = ShmRing.create(obs_ring_bytes)
    except BaseException:
        a.close()
        b.close()
        raise
    try:
        c2s = ShmRing.create(act_ring_bytes)
    except BaseException:  # don't leak the first segment (/dev/shm full)
        s2c.close()
        a.close()
        b.close()
        raise
    server = ShmTransport(a, send_ring=s2c, recv_ring=c2s,
                          max_frame_bytes=max_frame_bytes)
    # The client end shares the in-process mapping (attaching by name
    # would double-book this process's resource_tracker registration);
    # only the server end unmaps/unlinks.
    client = ShmTransport(
        b,
        send_ring=ShmRing(c2s._shm, c2s.capacity, owner=False,
                          close_shm=False),
        recv_ring=ShmRing(s2c._shm, s2c.capacity, owner=False,
                          close_shm=False),
        max_frame_bytes=max_frame_bytes,
    )
    return server, client
