"""Runtime: queues, dynamic batching, wire protocol, env servers, actor
pool — the reference's `libtorchbeast` layer (SURVEY.md §2.1 N3-N9),
re-designed for the framed-socket transport and XLA-static inference.

Python implementations carry the semantics and the test surface; the C++
hot-path equivalents live under csrc/ and are used when built.
"""

from torchbeast_tpu.runtime.queues import (  # noqa: F401
    AsyncError,
    Batch,
    BatchArena,
    BatchingQueue,
    ClosedBatchingQueue,
    DevicePrefetcher,
    DynamicBatcher,
)
