"""Learner-side batching queue and dynamic inference batcher.

Python re-designs of the reference's C++ runtime pieces (the C++ versions
land under csrc/ for the hot path; these carry the exact semantics and the
test surface):

- BatchingQueue: the reference's `BatchingQueue<T>`
  (/root/reference/src/cc/actorpool.cc:57-222). Bounded producer/consumer
  queue of (nest-of-arrays, payload); `enqueue` blocks when full — the
  backpressure that keeps rollouts on-policy; `dequeue_many` waits for
  min_batch_size items (or timeout) and concatenates up to max_batch_size
  along batch_dim; `close()` drains and wakes waiters; iterating a closed,
  empty queue raises StopIteration.

- DynamicBatcher: the reference's `DynamicBatcher`
  (actorpool.cc:224-340). Producers call `compute(inputs)` and block until
  a consumer picks up the batch via iteration, runs the model, and calls
  `batch.set_outputs(outputs)`; each producer gets its slice back. Dropping
  a batch without outputs breaks the promise -> AsyncError at producers.
  Batch sizes are dynamic in [minimum_batch_size, maximum_batch_size] with
  a timeout — the TPU-side consumer pads to a bucket size before running
  XLA (see runtime/inference.py) because variable shapes would recompile.
"""

import collections
import queue as stdlib_queue
import threading
import time
from typing import Any, Callable, Iterable, List, Optional, Tuple

import numpy as np

from torchbeast_tpu import nest
from torchbeast_tpu import telemetry


class _QueueTelemetry:
    """Instrument bundle for a named queue/batcher (telemetry_name=None
    keeps the queue un-instrumented — a single None check per op).
    request_wait_s is NOT here: only the DynamicBatcher's compute()
    side can observe it, and a plain BatchingQueue registering it would
    export a permanently-zero histogram that reads as "requests never
    wait" instead of "not measured"."""

    __slots__ = ("depth", "items_in", "dequeue_wait_s", "batch_size")

    def __init__(self, name: str):
        reg = telemetry.get_registry()
        self.depth = reg.gauge(f"{name}.depth")
        self.items_in = reg.counter(f"{name}.items_in")
        self.dequeue_wait_s = reg.histogram(f"{name}.dequeue_wait_s")
        self.batch_size = reg.histogram(f"{name}.batch_size")


class ClosedBatchingQueue(RuntimeError):
    pass


class AsyncError(RuntimeError):
    pass


def _concat_nests(items: List[Any], batch_dim: int):
    """Concatenate structurally-equal nests of numpy arrays along
    batch_dim (the reference's batch() helper, actorpool.cc:49-55)."""
    flats = [nest.flatten(item) for item in items]
    out = [
        np.concatenate([f[i] for f in flats], axis=batch_dim)
        for i in range(len(flats[0]))
    ]
    return nest.pack_as(items[0], out)


class BatchingQueue:
    def __init__(
        self,
        batch_dim: int = 0,
        minimum_batch_size: int = 1,
        maximum_batch_size: Optional[int] = None,
        timeout_ms: Optional[float] = None,
        maximum_queue_size: Optional[int] = None,
        check_inputs: bool = True,
        telemetry_name: Optional[str] = None,
    ):
        if minimum_batch_size < 1:
            raise ValueError("Min batch size must be >= 1")
        if maximum_batch_size is not None:
            if maximum_batch_size < minimum_batch_size:
                raise ValueError(
                    "Max batch size must be >= min batch size"
                )
        if maximum_queue_size is not None and maximum_queue_size < 1:
            raise ValueError("Max queue size must be >= 1")
        self._batch_dim = batch_dim
        self._min = minimum_batch_size
        self._max = (
            maximum_batch_size if maximum_batch_size is not None else float("inf")
        )
        # `is not None`, not truthiness: timeout_ms=0 means "time out
        # immediately", never "block forever".
        self._timeout_s = timeout_ms / 1000 if timeout_ms is not None else None
        self._max_queue = (
            maximum_queue_size if maximum_queue_size is not None else float("inf")
        )
        self._check_inputs = check_inputs
        # Queue depth/occupancy + batch-size/wait-time series under
        # `{telemetry_name}.*` (ISSUE 2: attribute stalls to queue wait
        # vs. batch wait). None = no instruments, no overhead.
        self._tm = (
            _QueueTelemetry(telemetry_name) if telemetry_name else None
        )

        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        # (inputs, payload, rows) items  # guarded-by: self._lock
        self._deque = collections.deque()
        self._closed = False  # guarded-by: self._lock
        self._num_enqueued = 0  # guarded-by: self._lock

    def name(self):
        return type(self).__name__

    def size(self) -> int:
        with self._lock:
            return len(self._deque)

    def num_enqueued(self) -> int:
        with self._lock:
            return self._num_enqueued

    # beastlint: hot
    def enqueue(self, inputs: Any, payload: Any = None):
        leaves = nest.flatten(inputs)
        if self._check_inputs:
            if not leaves:
                raise ValueError("Cannot enqueue empty vector of arrays")
            for leaf in leaves:
                arr = np.asarray(leaf)
                if arr.ndim <= self._batch_dim:
                    raise ValueError(
                        f"Enqueued array with {arr.ndim} dims but "
                        f"batch_dim is {self._batch_dim}"
                    )
        # Batch sizes are counted in ROWS along batch_dim (an item may carry
        # several), so dequeue_many's max matches the consumer's bucket
        # contract even for multi-row compute() calls.
        rows = int(np.asarray(leaves[0]).shape[self._batch_dim]) if leaves else 1
        with self._not_full:
            if self._closed:
                raise ClosedBatchingQueue(
                    "Enqueue to closed batching queue"
                )
            while len(self._deque) >= self._max_queue:
                self._not_full.wait()
                if self._closed:
                    raise ClosedBatchingQueue(
                        "Enqueue to closed batching queue"
                    )
            self._deque.append((inputs, payload, rows))
            self._num_enqueued += 1
            if self._tm is not None:
                self._tm.items_in.inc()
                self._tm.depth.set(len(self._deque))
            self._not_empty.notify()

    def close(self):
        with self._lock:
            if self._closed:
                raise RuntimeError("Queue was closed already")
            self._closed = True
            leftover = len(self._deque)
            self._deque.clear()
            self._not_empty.notify_all()
            self._not_full.notify_all()
            return leftover

    def is_closed(self) -> bool:
        with self._lock:
            return self._closed

    # beastlint: hot
    def dequeue_many(self) -> Tuple[Any, List[Any]]:
        """Block for >= minimum_batch_size rows (or any rows after
        timeout); return (batched nest, payloads). Up to
        maximum_batch_size rows are concatenated; the first item is always
        taken so an oversized single item can't deadlock the queue."""
        t_wait = time.perf_counter() if self._tm is not None else 0.0
        with self._not_empty:
            # The timeout bounds how long we hold out for a FULL minimum
            # batch; an empty queue always blocks (there is nothing to
            # return), so an expired deadline must not busy-spin — we fall
            # back to an untimed wait for the first item.
            deadline = (
                None
                if self._timeout_s is None
                else time.monotonic() + self._timeout_s
            )
            while True:
                if sum(r for _, _, r in self._deque) >= self._min:
                    break
                if self._closed:
                    raise StopIteration
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        if self._deque:
                            break
                        remaining = None
                self._not_empty.wait(timeout=remaining)
            items = [self._deque.popleft()]
            rows = items[0][2]
            while self._deque and rows + self._deque[0][2] <= self._max:
                item = self._deque.popleft()
                rows += item[2]
                items.append(item)
            if self._tm is not None:
                self._tm.depth.set(len(self._deque))
                self._tm.dequeue_wait_s.observe(
                    time.perf_counter() - t_wait
                )
                self._tm.batch_size.observe(rows)
            self._not_full.notify_all()
        inputs = [it[0] for it in items]
        payloads = [it[1] for it in items]
        return _concat_nests(inputs, self._batch_dim), payloads

    # beastlint: hot
    def dequeue_item(self) -> Tuple[Any, int]:
        """One raw (inputs, rows) item in FIFO order, blocking until an
        item arrives; StopIteration once the queue is closed. The
        BatchArena's intake: assembly happens by write-through column
        copy straight into the arena, so this path skips dequeue_many's
        min-batch wait and its list-of-nests + np.concatenate."""
        t_wait = time.perf_counter() if self._tm is not None else 0.0
        with self._not_empty:
            while not self._deque:
                if self._closed:
                    raise StopIteration
                self._not_empty.wait()
            inputs, _payload, rows = self._deque.popleft()
            if self._tm is not None:
                self._tm.depth.set(len(self._deque))
                self._tm.dequeue_wait_s.observe(
                    time.perf_counter() - t_wait
                )
            self._not_full.notify_all()
        return inputs, rows

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch, _ = self.dequeue_many()
        except StopIteration:
            raise StopIteration from None
        return batch


class _ArenaSlot:
    """One preallocated arena: per-leaf [K, ...] numpy arrays + a
    free/busy latch. Released (reusable) only via its release().

    Replay bookkeeping (--replay_reuse): `uses_left` counts the replay
    handouts this filled slot still owes, `outstanding` the handouts
    not yet released. The slot is free for rewrite only when BOTH hit
    zero — the reuse-counter fence that replaces the single
    release-flips-free latch."""

    __slots__ = ("arrays", "free", "uses_left", "outstanding")

    def __init__(self):
        self.arrays = None  # lazily allocated from the first item
        self.free = True
        self.uses_left = 0  # guarded-by: arena._free
        self.outstanding = 0  # guarded-by: arena._free


class BatchArena:
    """Host staging for K-batch supersteps: rollout items drain from a
    BatchingQueue straight into preallocated contiguous per-leaf
    [K, T+1, B, ...] numpy arenas (write-through column copy — no
    per-batch list-of-nests + np.stack/np.concatenate), yielding one
    stacked nest per K assembled batches. Values are bit-identical to
    the concat+stack path they replace (pure copies; pinned by test).

    Slot-reuse fence: device placement may ALIAS host memory (the CPU
    backend's zero-copy device_put) or read it asynchronously (TPU H2D
    rides behind compute), so a filled arena is handed out with a
    `release` callable and is NOT rewritten until release() is called.
    Callers release once the consuming update's completion is PROVEN —
    the drivers do it when that superstep's stats arrive on host (the
    stats are outputs of the same XLA execution that read the arena).
    `pool` slots cycle; if none frees within `grow_timeout_s` the arena
    allocates a fresh slot (logged) so a consumer that forgets to
    release degrades to allocation, never to deadlock or corruption.

    Item contract: each dequeued item is a nest whose leaves have
    `rows` columns along `batch_dim`; items must tile the B-column
    batches exactly (an item straddling a batch boundary raises —
    ActorPool rollouts are one column each, so the learner queue always
    tiles). All items must share one nest structure/dtype set.

    Precision staging (`float_dtype`, torchbeast_tpu/precision.py):
    when set (e.g. ml_dtypes.bfloat16 under --precision bf16_train),
    float32 leaves allocate their arena columns in that dtype and the
    write-through copy IS the cast — the staged [K, T+1, B, ...] stack,
    and with it the host->device transfer, is half-width with zero
    extra passes. Non-f32 leaves (uint8 frames, ints, bools) are
    untouched. The learner upcasts at point of use (f32-accumulate).

    Circular replay (`replay_reuse` K' > 1, --loss impact): after a
    fresh fill, the SAME slot is handed out K'-1 more times WITHOUT
    draining the queue — sample reuse as slot re-release. Each handout
    carries its own release() (stamped `release.fresh`: True for the
    queue-draining fill, False for replays) and the slot's rewrite
    fence holds until every handout is released AND the replay quota is
    spent — a slot is never rewritten mid-reuse. At K'=1 the behavior
    (and the staged bytes) are bit-identical to the original
    single-release arena.
    """

    def __init__(
        self,
        k: int,
        rows: int,
        batch_dim: int = 1,
        pool: int = 5,
        grow_timeout_s: float = 5.0,
        telemetry_name: Optional[str] = None,
        float_dtype=None,
        replay_reuse: int = 1,
    ):
        if k < 1:
            raise ValueError(f"superstep k must be >= 1, got {k}")
        if rows < 1:
            raise ValueError(f"arena rows must be >= 1, got {rows}")
        if pool < 2:
            # One slot filling + at least one staged/consumed: fewer
            # would force a grow on every superstep.
            raise ValueError(f"arena pool must be >= 2, got {pool}")
        if replay_reuse < 1:
            raise ValueError(
                f"replay_reuse must be >= 1, got {replay_reuse}"
            )
        self._k = k
        self._rows = rows
        self._batch_dim = batch_dim
        self._float_dtype = (
            np.dtype(float_dtype) if float_dtype is not None else None
        )
        self._grow_timeout_s = grow_timeout_s
        self._replay_reuse = replay_reuse
        self._replay_slot = None  # guarded-by: self._free
        self._slots = [_ArenaSlot() for _ in range(pool)]
        self._free = threading.Condition(threading.Lock())
        self._template = None  # nest structure of the first item
        self._tm_assemble = self._tm_batch_size = None
        self._tm_occupancy = None
        if telemetry_name:
            reg = telemetry.get_registry()
            self._tm_assemble = reg.histogram(
                f"{telemetry_name}.assemble_s"
            )
            self._tm_batch_size = reg.histogram(
                f"{telemetry_name}.batch_size"
            )
            self._tm_occupancy = reg.gauge(
                f"{telemetry_name}.occupancy"
            )

    def _set_occupancy(self):
        # Caller holds self._free.
        if self._tm_occupancy is not None:
            self._tm_occupancy.set(
                sum(1 for slot in self._slots if not slot.free)
            )

    def _acquire_slot(self) -> _ArenaSlot:
        deadline = time.monotonic() + self._grow_timeout_s
        with self._free:
            while True:
                for slot in self._slots:
                    if slot.free:
                        slot.free = False
                        self._set_occupancy()
                        return slot
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._free.wait(timeout=remaining)
        # Consumer is holding every slot (or never releasing): growing
        # is always safe — the held slots stay untouched.
        import logging

        logging.getLogger(__name__).warning(
            "BatchArena: no slot released within %.1fs; growing the "
            "pool to %d (a consumer is not calling release())",
            self._grow_timeout_s, len(self._slots) + 1,
        )
        slot = _ArenaSlot()
        slot.free = False
        with self._free:
            self._slots.append(slot)
            self._set_occupancy()
        return slot

    def _release_fn(self, slot: _ArenaSlot, fresh: bool = True):
        def release():
            with self._free:
                slot.outstanding = max(0, slot.outstanding - 1)
                if slot.outstanding == 0 and slot.uses_left == 0:
                    slot.free = True
                    self._set_occupancy()
                    self._free.notify()

        release.fresh = fresh
        return release

    def _abort_slot(self, slot: _ArenaSlot):
        """Drop a slot whose fill raised: a partial fill must never be
        replayed, so the replay quota and handout count reset before
        the slot frees."""
        with self._free:
            if self._replay_slot is slot:
                self._replay_slot = None
            slot.uses_left = 0
            slot.outstanding = 0
            slot.free = True
            self._set_occupancy()
            self._free.notify()

    def _allocate(self, slot: _ArenaSlot, item_leaves: List[np.ndarray]):
        bd = self._batch_dim
        arrays = []
        for leaf in item_leaves:
            shape = list(leaf.shape)
            shape[bd] = self._rows
            dtype = leaf.dtype
            if (
                self._float_dtype is not None
                and dtype == np.float32
            ):
                dtype = self._float_dtype
            arrays.append(np.empty([self._k] + shape, dtype))
        slot.arrays = arrays

    # beastlint: hot
    def assemble_from(self, queue: "BatchingQueue"):
        """Fill the next free arena with K batches of `rows` columns
        drained from `queue`; returns (stacked_nest, release). Raises
        StopIteration when the queue closes — a partially filled arena
        is dropped (a fixed-K scan cannot consume it) and its slot
        released.

        With replay_reuse K' > 1 the last fresh fill is handed out
        again (no queue drain) until its K'-fold quota is spent;
        `release.fresh` says which kind this handout was."""
        t0 = time.perf_counter() if self._tm_assemble is not None else 0.0
        with self._free:
            replay = self._replay_slot
            if replay is not None:
                replay.uses_left -= 1
                replay.outstanding += 1
                if replay.uses_left == 0:
                    self._replay_slot = None
        if replay is not None:
            return (
                nest.pack_as(self._template, replay.arrays),
                self._release_fn(replay, fresh=False),
            )
        slot = self._acquire_slot()
        bd = self._batch_dim
        batch_idx, col = 0, 0
        try:
            while batch_idx < self._k:
                inputs, rows = queue.dequeue_item()
                leaves = [np.asarray(a) for a in nest.flatten(inputs)]
                if self._template is None:
                    self._template = inputs
                if slot.arrays is None:
                    self._allocate(slot, leaves)
                if col + rows > self._rows:
                    raise ValueError(
                        f"arena item with {rows} rows straddles the "
                        f"{self._rows}-column batch boundary at column "
                        f"{col} (items must tile batches exactly)"
                    )
                idx = (batch_idx,) + (slice(None),) * bd
                for arena, leaf in zip(slot.arrays, leaves):
                    arena[idx + (slice(col, col + rows),)] = leaf
                col += rows
                if col == self._rows:
                    if self._tm_batch_size is not None:
                        self._tm_batch_size.observe(col)
                    batch_idx, col = batch_idx + 1, 0
        except BaseException:
            dropped = batch_idx * self._rows + col
            if dropped:
                import logging

                logging.getLogger(__name__).info(
                    "BatchArena: dropping %d assembled rows (source "
                    "closed mid-superstep)", dropped,
                )
            self._abort_slot(slot)
            raise
        with self._free:
            slot.uses_left = self._replay_reuse - 1
            slot.outstanding = 1
            if slot.uses_left > 0:
                self._replay_slot = slot
        if self._tm_assemble is not None:
            self._tm_assemble.observe(time.perf_counter() - t0)
        return nest.pack_as(self._template, slot.arrays), self._release_fn(
            slot
        )


class _Promise:
    __slots__ = ("event", "value", "error")

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.error = None


class Batch:
    """One pending inference batch: inputs + the promises awaiting rows."""

    def __init__(self, batch_dim: int, inputs: Any, promises: List[_Promise],
                 sizes: List[int], traces: Optional[List] = None):
        self._batch_dim = batch_dim
        self._inputs = inputs
        self._promises = promises
        self._sizes = sizes
        self._traces = traces or []
        self._outputs_set = False

    def _finish_traces(self, stage: str):
        for trace in self._traces:
            trace.stamp(stage)
            trace.finish()

    def __len__(self):
        return sum(self._sizes)

    def get_inputs(self) -> Any:
        return self._inputs

    # beastlint: hot
    def set_outputs(self, outputs: Any):
        if self._outputs_set:
            raise RuntimeError("set_outputs called twice")
        leaves = nest.flatten(outputs)
        if not leaves:
            raise ValueError("Empty output")
        expected = len(self)
        for leaf in leaves:
            arr = np.asarray(leaf)
            if arr.ndim <= self._batch_dim:
                raise ValueError(
                    f"With batch_dim {self._batch_dim}, output shape "
                    f"{arr.shape} has too few dims"
                )
            if arr.shape[self._batch_dim] != expected:
                raise ValueError(
                    f"Output shape {arr.shape} must have size {expected} "
                    f"in batch_dim {self._batch_dim}"
                )
        self._outputs_set = True
        offset = 0
        for promise, size in zip(self._promises, self._sizes):
            sl = [slice(None)] * (self._batch_dim + 1)
            sl[self._batch_dim] = slice(offset, offset + size)
            promise.value = nest.map(
                lambda a: np.asarray(a)[tuple(sl)], outputs
            )
            promise.event.set()
            offset += size
        self._finish_traces("reply")

    def fail(self, error: BaseException):
        """Break every waiting promise with `error` (used by consumers
        whose model call failed, so producers fail fast instead of
        timing out)."""
        if self._outputs_set:
            return
        self._outputs_set = True
        for promise in self._promises:
            promise.error = AsyncError(
                f"Inference failed: {type(error).__name__}: {error}"
            )
            promise.event.set()
        self._finish_traces("failed")

    def __del__(self):
        if not self._outputs_set:
            for promise in self._promises:
                promise.error = AsyncError(
                    "Batch died before outputs were set"
                )
                promise.event.set()
            self._finish_traces("dropped")


class DevicePrefetcher:
    """Double-buffered host→device staging between a batch source and
    the learner thread.

    A background thread drains `source` (any iterable — typically the
    learner BatchingQueue) and applies `place_fn` (jax.device_put / the
    DP shard placement — injected so this module stays numpy-only) to
    each item. Because device placement is asynchronous, by the time the
    learner pulls an item its H2D transfer is already riding behind the
    previous update's compute instead of stalling the next dispatch;
    `depth=2` is the classic double buffer (one staging while one is
    consumed). Staging contract: each staged batch is handed to exactly
    one consumer and nothing re-reads it afterwards, so its device
    buffers free as soon as the consuming update drops the reference
    (and a derived update step with batch-shaped outputs may safely
    donate them — learner.donate_argnums_for(donate, donate_batch=True)).

    End-of-stream contract (mirrors the inline prefetch thread this
    replaces, polybeast r05): no end sentinel is enqueued — the internal
    queue may still hold live items when the source closes — consumers
    detect exhaustion by `get()` raising `queue.Empty` while
    `is_alive()` is False. A `place_fn`/source error is logged, recorded
    on `.error`, and ends the stream the same way.

    Superstep mode (`arena` set): `source` must be a BatchingQueue; the
    staging thread drains raw items through the BatchArena into
    [K, ...] stacked nests and stages ONE K-batch transfer per
    superstep — riding behind the previous superstep's compute exactly
    like the single-batch double buffer. `get()` then returns
    `(place_fn(stacked), release)` pairs; the consumer MUST call
    release() once the superstep's completion is proven (its stats
    arrived on host) so the arena slot can be rewritten (see
    BatchArena's fence contract).
    """

    def __init__(
        self,
        source: Iterable,
        place_fn: Callable[[Any], Any],
        depth: int = 2,
        telemetry_name: Optional[str] = None,
        arena: Optional[BatchArena] = None,
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._source = source
        self._place = place_fn
        self._arena = arena
        # Staging-time series: place_fn (device_put / shard placement)
        # dispatch latency + staged-buffer occupancy.
        self._tm_stage = self._tm_depth = None
        if telemetry_name:
            reg = telemetry.get_registry()
            self._tm_stage = reg.histogram(f"{telemetry_name}.stage_s")
            self._tm_depth = reg.gauge(f"{telemetry_name}.depth")
        self._q = stdlib_queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self.error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="device-prefetch"
        )

    def start(self):
        self._thread.start()
        return self

    def _items(self):
        """Source iteration: plain items, or (stacked, release) pairs
        assembled through the arena in superstep mode."""
        if self._arena is None:
            for item in self._source:
                yield item, None
            return
        while True:
            try:
                yield self._arena.assemble_from(self._source)
            except StopIteration:
                return

    # beastlint: hot
    def _run(self):
        import logging

        try:
            for item, release in self._items():
                if self._tm_stage is not None:
                    t0 = time.perf_counter()
                    staged = self._place(item)
                    self._tm_stage.observe(time.perf_counter() - t0)
                else:
                    staged = self._place(item)
                if release is not None:
                    staged = (staged, release)
                while not self._stop.is_set():
                    try:
                        self._q.put(staged, timeout=1.0)
                        break
                    except stdlib_queue.Full:
                        continue
                if self._tm_depth is not None:
                    self._tm_depth.set(self._q.qsize())
                if self._stop.is_set():
                    return
        except StopIteration:
            pass
        except Exception as e:  # noqa: BLE001
            self.error = e
            logging.getLogger(__name__).exception(
                "Device prefetch thread failed"
            )

    def get(self, timeout: Optional[float] = None):
        """One staged item; raises queue.Empty on timeout (the caller
        loops, checking is_alive() to detect exhaustion)."""
        item = self._q.get(timeout=timeout)
        if self._tm_depth is not None:
            self._tm_depth.set(self._q.qsize())
        return item

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    def close(self):
        """Stop staging (a blocked put exits within its poll interval).
        Already-staged items stay readable."""
        self._stop.set()

    def join(self, timeout: Optional[float] = None):
        self._thread.join(timeout)

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            try:
                return self.get(timeout=0.2)
            except stdlib_queue.Empty:
                if not self.is_alive():
                    raise StopIteration from None


class DynamicBatcher:
    def __init__(
        self,
        batch_dim: int = 1,
        minimum_batch_size: int = 1,
        maximum_batch_size: Optional[int] = None,
        timeout_ms: Optional[float] = None,
        check_outputs: bool = True,
        telemetry_name: Optional[str] = None,
        admission=None,
    ):
        self._batch_dim = batch_dim
        self._queue = BatchingQueue(
            batch_dim=batch_dim,
            minimum_batch_size=minimum_batch_size,
            maximum_batch_size=maximum_batch_size,
            timeout_ms=timeout_ms,
            telemetry_name=telemetry_name,
        )
        # The inner queue owns depth/batch-size; the batcher adds the
        # producer-side time-in-queue series ({name}.request_wait_s).
        self._tm = self._queue._tm
        self._tm_request_wait = (
            telemetry.get_registry().histogram(
                f"{telemetry_name}.request_wait_s"
            )
            if telemetry_name else None
        )
        self._check_outputs = check_outputs
        self._compute_timeout_s = 600  # reference: 10-min future timeout
        # Overload gate (ISSUE 14, serving/admission.py): when armed,
        # compute() may shed at enqueue (bounded queue depth — the
        # driver sizes it as --admission_depth_factor x the max batch)
        # and __next__ sheds requests whose deadline expired in the
        # queue — both as the typed ShedError the actor retry path
        # re-submits. One AdmissionController may gate SEVERAL
        # batchers (the Sebulba split shares one across its per-slice
        # batchers): the depth bound applies per queue, the counters
        # aggregate.
        self._admission = admission

    def size(self) -> int:
        return self._queue.size()

    def close(self):
        """Close the intake and break every pending promise so blocked
        compute() callers wake with AsyncError instead of hanging on the
        10-minute timeout. Closing and draining happen atomically under
        the queue lock — a concurrent compute() either enqueues before
        (its promise is broken here) or raises ClosedBatchingQueue."""
        q = self._queue
        with q._lock:
            if q._closed:
                raise RuntimeError("Queue was closed already")
            q._closed = True
            pending = [payload for _, payload, _ in q._deque]
            leftover = len(q._deque)
            q._deque.clear()
            q._not_empty.notify_all()
            q._not_full.notify_all()
        for payload in pending:
            promise = payload[0]
            promise.error = AsyncError("Batcher closed with pending requests")
            promise.event.set()
        return leftover

    def is_closed(self) -> bool:
        return self._queue.is_closed()

    # beastlint: hot
    def compute(self, inputs: Any, trace=None) -> Any:
        """Blocking request/response: returns this caller's output rows.

        `trace` (an optional telemetry StageTrace) rides the payload
        through the pipeline: stamped "enqueue" here, "batch" when the
        consumer picks the request up, "reply"/"failed" when its rows
        come back — per-request stage attribution for sampled traffic.

        With an armed admission controller this may raise ShedError
        BEFORE enqueueing (depth gate) — the caller re-submits after
        backoff (runtime/actor_pool.py owns that retry contract).
        """
        size = np.asarray(nest.front(inputs)).shape[self._batch_dim]
        if size > self._queue._max:
            raise ValueError(
                f"compute() input has {size} rows along batch_dim, more "
                f"than maximum_batch_size={self._queue._max}"
            )
        deadline = None
        if self._admission is not None:
            # May raise ShedError; checked before the trace stamps so a
            # shed-at-admission request never emits a half-open trace.
            deadline = self._admission.admit(self._queue.size())
        promise = _Promise()
        t_enq = (
            time.perf_counter()
            if (self._tm is not None or self._admission is not None)
            else 0.0
        )
        if trace is not None:
            trace.stamp("enqueue")
        self._queue.enqueue(inputs, (promise, size, t_enq, trace, deadline))
        if not promise.event.wait(timeout=self._compute_timeout_s):
            raise TimeoutError(
                "Compute response not ready after 10 minutes"
            )
        if promise.error is not None:
            raise promise.error
        return promise.value

    def __iter__(self):
        return self

    def _shed_expired(self, batch_inputs, payloads):
        """Deadline gate at dequeue (ISSUE 14): fail requests that sat
        in the queue past their deadline with the typed ShedError and
        cut their rows out of the batch. Returns (inputs, payloads)
        restricted to live requests — possibly ([], []) when the whole
        batch expired."""
        live_idx, expired_idx = self._admission.split_expired(
            [p[4] for p in payloads], [p[2] for p in payloads]
        )
        if not expired_idx:
            return batch_inputs, payloads
        for i in expired_idx:
            promise, _, _, trace, _ = payloads[i]
            if trace is not None:
                trace.stamp("shed")
                trace.finish()
            promise.error = self._admission.expired_error()
            promise.event.set()
        if not live_idx:
            return None, []
        offsets = np.cumsum([0] + [p[1] for p in payloads])
        rows = np.concatenate(
            [np.arange(offsets[i], offsets[i + 1]) for i in live_idx]
        )
        bd = self._batch_dim
        batch_inputs = nest.map(
            lambda a: np.take(np.asarray(a), rows, axis=bd), batch_inputs
        )
        return batch_inputs, [payloads[i] for i in live_idx]

    # beastlint: hot
    def __next__(self) -> Batch:
        while True:
            batch_inputs, payloads = self._queue.dequeue_many()
            if self._admission is not None:
                batch_inputs, payloads = self._shed_expired(
                    batch_inputs, payloads
                )
                if not payloads:
                    continue  # the whole batch expired in-queue
            promises = [p[0] for p in payloads]
            sizes = [p[1] for p in payloads]
            traces = [p[3] for p in payloads if p[3] is not None]
            if self._tm_request_wait is not None:
                now = time.perf_counter()
                for p in payloads:
                    self._tm_request_wait.observe(now - p[2])
            for trace in traces:
                trace.stamp("batch")
            return Batch(
                self._batch_dim, batch_inputs, promises, sizes, traces=traces
            )
