"""Device-resident agent-state table for the async acting path.

The legacy inference wiring ships recurrent state with every request:
actors enqueue `{"env", "agent_state"}`, the server pads BOTH, runs the
forward, and materializes the new state back to numpy so each actor can
send it up again next step (runtime/inference.py). For an LSTM that is
two `[L, 1, H]` float32 leaves crossing the host boundary twice per env
step per actor — pure overhead on a local device and a round-trip tax on
a remote-TPU tunnel (VERDICT.md localizes the end-to-end bottleneck
there; the Podracer architectures, arXiv:2104.06272, keep policy state
on the accelerator for exactly this reason).

Here the state lives in a `[.., num_slots+1, ..]`-per-leaf on-device
pytree keyed by slot id (one slot per actor). The jitted step gathers
the batch's states by slot index, runs the bound acting function, and
scatters the advanced states back — all inside ONE dispatch, with the
table buffer donated so the update is in-place in HBM. Per env step the
only host↔device traffic is observations down and actions/logits up;
agent state never crosses (pinned by the transfer-guard test in
tests/test_state_table.py).

Layout/contract notes:

- Slot `num_slots` is a TRASH slot: bucket padding scatters its rows
  there, so padded rows can never race a real slot's update (a masked
  scatter with duplicate indices would be last-writer-wins —
  nondeterministic about whether the real row's advance survives).
- Real slot ids must be unique within a batch. The actor pool
  guarantees this structurally: each actor owns one slot and has at
  most one request in flight.
- `advance=False` rows write their CURRENT state back (a no-op write):
  the actor pool's priming call computes agent outputs without
  persisting the state advance, same as the legacy `advance=False`
  path (reference monobeast.py:145-147).
- Dispatch is serialized under an internal lock because the table
  buffer is donated — a second dispatch against an already-donated
  reference would be a use-after-free. `read_slot`/`reset` share the
  lock; the host fetch in `read_slot` happens OUTSIDE it on a fresh
  (non-donated) gather output, so the inference hot path never blocks
  behind a rollout-boundary fetch.
"""

# beastlint: hot-module — the table dispatch runs once per acting batch.

import threading
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from torchbeast_tpu import nest
from torchbeast_tpu import telemetry


# Canonical re-export: the class lives in runtime/errors.py so the
# jax-free catch sites (actor pool, inference supervisor) can import it
# without pulling this module's jax dependency.
from torchbeast_tpu.runtime.errors import (  # noqa: F401
    StateTablePoisonedError,
)


def _leaves(tree) -> bool:
    return bool(jax.tree_util.tree_leaves(tree))


class DeviceStateTable:
    """On-device `[.., num_slots+1, ..]` agent-state pytree keyed by slot.

    act_fn(ctx, env_outputs, agent_state) -> (outputs, new_agent_state)
        Pure/traceable; runs INSIDE the table's jitted step. `ctx` is
        whatever `context_fn()` returns (e.g. (params, rng_key)) and is
        passed through as traced arguments, so fresh params/rng per
        call never trigger a recompile.

    Per-bucket static shapes: one compile per (batch bucket) — the
    same compile discipline as the legacy bucket-padded forward.
    """

    def __init__(
        self,
        initial_state: Any,
        num_slots: int,
        act_fn: Callable,
        context_fn: Optional[Callable] = None,
        batch_dim: int = 1,
        input_filter: Optional[Callable] = None,
        device=None,
    ):
        """`device` (optional): pin the table — and every dispatch — to
        one specific jax device. The Sebulba split (runtime/placement.py)
        builds one table per inference slice this way: the initial
        state, slot ids, and env inputs are all explicitly device_put
        there, so the jitted step executes on that device and the
        donated table buffer never leaves it. Context leaves (params,
        rng) are the CALLER's placement responsibility — the slice
        serving hooks place them on the same device (a mixed-device
        dispatch is a jax error, not a silent transfer). None keeps
        today's default-device behavior."""
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if not _leaves(initial_state):
            raise ValueError(
                "DeviceStateTable needs a non-empty state pytree; "
                "feed-forward models should use the legacy stateless path"
            )
        self.num_slots = num_slots
        self.batch_dim = batch_dim
        self.device = device
        self._act_fn = act_fn
        self._context_fn = context_fn
        self._input_filter = input_filter
        self._lock = threading.Lock()
        # Pure-host telemetry (perf_counter + dict increments only):
        # adds no device syncs to the acting hot path — pinned by the
        # transfer-guard test in tests/test_telemetry.py.
        _reg = telemetry.get_registry()
        self._tm_dispatches = _reg.counter("state_table.dispatches")
        self._tm_fetch_s = _reg.histogram("state_table.fetch_s")
        self._tm_read_slot_s = _reg.histogram("state_table.read_slot_s")

        bd = batch_dim
        for leaf in jax.tree_util.tree_leaves(initial_state):
            if np.ndim(leaf) <= bd or np.shape(leaf)[bd] != 1:
                raise ValueError(
                    "initial_state leaves must have size 1 along "
                    f"batch_dim {bd}; got shape {np.shape(leaf)}"
                )
        self._initial = jax.tree_util.tree_map(
            jnp.asarray, initial_state
        )
        if device is not None:
            # Commit the initial state to the pinned device: every
            # derived value (_fresh_table's tile, reset's gather) then
            # computes — and stays — there.
            self._initial = jax.device_put(self._initial, device)
        # Cached host copy: the actor pool hands it to rollouts as the
        # boundary state for freshly-connected actors.
        self.initial_state_host = jax.tree_util.tree_map(
            np.asarray, initial_state
        )
        # +1: the trash slot for bucket-padding rows.
        rows = num_slots + 1

        def expand(leaf):
            reps = [1] * leaf.ndim
            reps[bd] = rows
            return jnp.tile(leaf, reps)

        self._expand = expand
        self._table = self._fresh_table()

        def index(slots):
            return (slice(None),) * bd + (slots,)

        def gather(table, slots):
            return jax.tree_util.tree_map(
                lambda leaf: jnp.take(leaf, slots, axis=bd), table
            )

        def scatter(table, slots, values):
            return jax.tree_util.tree_map(
                lambda t, v: t.at[index(slots)].set(v), table, values
            )

        def step(table, slots, advance, ctx, env_outputs):
            state = gather(table, slots)
            outputs, new_state = act_fn(ctx, env_outputs, state)

            def merge(new, old):
                shape = [1] * new.ndim
                shape[bd] = advance.shape[0]
                return jnp.where(advance.reshape(shape), new, old)

            merged = jax.tree_util.tree_map(merge, new_state, state)
            return scatter(table, slots, merged), outputs

        def reset(table, slots, initial):
            values = jax.tree_util.tree_map(
                lambda leaf: jnp.take(
                    leaf, jnp.zeros_like(slots), axis=bd
                ),
                initial,
            )
            return scatter(table, slots, values)

        self._step_jit = jax.jit(step, donate_argnums=(0,))
        self._reset_jit = jax.jit(reset, donate_argnums=(0,))
        self._gather_jit = jax.jit(gather)

    def _fresh_table(self):
        """A brand-new [.., num_slots+1, ..] table, every slot at the
        initial state."""
        return jax.tree_util.tree_map(self._expand, self._initial)

    @property
    def trash_slot(self) -> int:
        """Slot id bucket padding scatters to (never read back)."""
        return self.num_slots

    @property
    def poisoned(self) -> bool:
        """True after a table-mutating dispatch failed. The table buffer
        is donated into every step/reset, so a dispatch that raises may
        already have consumed it — continuing would be a use-after-free
        with garbage state. All further calls raise
        StateTablePoisonedError; the serving loop re-raises to kill its
        thread rather than retry per-batch, and the inference
        supervisor (resilience/supervisor.py) owns the recovery:
        `rebuild()` + a thread restart under a bounded budget.

        Read under the table lock (cold path: exception handling and
        supervisor recovery only), so a concurrent poison/rebuild is
        seen whole rather than half-observed (RACE burn-down, ISSUE 7)."""
        with self._lock:
            return self._table is None

    def poison(self) -> None:
        """Chaos/testing hook: put the table into the poisoned state a
        failed donated dispatch produces (resilience/chaos.py's
        `state_table_poison` fault). The dropped buffer is reclaimed by
        XLA once its in-flight uses retire."""
        with self._lock:
            self._table = None

    def rebuild(self) -> None:
        """Recover from poisoning: a fresh table, every actor slot back
        at the initial state. Serving threads may restart immediately
        after. Actors whose request was in the FAILED batch re-prime
        via their batch-retry path (partial rollout discarded, same as
        a reconnect), so their slot state and rollout boundaries
        re-align. Actors with NO request in flight at poison time
        continue their current unroll against a silently-reset slot —
        a bounded mid-unroll state glitch (at most one unroll per
        actor per rebuild), equivalent to the episode-boundary resets
        V-trace already absorbs; pinned acceptable by the chaos
        harness's return-parity check."""
        with self._lock:
            self._table = self._fresh_table()

    def _require_alive(self):
        if self._table is None:
            raise StateTablePoisonedError(
                "DeviceStateTable is poisoned: a prior step/reset failed "
                "after its table buffer was donated; rebuild() it (the "
                "inference supervisor does) before serving again"
            )

    def _put_ids(self, slots):
        return jax.device_put(
            np.asarray(slots, np.int32).reshape(-1), self.device
        )

    def step(self, slots, advance, env_outputs, context=None):
        """One acting dispatch over already-padded inputs.

        slots: [n] int ids (padding rows = trash_slot), advance: [n]
        bool, env_outputs: env nest padded to n along batch_dim.
        Returns the on-device outputs nest (fetch with `fetch`).

        `context` overrides the table's own context_fn for THIS
        dispatch — the replica serving path (serving/replica.py) feeds
        snapshot params through the same jitted step (ctx leaves are
        traced arguments, so a replica batch never recompiles); the
        state rows gathered/scattered are the shared table's either
        way, so state continuity is preserved across routing changes.

        `input_filter` (host-side, BEFORE device_put) subsets the env
        nest to what act_fn actually reads: leaves the model ignores
        would otherwise still be transferred every dispatch and fatten
        the jit signature — and a prewarm built from the model schema
        would compile a signature real (unfiltered) traffic misses.
        """
        if self._input_filter is not None:
            env_outputs = self._input_filter(env_outputs)
        ctx = context
        if ctx is None and self._context_fn is not None:
            ctx = self._context_fn()
        slots_d = self._put_ids(slots)
        advance_d = jax.device_put(
            np.asarray(advance, bool).reshape(-1), self.device
        )
        env_d = jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, self.device), env_outputs
        )
        with self._lock:
            self._require_alive()
            table, self._table = self._table, None
            self._table, outputs = self._step_jit(
                table, slots_d, advance_d, ctx, env_d
            )
        self._tm_dispatches.inc()
        return outputs

    def fetch(self, outputs: Any, n: int) -> Any:
        """One explicit device_get of a step's padded outputs, sliced to
        the true batch size on HOST. Host-side slicing is deliberate: a
        device-side cut would either recompile per distinct true n (the
        dynamic batch size takes any value up to max_batch, unlike the
        handful of buckets) or upload fresh index constants per call —
        and the padding overhead fetched here is only the small
        action/logits/baseline rows, not agent state. Transfer-guard-
        clean: the device_get is explicit, the slice is numpy."""
        t0 = time.perf_counter()
        host = jax.device_get(outputs)
        bd = self.batch_dim

        def cut(arr):
            sl = [slice(None)] * arr.ndim
            sl[bd] = slice(0, n)
            return arr[tuple(sl)]

        out = jax.tree_util.tree_map(cut, host)
        self._tm_fetch_s.observe(time.perf_counter() - t0)
        return out

    def read_slot(self, slot: int) -> Any:
        """Host copy of one slot's state, shaped like `initial_state`
        (size 1 along batch_dim) — the rollout-boundary
        `initial_agent_state` fetch, once per unroll per actor."""
        t0 = time.perf_counter()
        ids = self._put_ids([slot])
        with self._lock:
            self._require_alive()
            piece = self._gather_jit(self._table, ids)
        out = jax.device_get(piece)
        self._tm_read_slot_s.observe(time.perf_counter() - t0)
        return out

    def reset(self, slots) -> None:
        """Reset `slots` to the initial state (actor connect/reconnect)."""
        ids = self._put_ids(slots)
        with self._lock:
            self._require_alive()
            table, self._table = self._table, None
            self._table = self._reset_jit(table, ids, self._initial)
