"""Remat auto-tuning under supersteps: choose per-stage remat =
f(HBM headroom, K) instead of the static all-remat default.

The learner's rematerialization levers were, until ISSUE 13, static
booleans chosen for the worst case: the ResNet trunk remats every stage
(the configuration that fits a 15.75 GB v5e at the flagship shape), the
transformer never remats unless --transformer_remat, and the LSTM scan
always saves its gate activations. But remat trades HBM for recompute —
on a run whose (K, T, B, precision) leaves headroom, recomputing is
pure waste, and on one that does not, a single under-remat'd stage
OOMs. This module makes the choice a measured decision:

- Every model family exposes a small per-stage lattice of remat
  settings (stages_for): the ResNet trunk's per-stage False/"front"/
  True (models/resnet.py), the transformer families' block remat, and
  the LSTM scan's step remat (models/cores.LSTMCore.remat) — each
  option list ordered by increasing recompute.
- The planner (plan_remat) picks the MINIMUM-RECOMPUTE assignment
  whose peak HBM fits a budget. Peak comes from XLA itself:
  precision.memory_stats lowers the exact superstep the driver will
  dispatch (same K/T/B/precision) and reads the compiled module's
  temp/argument/output allocation — the `bytes_accessed` machinery
  extended to peak allocation. Recompute is compared through the same
  lowering's pre-opt bytes-accessed figure (rematerialized ops appear
  as real reads in the pre-opt HLO, so more remat == more bytes there).
- Nothing fits -> fall back to all-remat (the save-everything-
  recompute-everything configuration, today's static default) with the
  failure visible in the plan table.

Exposed on both drivers as `--remat {auto,all,none,<spec>}` +
`--hbm_budget_gb`; the chosen plan is logged and exported as the
`learner.remat_plan` telemetry static. `<spec>` pins stages by hand:
a comma list of `stage=setting` with settings {none,front,all}, e.g.
`--remat stage0=front,stage1=all,stage2=all,core=none`.

Budget semantics: the envelope covers ONE live update dispatch
(params + optimizer state + staged [K, T+1, B, ...] stack + XLA temp
buffers). The planner's peak is measured on the ambient backend's
compiled module — on the chipless container that is XLA:CPU, which
widens bf16 to f32 emulation, so the figure is an UPPER bound for the
bf16 policies (the safe direction for a fits-in-budget decision). On a
real TPU the same call reads the true HBM assignment.
"""

import itertools
import logging
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

log = logging.getLogger(__name__)

# Flag-spelling <-> model-kwarg values, ordered nowhere (the ORDER
# lives in the per-stage option tuples below).
SETTING_NAMES = {"none": False, "front": "front", "all": True}
_SETTING_SPELLING = {False: "none", "front": "front", True: "all"}

# Default budget when --hbm_budget_gb is 0/unset and the device reports
# no memory limit: a v5e's 16 GB minus the runtime reserve — the chip
# the committed roofline evidence (BENCH_r05) was measured on.
DEFAULT_BUDGET_GB = 15.75


class Stage(NamedTuple):
    """One remat lever: `options` ordered by INCREASING recompute
    (index 0 saves everything, the last entry remats the most)."""

    name: str
    options: Tuple[Any, ...]


def stages_for(model: str, use_lstm: bool) -> List[Stage]:
    """The remat lattice of one model family (empty = nothing to plan:
    the feed-forward MLP/AtariNet trunks are not remat-able levers)."""
    stages: List[Stage] = []
    if model in ("deep", "resnet"):
        for i in range(3):
            stages.append(Stage(f"stage{i}", (False, "front", True)))
    if model in ("transformer", "pipelined_transformer"):
        stages.append(Stage("blocks", (False, True)))
    if use_lstm:
        stages.append(Stage("core", (False, True)))
    return stages


def model_kwargs(model: str, assignment: Dict[str, Any]) -> Dict[str, Any]:
    """Assignment -> create_model(**kwargs) for the family's levers."""
    kwargs: Dict[str, Any] = {}
    if model in ("deep", "resnet"):
        kwargs["remat"] = tuple(
            assignment[f"stage{i}"] for i in range(3)
        )
    if model in ("transformer", "pipelined_transformer"):
        kwargs["remat"] = bool(assignment["blocks"])
    if "core" in assignment:
        kwargs["core_remat"] = bool(assignment["core"])
    return kwargs


def _level_assignment(stages: List[Stage], level: int) -> Dict[str, Any]:
    """Every stage at `level` clamped to its own option count."""
    return {
        s.name: s.options[min(level, len(s.options) - 1)] for s in stages
    }


def all_remat(stages: List[Stage]) -> Dict[str, Any]:
    """The save-everything fallback (today's static default)."""
    return _level_assignment(stages, max(
        (len(s.options) for s in stages), default=1
    ))


def no_remat(stages: List[Stage]) -> Dict[str, Any]:
    return _level_assignment(stages, 0)


def enumerate_assignments(stages: List[Stage]) -> List[Dict[str, Any]]:
    """Every per-stage combination, ordered by ascending recompute RANK
    (sum of per-stage option indices, ties broken by the index tuple) —
    minimum recompute first, all-remat last. The rank is the lazy
    walk's evaluation order; the exhaustive planner re-orders by the
    cost model's measured recompute."""
    if not stages:
        return [{}]
    level_sets = [range(len(s.options)) for s in stages]
    combos = sorted(
        itertools.product(*level_sets),
        key=lambda levels: (sum(levels), levels),
    )
    return [
        {s.name: s.options[lv] for s, lv in zip(stages, levels)}
        for levels in combos
    ]


def spell(assignment: Dict[str, Any]) -> str:
    return ",".join(
        f"{name}={_SETTING_SPELLING[val]}"
        for name, val in sorted(assignment.items())
    )


def parse_spec(spec: str, stages: List[Stage]) -> Dict[str, Any]:
    """`stage0=front,core=all` -> assignment, validated against the
    family's stages and each stage's own option set."""
    by_name = {s.name: s for s in stages}
    assignment: Dict[str, Any] = {}
    for part in spec.split(","):
        if "=" not in part:
            raise ValueError(
                f"--remat spec entry {part!r} is not stage=setting "
                f"(stages for this model: {sorted(by_name) or 'none'})"
            )
        name, _, setting = part.partition("=")
        name, setting = name.strip(), setting.strip()
        if name not in by_name:
            raise ValueError(
                f"--remat spec names unknown stage {name!r} "
                f"(stages for this model: {sorted(by_name) or 'none'})"
            )
        if setting not in SETTING_NAMES:
            raise ValueError(
                f"--remat spec setting {setting!r} for stage {name!r} "
                f"must be one of {sorted(SETTING_NAMES)}"
            )
        value = SETTING_NAMES[setting]
        if value not in by_name[name].options:
            raise ValueError(
                f"--remat stage {name!r} has no {setting!r} option "
                f"(choices: "
                f"{[_SETTING_SPELLING[o] for o in by_name[name].options]})"
            )
        if name in assignment:
            raise ValueError(f"--remat spec repeats stage {name!r}")
        assignment[name] = value
    missing = set(by_name) - set(assignment)
    if missing:
        raise ValueError(
            f"--remat spec misses stages {sorted(missing)} "
            "(every stage needs a setting)"
        )
    return assignment


class PlanResult(NamedTuple):
    """One planning outcome. `assignment` is the chosen per-stage
    setting; `source` records how it was chosen ("auto", "all",
    "none", "spec", "default", or "fallback" when no candidate fit the
    budget); `table` carries every evaluated candidate (assignment
    spelling, peak, recompute, fits) for the telemetry static."""

    assignment: Dict[str, Any]
    source: str
    budget_bytes: Optional[float]
    peak_bytes: Optional[float]
    recompute_bytes: Optional[float]
    table: Tuple[Dict[str, Any], ...]

    def summary(self, include_table: bool = False) -> Dict[str, Any]:
        """JSON-able form for the `learner.remat_plan` static + logs.
        The per-candidate table is opt-in: the static re-serializes
        into EVERY telemetry.jsonl line, and up to 64 identical table
        rows per 5-second snapshot is pure bloat — the table is logged
        once at resolution instead."""
        out = {
            "assignment": {
                k: _SETTING_SPELLING[v]
                for k, v in sorted(self.assignment.items())
            },
            "source": self.source,
            "budget_bytes": self.budget_bytes,
            "peak_bytes": self.peak_bytes,
            "recompute_bytes": self.recompute_bytes,
            "evaluated": len(self.table),
        }
        if include_table:
            out["table"] = list(self.table)
        return out


def plan_remat(
    stages: List[Stage],
    cost_fn: Callable[[Dict[str, Any]], Tuple[Optional[float],
                                              Optional[float]]],
    budget_bytes: float,
    lazy: bool = False,
    max_evals: int = 64,
) -> PlanResult:
    """Pick the minimum-recompute assignment whose peak fits the budget.

    `cost_fn(assignment) -> (peak_bytes, recompute_bytes)`; a None peak
    means the oracle could not measure that candidate (it is skipped —
    never chosen on faith). `lazy=True` walks candidates in ascending
    recompute-RANK order and stops at the first fit (the driver path,
    where each evaluation lowers+compiles the real superstep);
    `lazy=False` evaluates everything and picks the true measured
    minimum (tests and the bench). Nothing fits -> all-remat fallback,
    the one case whose peak may exceed the budget (it is also today's
    static default, so the fallback never regresses the pre-planner
    behavior)."""
    candidates = enumerate_assignments(stages)[:max_evals]
    table: List[Dict[str, Any]] = []
    fitting: List[Tuple[float, int, Dict[str, Any], float]] = []
    for idx, assignment in enumerate(candidates):
        peak, recompute = cost_fn(assignment)
        fits = peak is not None and peak <= budget_bytes
        table.append({
            "assignment": spell(assignment),
            "peak_bytes": peak,
            "recompute_bytes": recompute,
            "fits": bool(fits),
        })
        if fits:
            rec = recompute if recompute is not None else float("inf")
            fitting.append((rec, idx, assignment, peak))
            if lazy:
                break
    if fitting:
        rec, _, assignment, peak = min(fitting, key=lambda t: t[:2])
        return PlanResult(
            assignment=assignment,
            source="auto",
            budget_bytes=float(budget_bytes),
            peak_bytes=peak,
            recompute_bytes=None if rec == float("inf") else rec,
            table=tuple(table),
        )
    fallback = all_remat(stages)
    peak = recompute = None
    for row in table:
        if row["assignment"] == spell(fallback):
            peak, recompute = row["peak_bytes"], row["recompute_bytes"]
            break
    return PlanResult(
        assignment=fallback,
        source="fallback",
        budget_bytes=float(budget_bytes),
        peak_bytes=peak,
        recompute_bytes=recompute,
        table=tuple(table),
    )


def default_budget_bytes() -> float:
    """--hbm_budget_gb unset: the device's own limit when it reports
    one, else the v5e envelope the roofline work targets."""
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        limit = (stats or {}).get("bytes_limit")
        if limit:
            return float(limit)
    except Exception:  # pragma: no cover - backend without stats
        log.debug("device memory_stats unavailable", exc_info=True)
    return DEFAULT_BUDGET_GB * (1 << 30)


def superstep_cost_fn(
    build_model: Callable[[Dict[str, Any]], Any],
    hp,
    superstep_k: int,
    batch_structs: Dict[str, Any],
    state_batch_size: int,
    model_name: str,
) -> Callable[[Dict[str, Any]], Tuple[Optional[float], Optional[float]]]:
    """The driver's cost oracle: build the candidate model, eval_shape
    its params/opt-state (no compute, no buffers), and read
    precision.memory_stats off the EXACT jitted (super)step the run
    will dispatch. All inputs are ShapeDtypeStructs — a candidate
    evaluation allocates nothing but the compile itself."""
    import jax

    from torchbeast_tpu import learner as learner_lib
    from torchbeast_tpu import precision as precision_lib

    rngs = {
        "params": jax.random.PRNGKey(0),
        "action": jax.random.PRNGKey(1),
    }
    # A [1, B] dummy in the env-output schema (model init never sees
    # the learner's T; dtypes ride along from the staged batch — the
    # models astype at use either way).
    dummy = {
        key: jax.ShapeDtypeStruct(
            (1, state_batch_size) + tuple(s.shape[2:]), s.dtype
        )
        for key, s in batch_structs.items()
        if key in ("frame", "reward", "done", "last_action")
    }

    def cost_fn(assignment):
        try:
            model = build_model(model_kwargs(model_name, assignment))
            state = jax.eval_shape(
                lambda: model.initial_state(state_batch_size)
            )
            params = jax.eval_shape(
                lambda d, s: model.init(rngs, d, s), dummy, state
            )
            optimizer = learner_lib.make_optimizer(hp)
            opt_state = jax.eval_shape(optimizer.init, params)
            if superstep_k > 1:
                update = learner_lib.make_update_superstep(
                    model, optimizer, hp, superstep_k, donate=False
                )
                stack = lambda s: jax.ShapeDtypeStruct(  # noqa: E731
                    (superstep_k,) + tuple(s.shape), s.dtype
                )
                batch = {
                    k: stack(s) for k, s in batch_structs.items()
                }
                states = jax.tree_util.tree_map(stack, state)
            else:
                update = learner_lib.make_update_step(
                    model, optimizer, hp, donate=False
                )
                batch = dict(batch_structs)
                states = state
            stats = precision_lib.memory_stats(
                update, params, opt_state, batch, states
            )
            return stats.peak_bytes, stats.bytes_accessed
        except Exception:
            log.debug(
                "remat cost evaluation failed for %s",
                spell(assignment), exc_info=True,
            )
            return None, None

    return cost_fn


def learner_batch_structs(
    hp, num_actions: int, frame_shape, frame_dtype, batch_dtype=None
):
    """ShapeDtypeStructs of one [T+1, B] learner batch in the actor-pool
    schema, float leaves in the precision policy's staging dtype."""
    import jax
    import numpy as np

    t1 = hp.unroll_length + 1
    b = hp.batch_size
    f32 = np.dtype(batch_dtype) if batch_dtype is not None else (
        np.dtype(np.float32)
    )
    return {
        "frame": jax.ShapeDtypeStruct(
            (t1, b) + tuple(frame_shape), np.dtype(frame_dtype)
        ),
        "reward": jax.ShapeDtypeStruct((t1, b), f32),
        "done": jax.ShapeDtypeStruct((t1, b), np.dtype(bool)),
        "episode_return": jax.ShapeDtypeStruct((t1, b), f32),
        "episode_step": jax.ShapeDtypeStruct(
            (t1, b), np.dtype(np.int32)
        ),
        "last_action": jax.ShapeDtypeStruct(
            (t1, b), np.dtype(np.int32)
        ),
        "action": jax.ShapeDtypeStruct((t1, b), np.dtype(np.int32)),
        "policy_logits": jax.ShapeDtypeStruct(
            (t1, b, num_actions), f32
        ),
        "baseline": jax.ShapeDtypeStruct((t1, b), f32),
    }


# Memoized driver-resolution results: polybeast builds the model twice
# (learner + unmeshed acting twin) from identical flags, and an auto
# plan compiles candidates — the second resolution must be free. Also
# the hook DriverTelemetry reads for the `learner.remat_plan` static.
_RESOLVED: Dict[Tuple, PlanResult] = {}
_LAST: List[Optional[PlanResult]] = [None]


def last_plan() -> Optional[PlanResult]:
    """The most recent resolution in this process (driver startup is
    single-threaded; the drivers read this right after model init to
    log + export the `learner.remat_plan` static)."""
    return _LAST[0]


def resolve_from_flags(
    flags, hp, num_actions: int, frame_shape, frame_dtype,
    policy, build_model: Callable[[Dict[str, Any]], Any],
) -> PlanResult:
    """Driver entry: flags.remat -> the plan + model kwargs.

    - None (flag unset): the pre-ISSUE-13 static defaults — ResNet
      all-remat, transformer blocks per --transformer_remat, LSTM scan
      un-remat'd (source="default"; no planning cost).
    - "all" / "none": every stage at its max-save / no-remat setting.
    - "auto": plan_remat over the family lattice with the superstep
      cost oracle against --hbm_budget_gb (0 = the device limit, else
      the v5e default envelope). Lazy first-fit walk in recompute-rank
      order: big budgets evaluate ONE candidate.
    - anything else: a per-stage spec (parse_spec).
    """
    model_name = flags.model
    use_lstm = bool(getattr(flags, "use_lstm", False))
    stages = stages_for(model_name, use_lstm)
    remat_flag = getattr(flags, "remat", None)
    transformer_remat = bool(getattr(flags, "transformer_remat", False))
    if remat_flag is not None and transformer_remat:
        raise ValueError(
            "--transformer_remat is the deprecated spelling of "
            "--remat all (blocks stage); pass only --remat"
        )
    budget_gb = float(getattr(flags, "hbm_budget_gb", 0.0) or 0.0)
    superstep_k = int(getattr(flags, "superstep_k", 1) or 1)
    # hp rides the key WHOLE (a hashable NamedTuple): optimizer-shape
    # knobs (momentum adds a params-sized trace, factored/bf16 state
    # change opt_state bytes) move the measured peak, so an auto plan
    # is only reusable for an identical learner configuration.
    key = (
        remat_flag, transformer_remat, budget_gb, model_name, use_lstm,
        policy.name, superstep_k, hp,
        num_actions, tuple(frame_shape), str(frame_dtype),
    )
    cached = _RESOLVED.get(key)
    if cached is not None:
        _LAST[0] = cached
        return cached

    if remat_flag is None:
        assignment = all_remat(stages)
        if "blocks" in assignment:
            assignment["blocks"] = transformer_remat
        if "core" in assignment:
            assignment["core"] = False
        plan = PlanResult(
            assignment=assignment, source="default",
            budget_bytes=None, peak_bytes=None, recompute_bytes=None,
            table=(),
        )
    elif remat_flag == "all":
        plan = PlanResult(
            assignment=all_remat(stages), source="all",
            budget_bytes=None, peak_bytes=None, recompute_bytes=None,
            table=(),
        )
    elif remat_flag == "none":
        plan = PlanResult(
            assignment=no_remat(stages), source="none",
            budget_bytes=None, peak_bytes=None, recompute_bytes=None,
            table=(),
        )
    elif remat_flag == "auto":
        budget = (
            budget_gb * (1 << 30) if budget_gb > 0
            else default_budget_bytes()
        )
        cost_fn = superstep_cost_fn(
            build_model, hp, superstep_k,
            learner_batch_structs(
                hp, num_actions, frame_shape, frame_dtype,
                policy.batch_dtype,
            ),
            hp.batch_size, model_name,
        )
        plan = plan_remat(stages, cost_fn, budget, lazy=True)
    else:
        plan = PlanResult(
            assignment=parse_spec(remat_flag, stages), source="spec",
            budget_bytes=None, peak_bytes=None, recompute_bytes=None,
            table=(),
        )
    _RESOLVED[key] = plan
    _LAST[0] = plan
    if plan.source == "fallback":
        log.warning(
            "remat auto-tuning: no candidate fits the %.2f GB budget; "
            "falling back to all-remat (%s)",
            (plan.budget_bytes or 0) / (1 << 30),
            spell(plan.assignment),
        )
    elif remat_flag is not None:
        log.info(
            "remat plan (%s): %s", plan.source,
            spell(plan.assignment) or "<no remat-able stages>",
        )
    if plan.table:
        # The evaluation table is logged ONCE here; the telemetry
        # static carries only the compact summary (see summary()).
        log.info("remat plan candidates: %s", list(plan.table))
    return plan
