"""TPU inference server loop: drain the DynamicBatcher with a jitted,
bucket-padded forward.

The reference's inference threads run the model on whatever batch size the
batcher produced (polybeast_learner.py:269-285) — fine for CUDA, hostile to
XLA, where every distinct batch size is a recompile (SURVEY.md §7 hard part
#1). Here each dynamic batch is padded up to the nearest power-of-two bucket
(the last row repeated — see pad_to), the jitted step runs at that static
shape (one compile per bucket, a handful total), and the outputs are sliced
back to the true size before set_outputs distributes rows to the waiting
actors.

Two state regimes:

- Legacy (state_table=None): requests carry `agent_state`; the loop pads
  it alongside the env nest and the reply materializes the advanced state
  back to the actor — state crosses the host boundary twice per step.
- Device-resident (state_table=DeviceStateTable): requests carry a `slot`
  id and an `advance` flag instead of state; the table's jitted step
  gathers/advances/scatters state entirely on device and the reply holds
  outputs only. Padding rows scatter to the table's trash slot so they
  can never race a real slot's update.
"""

# beastlint: hot-module — every function here sits on the per-batch serving path.

import logging
import threading
import time
from typing import Any, Callable, List

import numpy as np

from torchbeast_tpu import nest
from torchbeast_tpu import telemetry

log = logging.getLogger(__name__)


def bucket_size(n: int, buckets: List[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"Batch of {n} exceeds largest bucket {buckets[-1]}")


def default_buckets(max_batch_size: int) -> List[int]:
    out = []
    b = 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(max_batch_size)
    return out


def pad_to(tree: Any, size: int, batch_dim: int) -> Any:
    """Pad every leaf to `size` along batch_dim by repeating the edge row
    (valid data, so the padded forward can't produce NaNs that would
    poison batch-norm-style reductions; pad rows are sliced off after)."""

    def pad(arr):
        arr = np.asarray(arr)
        n = arr.shape[batch_dim]
        if n == size:
            return arr
        pad_width = [(0, 0)] * arr.ndim
        pad_width[batch_dim] = (0, size - n)
        return np.pad(arr, pad_width, mode="edge")

    return nest.map(pad, tree)


def slice_to(tree: Any, size: int, batch_dim: int) -> Any:
    def cut(arr):
        arr = np.asarray(arr)
        sl = [slice(None)] * arr.ndim
        sl[batch_dim] = slice(0, size)
        return arr[tuple(sl)]

    return nest.map(cut, tree)


def pad_slots(slots: np.ndarray, size: int, trash_slot: int) -> np.ndarray:
    """Pad a [n] slot-id vector to `size` with the table's trash slot —
    NOT edge-repeated: a repeated real id would make the padded row's
    scatter race the real row's (duplicate-index scatter is last-writer-
    wins, so the real advance could be silently dropped)."""
    slots = np.asarray(slots).reshape(-1)
    if slots.shape[0] == size:
        return slots
    return np.concatenate(
        [slots, np.full(size - slots.shape[0], trash_slot, slots.dtype)]
    )


def pad_advance(advance: np.ndarray, size: int) -> np.ndarray:
    """Pad a [n] advance mask to `size` with False (padding rows must
    never persist a state advance)."""
    advance = np.asarray(advance, bool).reshape(-1)
    if advance.shape[0] == size:
        return advance
    return np.concatenate(
        [advance, np.zeros(size - advance.shape[0], bool)]
    )


def inference_loop(
    inference_batcher,
    act_fn: Callable,
    max_batch_size: int,
    batch_dim: int = 1,
    lock: threading.Lock = None,
    pipelined: bool = False,
    state_table=None,
    serving_hooks=None,
    throttle_fn: Callable = None,
    telemetry_prefix: str = "inference",
):
    """Thread body (run num_inference_threads of these).

    act_fn(env_outputs, agent_state, batch_size) ->
        (agent_outputs, new_agent_state)   # numpy or device arrays

    With `state_table` (a runtime.state_table.DeviceStateTable), requests
    carry {"env", "slot", "advance"} instead of {"env", "agent_state"}:
    the table's own jitted step (which owns params/rng threading via its
    context_fn) gathers/advances/scatters agent state on device and
    `act_fn` is ignored (pass None). Replies then hold {"outputs"} only —
    no state leaf ever crosses the host boundary
    (tests/test_state_table.py pins this with jax.transfer_guard).

    act_fn owns params access and rng threading (see polybeast.py). Pass
    ONE lock shared by every inference thread to serialize model calls
    (the reference's inference lock, polybeast_learner.py:269, 281-283);
    with lock=None calls run concurrently (safe for pure jitted act_fns —
    the device serializes execution anyway).

    `pipelined` keeps a one-deep dispatch pipeline: when more requests
    are already waiting, batch k's host fetch (`np.asarray`, a full
    device round-trip — ~50 ms through a remote-TPU tunnel) happens
    AFTER batch k+1's act is dispatched, so the device always has a
    queued program and never idles on the reply path. The reply to k is
    only ever deferred while k+1 is in hand; when the batcher is empty
    the fetch happens immediately. SINGLE-CONSUMER ONLY: the "more
    requests waiting" check is a racy global size() — with several
    threads draining one batcher, another thread can steal the waiting
    request and leave this one parked on an empty batcher while holding
    finished replies, stalling those actors until new traffic arrives.
    Tail-latency cost: the held reply for batch k is only flushed once
    the batcher YIELDS batch k+1 — if size() > 0 but that next batch is
    still forming (waiting on stragglers to reach min batch size), the
    deferred actors wait up to the batcher's formation timeout (default
    100 ms) beyond the dispatch-side win. Worth it only when the reply
    path is the bottleneck (remote-tunnel round-trips); for local
    devices the default (off) avoids the tail.
    Default OFF: only enable it for a single consumer thread
    (polybeast wires pipelined=num_inference_threads==1; cross-thread
    overlap already comes from the threads themselves).

    A failing act_fn fails only its batch (promises broken with the error
    so producers wake immediately); the loop continues serving. Exception:
    a failed STATE-TABLE step poisons the table (its buffer is donated
    into the dispatch, so it may already be consumed) — the loop fails
    the batch and re-raises to kill the thread rather than serve garbage.

    `serving_hooks` (serving/replica.ReplicaServingHooks, or anything
    with the same `begin_batch() -> (ctx, annotate)` shape) turns this
    loop into a REPLICA serving loop: each batch's ctx overrides the
    state table's own context (snapshot params instead of live ones) —
    or, on the legacy path, rides as a 4th act_fn argument
    (`act_fn(env, state, batch_size, ctx)`) — and `annotate(outputs, n)`
    stamps the matching policy_lag into the reply at flush time, so the
    lag recorded is the lag of the params that actually served.

    `throttle_fn` (resilience/chaos.ChaosController.throttle) is the
    chaos harness's shared-chip stall model: called once per batch
    before dispatch; sleeps while a learner_stall window is active so
    induced overload grows the batcher queue the way a busy chip would.

    `telemetry_prefix` names this loop's instrument series (default
    "inference", today's schema). The Sebulba split runs one loop per
    inference slice with prefix "inference.slice.<i>" so per-slice
    batch/latency/poison series land on every telemetry line instead
    of aggregating into one indistinguishable pile.
    """
    buckets = default_buckets(max_batch_size)

    # Stage attribution for the serving loop (ISSUE 2): batch-size
    # distribution, lock contention, dispatch latency (async — the time
    # to hand XLA the program, not device compute), reply latency (the
    # device fetch + row slicing actors actually wait on). Instruments
    # resolve once; per-batch cost is a few perf_counter calls.
    _reg = telemetry.get_registry()
    _tracer = telemetry.get_tracer()
    _h_batch = _reg.histogram(f"{telemetry_prefix}.batch_size")
    # Registered only when a lock exists: a permanently-zero histogram
    # reads as "requests never wait", not "not measured".
    _h_lock = (
        _reg.histogram(f"{telemetry_prefix}.lock_wait_s")
        if lock is not None else None
    )
    _h_dispatch = _reg.histogram(f"{telemetry_prefix}.dispatch_s")
    _h_reply = _reg.histogram(f"{telemetry_prefix}.reply_s")
    _c_batches = _reg.counter(f"{telemetry_prefix}.batches")
    _c_rows = _reg.counter(f"{telemetry_prefix}.rows")
    _c_poison = _reg.counter(f"{telemetry_prefix}.poison_exits")
    # A Python DynamicBatcher with a telemetry_name already observes
    # inference.batch_size per dequeued batch — observing here too
    # would double-count it. The loop keeps that role only for
    # un-instrumented batchers (the C++ native runtime).
    _observe_sizes = getattr(inference_batcher, "_tm", None) is None

    def flush(entry):
        batch, outputs, new_state, n, annotate = entry
        t_reply = time.perf_counter()
        try:
            if state_table is not None:
                # Device-side slice + one explicit device_get; the
                # reply carries no agent-state leaves.
                fetched = state_table.fetch(outputs, n)
                if annotate is not None:
                    fetched = annotate(fetched, n)
                batch.set_outputs({"outputs": fetched})
                return
            outputs = nest.map(np.asarray, outputs)
            new_state = nest.map(np.asarray, new_state)
            outputs = slice_to(outputs, n, batch_dim)
            if annotate is not None:
                outputs = annotate(outputs, n)
            batch.set_outputs(
                {
                    "outputs": outputs,
                    "agent_state": slice_to(new_state, n, batch_dim),
                }
            )
        except Exception as e:  # noqa: BLE001
            log.exception("Inference reply failed; continuing")
            batch.fail(e)
        finally:
            _h_reply.observe(time.perf_counter() - t_reply)

    pending = None
    batches = iter(inference_batcher)
    while True:
        # The stall gate runs BEFORE the blocking pull: a stalled chip
        # does not pick work up, so queued requests age toward their
        # deadline and the dequeue-side expiry gate sees the truth. A
        # throttle placed after the pull would grab fresh requests and
        # hold them un-expirable for the whole window.
        if throttle_fn is not None:
            throttle_fn()
        try:
            batch = next(batches)
        except StopIteration:
            break
        try:
            inputs = batch.get_inputs()
            env_outputs = inputs["env"]
            n = len(batch)
            if _observe_sizes:
                _h_batch.observe(n)
            _c_batches.inc()
            _c_rows.inc(n)
            padded = bucket_size(n, buckets)
            env_padded = pad_to(env_outputs, padded, batch_dim)

            def dispatch(fn):
                # inference.dispatch_s times ONLY the act dispatch (the
                # host handing XLA the program) — padding is host prep
                # and the lock wait has its own histogram; folding them
                # in would double-count stages and misattribute a lock
                # bottleneck to XLA.
                t0 = time.perf_counter()
                with _tracer.span(
                    f"{telemetry_prefix}.dispatch", cat="inference",
                    rows=n, padded=padded,
                ):
                    result = fn()
                _h_dispatch.observe(time.perf_counter() - t0)
                return result

            # Replica mode: ONE atomic (snapshot ctx, lag annotation)
            # pick per batch, so the lag stamped into the reply is the
            # lag of the params this dispatch actually used.
            ctx = annotate = None
            if serving_hooks is not None:
                ctx, annotate = serving_hooks.begin_batch()

            if state_table is not None:
                slots = pad_slots(
                    inputs["slot"], padded, state_table.trash_slot
                )
                advance = pad_advance(inputs["advance"], padded)
                outputs = dispatch(
                    lambda: state_table.step(
                        slots, advance, env_padded, context=ctx
                    )
                )
                new_state = None
            else:
                state_padded = pad_to(
                    inputs["agent_state"], padded, batch_dim
                )
                act_args = (env_padded, state_padded, padded)
                if serving_hooks is not None:
                    act_args = act_args + (ctx,)
                if lock is not None:
                    t_lock = time.perf_counter()
                    with lock:
                        _h_lock.observe(time.perf_counter() - t_lock)
                        outputs, new_state = dispatch(
                            lambda: act_fn(*act_args)
                        )
                else:
                    outputs, new_state = dispatch(
                        lambda: act_fn(*act_args)
                    )
        except Exception as e:  # noqa: BLE001
            batch.fail(e)
            if pending is not None:
                flush(pending)
                pending = None
            if state_table is not None and state_table.poisoned:
                # The donated table buffer may already be consumed;
                # per-batch retry would serve garbage state. Die loudly
                # — with the TYPED error, so a supervising wrapper
                # (resilience.InferenceSupervisor) can distinguish
                # "rebuild the table and restart me" from a real
                # serving bug that must stay fatal.
                from torchbeast_tpu.runtime.errors import (
                    StateTablePoisonedError,
                )

                _c_poison.inc()
                log.exception("State table poisoned; inference thread exiting")
                if isinstance(e, StateTablePoisonedError):
                    raise
                raise StateTablePoisonedError(
                    f"state table poisoned by: {type(e).__name__}: {e}"
                ) from e
            log.exception("Inference batch failed; continuing")
            continue
        # This batch is dispatched (async); NOW reply to the previous one.
        if pending is not None:
            flush(pending)
            pending = None
        if pipelined and inference_batcher.size() > 0:
            pending = (batch, outputs, new_state, n, annotate)
        else:
            flush((batch, outputs, new_state, n, annotate))
    if pending is not None:  # batcher closed with a reply in flight
        flush(pending)
