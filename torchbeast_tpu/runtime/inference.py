"""TPU inference server loop: drain the DynamicBatcher with a jitted,
bucket-padded forward.

The reference's inference threads run the model on whatever batch size the
batcher produced (polybeast_learner.py:269-285) — fine for CUDA, hostile to
XLA, where every distinct batch size is a recompile (SURVEY.md §7 hard part
#1). Here each dynamic batch is padded up to the nearest power-of-two bucket
(row 0 repeated), the jitted step runs at that static shape (one compile per
bucket, a handful total), and the outputs are sliced back to the true size
before set_outputs distributes rows to the waiting actors.
"""

import logging
import threading
from typing import Any, Callable, List

import numpy as np

from torchbeast_tpu import nest

log = logging.getLogger(__name__)


def bucket_size(n: int, buckets: List[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"Batch of {n} exceeds largest bucket {buckets[-1]}")


def default_buckets(max_batch_size: int) -> List[int]:
    out = []
    b = 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(max_batch_size)
    return out


def pad_to(tree: Any, size: int, batch_dim: int) -> Any:
    """Pad every leaf to `size` along batch_dim by repeating the edge row
    (valid data, so the padded forward can't produce NaNs that would
    poison batch-norm-style reductions; pad rows are sliced off after)."""

    def pad(arr):
        arr = np.asarray(arr)
        n = arr.shape[batch_dim]
        if n == size:
            return arr
        pad_width = [(0, 0)] * arr.ndim
        pad_width[batch_dim] = (0, size - n)
        return np.pad(arr, pad_width, mode="edge")

    return nest.map(pad, tree)


def slice_to(tree: Any, size: int, batch_dim: int) -> Any:
    def cut(arr):
        arr = np.asarray(arr)
        sl = [slice(None)] * arr.ndim
        sl[batch_dim] = slice(0, size)
        return arr[tuple(sl)]

    return nest.map(cut, tree)


def inference_loop(
    inference_batcher,
    act_fn: Callable,
    max_batch_size: int,
    batch_dim: int = 1,
    lock: threading.Lock = None,
    pipelined: bool = False,
):
    """Thread body (run num_inference_threads of these).

    act_fn(env_outputs, agent_state, batch_size) ->
        (agent_outputs, new_agent_state)   # numpy or device arrays

    act_fn owns params access and rng threading (see polybeast.py). Pass
    ONE lock shared by every inference thread to serialize model calls
    (the reference's inference lock, polybeast_learner.py:269, 281-283);
    with lock=None calls run concurrently (safe for pure jitted act_fns —
    the device serializes execution anyway).

    `pipelined` keeps a one-deep dispatch pipeline: when more requests
    are already waiting, batch k's host fetch (`np.asarray`, a full
    device round-trip — ~50 ms through a remote-TPU tunnel) happens
    AFTER batch k+1's act is dispatched, so the device always has a
    queued program and never idles on the reply path. The reply to k is
    only ever deferred while k+1 is in hand; when the batcher is empty
    the fetch happens immediately. SINGLE-CONSUMER ONLY: the "more
    requests waiting" check is a racy global size() — with several
    threads draining one batcher, another thread can steal the waiting
    request and leave this one parked on an empty batcher while holding
    finished replies, stalling those actors until new traffic arrives.
    Tail-latency cost: the held reply for batch k is only flushed once
    the batcher YIELDS batch k+1 — if size() > 0 but that next batch is
    still forming (waiting on stragglers to reach min batch size), the
    deferred actors wait up to the batcher's formation timeout (default
    100 ms) beyond the dispatch-side win. Worth it only when the reply
    path is the bottleneck (remote-tunnel round-trips); for local
    devices the default (off) avoids the tail.
    Default OFF: only enable it for a single consumer thread
    (polybeast wires pipelined=num_inference_threads==1; cross-thread
    overlap already comes from the threads themselves).

    A failing act_fn fails only its batch (promises broken with the error
    so producers wake immediately); the loop continues serving.
    """
    buckets = default_buckets(max_batch_size)

    def flush(entry):
        batch, outputs, new_state, n = entry
        try:
            outputs = nest.map(np.asarray, outputs)
            new_state = nest.map(np.asarray, new_state)
            batch.set_outputs(
                {
                    "outputs": slice_to(outputs, n, batch_dim),
                    "agent_state": slice_to(new_state, n, batch_dim),
                }
            )
        except Exception as e:  # noqa: BLE001
            log.exception("Inference reply failed; continuing")
            batch.fail(e)

    pending = None
    for batch in inference_batcher:
        try:
            inputs = batch.get_inputs()
            env_outputs, agent_state = inputs["env"], inputs["agent_state"]
            n = len(batch)
            padded = bucket_size(n, buckets)
            env_padded = pad_to(env_outputs, padded, batch_dim)
            state_padded = pad_to(agent_state, padded, batch_dim)
            if lock is not None:
                with lock:
                    outputs, new_state = act_fn(
                        env_padded, state_padded, padded
                    )
            else:
                outputs, new_state = act_fn(env_padded, state_padded, padded)
        except Exception as e:  # noqa: BLE001
            log.exception("Inference batch failed; continuing")
            batch.fail(e)
            if pending is not None:
                flush(pending)
                pending = None
            continue
        # This batch is dispatched (async); NOW reply to the previous one.
        if pending is not None:
            flush(pending)
            pending = None
        if pipelined and inference_batcher.size() > 0:
            pending = (batch, outputs, new_state, n)
        else:
            flush((batch, outputs, new_state, n))
    if pending is not None:  # batcher closed with a reply in flight
        flush(pending)
