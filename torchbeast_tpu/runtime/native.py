"""Native runtime loader + telemetry fold.

`import_native()` returns the `_tbt_core` C extension (C++ BatchingQueue /
DynamicBatcher / ActorPool / EnvServer — actor loops run GIL-free in C++
threads) when built, else None; `available()` tells you which. Drivers
select with `--native_runtime` (polybeast.py). The Python implementations
in queues.py / actor_pool.py remain the semantic reference and the
fallback.

`NativeTelemetryFolder` closes the observability gap (ISSUE 9): the C++
core stamps enqueue->batch->reply per request and counts wire bytes /
env steps / queue intake in-process; each driver monitor tick folds that
interval's aggregates into the process-wide telemetry registry under the
SAME series names the Python runtime writes (wire.bytes_up/down,
actor.env_steps/connects/request_rtt_s, recovery.actor_reconnects/
batch_retries, inference.request_wait_s, learner_queue.items_in/
dequeue_wait_s/batch_size) — so native runs emit a telemetry.jsonl
indistinguishable in schema from Python-runtime runs. Histogram folds
are exact: the C++ side accumulates into the same log-bucket geometry as
telemetry/metrics.py (csrc/queues.h telemetry_bucket_index) and
snapshots reset per interval. Sampled per-request spans (ISSUE 12)
fold the same way: 1-in-256 native computes record their stage stamps
C++-side and land in the tracer as actor.request.* spans, closing the
trace-schema gap for degraded-mode diagnosis.

Build: bash scripts/build_native.sh   (setup.py build_ext --inplace)
"""

import threading
import time
from typing import Optional


_cached = False
_module = None


def import_native() -> Optional[object]:
    global _cached, _module
    if not _cached:
        # beastlint: disable=RACE  idempotent lazy import: two racing threads both import the (interpreter-cached) module and store identical results; each store is GIL-atomic
        _cached = True
        try:
            import _tbt_core

            # beastlint: disable=RACE  same benign double-init as _cached above: both racers store the same module object
            _module = _tbt_core
        except ImportError:
            _module = None
    return _module


def available() -> bool:
    return import_native() is not None


# The extension API generation this tree requires. Bumped when the
# Python side starts DEPENDING on a C++ surface (not merely tolerating
# its absence): 1 = the ISSUE 14 shed protocol (ShedError type,
# admission kwargs on DynamicBatcher, shed counters in telemetry);
# 2 = the ISSUE 16 serving plane (SliceRouter/ReplicaRouter types,
# continuous batching + rolled counter, ActorPool record_policy_lag) —
# an older .so would silently serve central-only, so the default-on
# runtime falls back to Python instead.
REQUIRED_API_VERSION = 2


def gap_reason(core=None) -> Optional[str]:
    """Why the native runtime can NOT be used (None = usable). The
    driver's default-on plumbing logs this and falls back to the
    Python pool — `--native_runtime` behavior stays an explicit,
    observable choice rather than an import-time surprise."""
    if core is None:
        core = import_native()
    if core is None:
        return "_tbt_core is not built (run scripts/build_native.sh)"
    have = getattr(core, "API_VERSION", 0)
    if have < REQUIRED_API_VERSION:
        return (
            f"_tbt_core is stale: API version {have} < required "
            f"{REQUIRED_API_VERSION} (rebuild with "
            "scripts/build_native.sh)"
        )
    return None


class NativeTelemetryFolder:
    """Folds the C++ pool/batcher/queue telemetry into the registry.

    `tick()` runs as a DriverTelemetry tick callback (monitor thread,
    plus the final shutdown write): counter series are credited with
    the delta since the previous tick; histogram series fold the C++
    side's interval snapshot (which resets on read, so min/max are the
    interval's true extremes). The lock makes the shutdown-path tick
    safe against a monitor tick still in flight.
    """

    def __init__(self, registry, pool=None, batcher=None, queue=None,
                 tracer=None, slo_target_s=None, slice_batchers=None,
                 slice_router=None, replica_router=None,
                 replica_batcher=None, fleet=None):
        # ISSUE 17 fleet fold: with a FleetCoordinator attached, the
        # lead re-exports every remote host's heartbeat gauges
        # (inference.slice.<i>.* by construction — parallel.sebulba
        # .slice_gauge_snapshot feeds the remote end) prefixed
        # `host<r>.`, so one telemetry.jsonl shows every slice in the
        # fleet. Works with all native sources None — Python-runtime
        # fleet runs construct this folder for the fleet fold alone.
        self._fleet = fleet
        self._registry = registry
        self._fleet_gauges = {}  # name -> Gauge  # guarded-by: self._lock
        self._pool = pool
        self._batcher = batcher
        self._queue = queue
        self._slo_target_s = slo_target_s
        # ISSUE 16 per-slice fold: native per-slice batchers' admission
        # counters aggregate into the same serving.* series the central
        # fold uses (one audit schema either topology), while their
        # depths land on the per-slice "inference.slice.<i>.depth"
        # gauges — the exact series the Python SebulbaServing
        # gauge_tick publishes, so dashboards cannot tell the runtimes
        # apart. The native SliceRouter's routed counts fold onto
        # "inference.slice.<i>.requests" (the Python SliceRouter's
        # series), the ReplicaRouter's onto serving.replica_requests/
        # serving.central_requests (serving/replica.py's series).
        self._slice_batchers = list(slice_batchers or [])
        self._slice_router = slice_router
        self._replica_router = replica_router
        self._replica_batcher = replica_batcher
        self._g_slice_depth = [
            registry.gauge(f"inference.slice.{i}.depth")
            for i in range(len(self._slice_batchers))
        ]
        self._c_slice_requests = []
        if slice_router is not None:
            self._c_slice_requests = [
                registry.counter(f"inference.slice.{i}.requests")
                for i in range(slice_router.n_slices())
            ]
        if replica_router is not None:
            self._c_replica_requests = registry.counter(
                "serving.replica_requests"
            )
            self._c_central_requests = registry.counter(
                "serving.central_requests"
            )
        # Continuous-batching roll-ins (native only; the Python batcher
        # has no dispatch-window top-up).
        self._c_rolled = registry.counter("serving.rolled")
        # Sampled C++ request spans (ISSUE 12) land in the process
        # tracer as the same actor.request.* stage spans the Python
        # pool's StageTraces emit, so a native run's trace export is
        # schema-identical.
        if tracer is None:
            from torchbeast_tpu import telemetry

            tracer = telemetry.get_tracer()
        self._tracer = tracer
        self._lock = threading.Lock()
        self._prev = {}  # counter name -> last cumulative value  # guarded-by: self._lock
        # Same series names the Python runtime's instruments use.
        self._c_bytes_up = registry.counter("wire.bytes_up")
        self._c_bytes_down = registry.counter("wire.bytes_down")
        self._c_steps = registry.counter("actor.env_steps")
        self._c_connects = registry.counter("actor.connects")
        self._c_reconnects = registry.counter("recovery.actor_reconnects")
        self._c_retries = registry.counter("recovery.batch_retries")
        # shm doorbell-wait counters (ISSUE 10): same series names the
        # Python transport increments directly (transport.py
        # _ring_instruments), so mixed-runtime runs aggregate.
        self._c_ring_waits = registry.counter("ring.doorbell_waits")
        self._c_ring_rechecks = registry.counter("ring.recheck_wakeups")
        self._h_rtt = registry.histogram("actor.request_rtt_s")
        self._h_request_wait = registry.histogram("inference.request_wait_s")
        # Serving-tier fold (ISSUE 14): the C++ batcher gates admission
        # and deadline expiry in-process; its counters land on the SAME
        # serving.* series the Python AdmissionController writes, and
        # the C++ pool's shed_resubmits on the actor-side twin — so the
        # chaos harness audits one schema on either runtime.
        self._c_admitted = registry.counter("serving.admitted")
        self._c_shed = registry.counter("serving.shed")
        self._c_expired = registry.counter("serving.expired")
        self._c_resubmits = registry.counter("serving.resubmitted")
        self._c_slo_breaches = registry.counter("slo.rtt_breaches")
        self._h_queue_delay = registry.histogram("serving.queue_delay_s")
        self._g_delay_p99 = registry.gauge("serving.queue_delay_p99_s")
        self._g_slo_ratio = registry.gauge("serving.slo_ratio")
        self._c_queue_in = registry.counter("learner_queue.items_in")
        self._h_queue_wait = registry.histogram(
            "learner_queue.dequeue_wait_s"
        )
        self._h_queue_batch = registry.histogram("learner_queue.batch_size")

    # beastlint: holds self._lock
    def _inc_delta(self, counter, key: str, value: int) -> None:
        prev = self._prev.get(key, 0)
        if value > prev:
            counter.inc(value - prev)
        self._prev[key] = value

    @staticmethod
    def _fold_hist(histogram, snap: dict) -> None:
        histogram.observe_aggregate(
            snap["buckets"], snap["total"], snap["total_sq"],
            snap["min"], snap["max"],
        )

    # beastlint: holds self._lock
    def _fold_traces(self, batcher) -> None:
        """Drain the batcher's sampled (enqueued, batched, replied)
        stamp triples (csrc/queues.h, 1-in-256 computes like the Python
        pool) into tracer spans. Stamps are steady-clock; the payload's
        "now" rebases them onto the tracer's perf_counter timebase
        (both CLOCK_MONOTONIC on Linux — the offset absorbs any epoch
        difference). Always drained, even with tracing disabled, so
        the C++ buffer never sits full."""
        spans_fn = getattr(batcher, "trace_spans", None)
        if spans_fn is None:  # extension built before ISSUE 12
            return
        payload = spans_fn()
        if not payload["spans"] or not self._tracer.enabled():
            return
        offset = time.perf_counter() - payload["now"]
        for enqueued, batched, replied in payload["spans"]:
            self._tracer.add_complete(
                "actor.request.batch", "actor.request",
                enqueued + offset, batched - enqueued,
            )
            self._tracer.add_complete(
                "actor.request.reply", "actor.request",
                batched + offset, replied - batched,
            )
            self._tracer.add_complete(
                "actor.request", "actor.request",
                enqueued + offset, replied - enqueued,
            )

    # beastlint: holds self._lock
    def _batcher_sources(self):
        """Every native batcher feeding the serving-tier fold, keyed
        uniquely so _inc_delta's per-source cursors never collide."""
        sources = []
        if self._batcher is not None:
            sources.append(("central", self._batcher))
        if self._replica_batcher is not None:
            sources.append(("replica", self._replica_batcher))
        sources.extend(
            (f"slice{i}", b)
            for i, b in enumerate(self._slice_batchers)
        )
        return sources

    # beastlint: holds self._lock
    def _fold_batcher(self, key: str, batcher) -> bool:
        """Fold one native batcher's interval telemetry. Returns True
        when a queue-delay snapshot was folded (the caller refreshes
        the p99/SLO gauges once, after every source folded)."""
        b = batcher.telemetry()
        # batches/rows/batch_size stay with the Python serving
        # loop's own inference.* instruments (inference.py
        # observes them for un-instrumented batchers) — folding
        # them here would double-count.
        self._fold_hist(self._h_request_wait, b["request_wait_s"])
        self._fold_hist(self._h_rtt, b["request_rtt_s"])
        # .get: an extension built before ISSUE 14 reports no
        # admission accounting (and the stale gate keeps such a
        # build off the default path anyway).
        self._inc_delta(
            self._c_admitted, f"{key}_serving_admitted",
            b.get("admitted", 0),
        )
        self._inc_delta(
            self._c_shed, f"{key}_serving_shed", b.get("shed", 0)
        )
        self._inc_delta(
            self._c_expired, f"{key}_serving_expired",
            b.get("expired", 0),
        )
        self._inc_delta(
            self._c_slo_breaches, f"{key}_slo_breaches",
            b.get("slo_breaches", 0),
        )
        self._inc_delta(
            self._c_rolled, f"{key}_serving_rolled",
            b.get("rolled", 0),
        )
        self._fold_traces(batcher)
        delay = b.get("queue_delay_s")
        if delay is None:
            return False
        self._fold_hist(self._h_queue_delay, delay)
        return True

    def tick(self) -> None:
        with self._lock:
            if self._pool is not None:
                p = self._pool.telemetry()
                self._inc_delta(self._c_bytes_up, "bytes_up", p["bytes_up"])
                self._inc_delta(
                    self._c_bytes_down, "bytes_down", p["bytes_down"]
                )
                self._inc_delta(self._c_steps, "env_steps", p["env_steps"])
                self._inc_delta(self._c_connects, "connects", p["connects"])
                self._inc_delta(
                    self._c_reconnects, "reconnects", p["reconnects"]
                )
                # .get from here down: an extension built before ISSUE
                # 10/12 reports no ring counters / batch retries; the
                # fold must not KeyError on it.
                self._inc_delta(
                    self._c_retries, "batch_retries",
                    p.get("batch_retries", 0),
                )
                self._inc_delta(
                    self._c_ring_waits, "ring_doorbell_waits",
                    p.get("ring_doorbell_waits", 0),
                )
                self._inc_delta(
                    self._c_ring_rechecks, "ring_recheck_wakeups",
                    p.get("ring_recheck_wakeups", 0),
                )
                self._inc_delta(
                    self._c_resubmits, "shed_resubmits",
                    p.get("shed_resubmits", 0),
                )
            folded_delay = False
            for key, b_obj in self._batcher_sources():
                folded_delay |= self._fold_batcher(key, b_obj)
            if folded_delay:
                # The p99/SLO gauges the Python AdmissionController
                # refreshes inline are refolded here per tick from
                # the registry's cumulative histogram (which aggregates
                # every batcher source under one serving-tier view).
                p99 = self._h_queue_delay.percentile(0.99)
                self._g_delay_p99.set(p99)
                if self._slo_target_s:
                    self._g_slo_ratio.set(p99 / self._slo_target_s)
            for gauge, b_obj in zip(
                self._g_slice_depth, self._slice_batchers
            ):
                gauge.set(b_obj.size())
            if self._slice_router is not None:
                counts = self._slice_router.telemetry()["requests"]
                for i, count in enumerate(counts):
                    self._inc_delta(
                        self._c_slice_requests[i],
                        f"slice{i}_requests", count,
                    )
            if self._replica_router is not None:
                r = self._replica_router.telemetry()
                self._inc_delta(
                    self._c_replica_requests, "replica_requests",
                    r["replica_requests"],
                )
                self._inc_delta(
                    self._c_central_requests, "central_requests",
                    r["central_requests"],
                )
            if self._queue is not None:
                q = self._queue.telemetry()
                self._inc_delta(self._c_queue_in, "queue_items_in",
                                q["items_in"])
                self._fold_hist(self._h_queue_wait, q["dequeue_wait_s"])
                self._fold_hist(self._h_queue_batch, q["batch_size"])
            if self._fleet is not None:
                for rank, gauges in self._fleet.remote_gauges().items():
                    for name, value in gauges.items():
                        full = f"host{rank}.{name}"
                        gauge = self._fleet_gauges.get(full)
                        if gauge is None:
                            gauge = self._registry.gauge(full)
                            self._fleet_gauges[full] = gauge
                        gauge.set(value)
