"""Native runtime loader.

`import_native()` returns the `_tbt_core` C extension (C++ BatchingQueue /
DynamicBatcher / ActorPool — actor loops run GIL-free in C++ threads) when
built, else None; `available()` tells you which. Drivers select with
`--native_runtime` (polybeast.py). The Python implementations in queues.py /
actor_pool.py remain the semantic reference and the fallback.

Build: bash scripts/build_native.sh   (setup.py build_ext --inplace)
"""

from typing import Optional


_cached = False
_module = None


def import_native() -> Optional[object]:
    global _cached, _module
    if not _cached:
        # beastlint: disable=RACE  idempotent lazy import: two racing threads both import the (interpreter-cached) module and store identical results; each store is GIL-atomic
        _cached = True
        try:
            import _tbt_core

            # beastlint: disable=RACE  same benign double-init as _cached above: both racers store the same module object
            _module = _tbt_core
        except ImportError:
            _module = None
    return _module


def available() -> bool:
    return import_native() is not None
