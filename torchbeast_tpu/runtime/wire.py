"""Wire protocol: framed binary nest-of-arrays messages over sockets.

Plays the role of the reference's proto2 `ArrayNest`/`Step`/`Action` messages
over gRPC bidi streams (/root/reference/src/proto/rpcenv.proto:21-48,
nest_serialize.h:22-69). This image has no C++ gRPC, so the transport is a
deliberately simple length-prefixed framing that is trivial to implement
identically in C++ (csrc/wire.h) and Python — no IDL, no codegen, zero-copy
reads on the receiving side where possible.

Frame:   [u32le payload_length][payload]
Payload (recursive value encoding, little-endian):
  0x01 ARRAY  : u8 dtype_code, u8 ndim, ndim * i64 shape, C-order raw bytes
  0x02 LIST   : u32 count, then count values
  0x03 DICT   : u32 count, then count * (u16 keylen, utf8 key, value)
  0x04 NONE
  0x05 INT    : i64
  0x06 FLOAT  : f64
  0x07 BOOL   : u8
  0x08 STRING : u32 len, utf8 bytes

Arrays are always serialized C-contiguous (the reference had a regression
around non-contiguous numpy arrays, rpcenv.cc:166-170 /
tests/contiguous_arrays_test.py — here np.ascontiguousarray normalizes on
encode, and the property is pinned by tests/test_wire.py).

Zero-copy hot path (ISSUE 3): the legacy `encode()` paid 3-4 full host
copies per message (BytesIO growth + `arr.tobytes()` + frame assembly +
`sendall`'s kernel copy). The scatter-gather path replaces all of that:

- `encode_into(value, SendBuffer)` writes every scalar/structural byte
  into one reusable per-connection bytearray (sized on the fly: scratch
  segments are tracked as offsets, so grow-on-demand never invalidates
  them, and the length header is patched last) and emits a list of
  memoryviews in which large array payloads are referenced *directly
  from the numpy buffer*. `send_message(sock, value, buf=...)` hands
  that list to `socket.sendmsg`, so array bytes go numpy -> kernel with
  zero intermediate copies. The frame bytes on the wire are
  bit-identical to `encode_legacy()` (pinned by tests/test_wire.py
  fuzz).
- `recv_message_sized(sock, buf=RecvBuffer())` reads header and payload
  with `recv_into` into a grow-only per-connection buffer: steady-state
  per-step receives do zero payload-sized allocations (no chunk lists,
  no `b"".join`).

BUFFER-REUSE LIFETIME: with a `RecvBuffer`, decoded nests are zero-copy
views into the buffer, and the *next* `recv_message_sized` on the same
buffer overwrites them. The caller must consume (copy out of) a decoded
nest before receiving the next message — ActorPool copies env outputs
into its rollout storage per step for exactly this reason. Symmetrically,
the memoryviews returned by `encode_into` alias `SendBuffer.scratch` and
the source arrays: send them before the next `encode_into` on the same
buffer and do not mutate the arrays until the send completes.

Frames are bounded by `max_frame_bytes` (default 256 MiB): a corrupt
4-byte header must surface as WireError, not as a multi-GiB allocation
(mirrored in csrc/wire.h's kMaxFrameBytes).
"""

# beastlint: hot-module — the codec runs per message on the acting path.

import io
import socket
import struct
import time
from typing import Any, List, Optional, Tuple

import numpy as np

TAG_ARRAY = 0x01
TAG_LIST = 0x02
TAG_DICT = 0x03
TAG_NONE = 0x04
TAG_INT = 0x05
TAG_FLOAT = 0x06
TAG_BOOL = 0x07
TAG_STRING = 0x08
# Versioned policy snapshot (ISSUE 17, fleet/snapshot_wire.py): the
# fleet control plane's lead->remote publication of bf16-cast policy
# params. A DISTINCT tag (not a convention-keyed dict) so a snapshot
# frame can never be mistaken for actor traffic and the C++ observer
# (csrc/wire.h kTagSnapshot) stays WIRE-PARITY-pinned to it.
TAG_SNAPSHOT = 0x09

# Reject frames whose header demands more than this before allocating
# (csrc/wire.h kMaxFrameBytes must match).
DEFAULT_MAX_FRAME_BYTES = 256 * 1024 * 1024

# Arrays at least this big ride their own sendmsg iovec straight from the
# numpy buffer; smaller ones are cheaper to copy into the scratch segment
# than to pay a separate iovec entry for.
_GATHER_MIN_BYTES = 1024

# Stay under typical IOV_MAX (1024): messages with absurd array counts
# fall back to a single joined send.
_IOV_MAX = 512

# Stable dtype codes shared with the C++ implementation (csrc/array.h).
_DTYPE_CODES = {
    np.dtype(np.uint8): 0,
    np.dtype(np.int8): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.float32): 4,
    np.dtype(np.float64): 5,
    np.dtype(np.bool_): 6,
    np.dtype(np.uint16): 7,
    np.dtype(np.int16): 8,
    np.dtype(np.uint32): 9,
    np.dtype(np.uint64): 10,
    np.dtype(np.float16): 11,
}

# bfloat16 (code 12): TPU-native models emit bf16 outputs; without the
# wire code they had to be upcast host-side before encoding. numpy has no
# native bf16 — ml_dtypes (a jax dependency) provides it; decoding a
# code-12 array without ml_dtypes installed fails as WireError ("Unknown
# dtype code"), the standard teardown path.
try:
    from ml_dtypes import bfloat16 as _bfloat16

    _DTYPE_CODES[np.dtype(_bfloat16)] = 12
except ImportError:  # pragma: no cover - ml_dtypes ships with jax here
    _bfloat16 = None

_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}


class WireError(Exception):
    pass


class PolicySnapshot:
    """The TAG_SNAPSHOT message: one versioned bf16 policy snapshot.

    `params` is the bf16-cast param nest (what travels), `dtypes` the
    original-dtype nest (leaf dtype names, so the receiving host can
    restore exactly what `PolicySnapshotStore.publish` records — the
    restore is bit-exact because every leaf was bf16-cast before
    encoding, see fleet/snapshot_wire.py). The class lives here, next
    to the codec, because both encoders and the decoder must agree on
    it; the cast/restore POLICY lives with the snapshot store.

    Wire layout after the tag byte: u64le version, then the params
    value, then the dtypes value (both in the standard recursive
    encoding).
    """

    __slots__ = ("version", "params", "dtypes")

    def __init__(self, version: int, params: Any, dtypes: Any):
        if version < 0:
            raise WireError(f"snapshot version {version} must be >= 0")
        self.version = int(version)
        self.params = params
        self.dtypes = dtypes

    def __repr__(self):
        return f"PolicySnapshot(version={self.version})"


# wire.encode_s / wire.decode_s histograms (ISSUE 3 measurement): resolved
# lazily so importing wire never drags telemetry in at module-import time
# (and so --no_telemetry runs get the registry's no-op instruments).
_tm_encode = None
_tm_decode = None


def _instruments():
    global _tm_encode, _tm_decode
    if _tm_encode is None:
        from torchbeast_tpu import telemetry

        reg = telemetry.get_registry()
        # beastlint: disable=RACE  benign double-init: the registry's get-or-create is idempotent, so racing encoder threads store the SAME instrument object; each store is GIL-atomic
        _tm_encode = reg.histogram("wire.encode_s")
        # beastlint: disable=RACE  same idempotent lazy-init as _tm_encode above
        _tm_decode = reg.histogram("wire.decode_s")
    return _tm_encode, _tm_decode


def _encode_value(buf: io.BytesIO, value: Any) -> None:
    if value is None:
        buf.write(bytes([TAG_NONE]))
    elif isinstance(value, bool) or isinstance(value, np.bool_):
        buf.write(bytes([TAG_BOOL]))
        buf.write(struct.pack("<B", 1 if value else 0))
    elif isinstance(value, (int, np.integer)) and not isinstance(
        value, np.ndarray
    ):
        buf.write(bytes([TAG_INT]))
        buf.write(struct.pack("<q", int(value)))
    elif isinstance(value, (float, np.floating)):
        buf.write(bytes([TAG_FLOAT]))
        buf.write(struct.pack("<d", float(value)))
    elif isinstance(value, str):
        raw = value.encode("utf-8")
        buf.write(bytes([TAG_STRING]))
        buf.write(struct.pack("<I", len(raw)))
        buf.write(raw)
    elif isinstance(value, np.ndarray):
        # NB: np.ascontiguousarray promotes 0-d to 1-d, so only normalize
        # when actually needed (0-d arrays are always contiguous).
        arr = value
        if not arr.flags["C_CONTIGUOUS"]:
            arr = np.ascontiguousarray(arr)
        code = _DTYPE_CODES.get(arr.dtype)
        if code is None:
            raise WireError(f"Unsupported array dtype {arr.dtype}")
        buf.write(bytes([TAG_ARRAY]))
        buf.write(struct.pack("<BB", code, arr.ndim))
        buf.write(struct.pack(f"<{arr.ndim}q", *arr.shape))
        buf.write(arr.tobytes())
    elif isinstance(value, (list, tuple)):
        buf.write(bytes([TAG_LIST]))
        buf.write(struct.pack("<I", len(value)))
        for v in value:
            _encode_value(buf, v)
    elif isinstance(value, dict):
        buf.write(bytes([TAG_DICT]))
        buf.write(struct.pack("<I", len(value)))
        for k, v in value.items():
            raw = str(k).encode("utf-8")
            buf.write(struct.pack("<H", len(raw)))
            buf.write(raw)
            _encode_value(buf, v)
    elif isinstance(value, PolicySnapshot):
        buf.write(bytes([TAG_SNAPSHOT]))
        buf.write(struct.pack("<Q", value.version))
        _encode_value(buf, value.params)
        _encode_value(buf, value.dtypes)
    else:
        raise WireError(f"Cannot serialize {type(value)!r}")


class SendBuffer:
    """Reusable per-connection scatter-gather encode state: one grow-only
    bytearray holding the frame header plus all scalar/structural bytes.
    Steady state (message sizes stabilized) performs zero allocations
    beyond the returned memoryview objects."""

    __slots__ = ("scratch",)

    def __init__(self, initial_bytes: int = 8192):
        self.scratch = bytearray(max(int(initial_bytes), 64))


class _Encoder:
    """Single-pass scatter-gather writer. Scratch segments are recorded
    as (start, end) OFFSETS — not memoryviews — so mid-encode growth
    (fresh bytearray + content copy) cannot invalidate them; the iovec
    list materializes once, at the end, against the final buffer. The
    frame-length header is patched last (the sizing pass this replaces
    cost as much as the writing pass on small-leaf messages)."""

    __slots__ = ("scratch", "pos", "seg_start", "parts", "gathered")

    def __init__(self, scratch: bytearray):
        self.scratch = scratch
        self.pos = 0
        self.seg_start = 0
        # (start, end) offset pairs into scratch, interleaved (in frame
        # order) with direct array memoryviews.
        self.parts: List[Any] = []
        self.gathered = 0  # total bytes riding direct array views

    def need(self, n: int):
        if self.pos + n > len(self.scratch):
            grown = bytearray(max(self.pos + n, 2 * len(self.scratch)))
            grown[: self.pos] = self.scratch[: self.pos]
            self.scratch = grown

    def flush(self):
        if self.pos > self.seg_start:
            self.parts.append((self.seg_start, self.pos))
            self.seg_start = self.pos

    def gather(self, view: memoryview):
        """Append an out-of-scratch segment (a direct array view)."""
        self.flush()
        self.parts.append(view)
        self.gathered += view.nbytes


def _byte_view(arr: np.ndarray) -> memoryview:
    """Flat byte view of a C-contiguous array. User dtypes (bfloat16)
    don't export the buffer protocol — reinterpret as uint8 (a view, not
    a copy) for those."""
    try:
        return memoryview(arr).cast("B")
    except (ValueError, TypeError, BufferError):
        return memoryview(arr.view(np.uint8).reshape(-1))


def _write_array(enc: _Encoder, value: np.ndarray) -> None:
    arr = value
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    code = _DTYPE_CODES.get(arr.dtype)
    if code is None:
        raise WireError(f"Unsupported array dtype {arr.dtype}")
    ndim = arr.ndim
    nbytes = arr.nbytes
    gathering = nbytes >= _GATHER_MIN_BYTES
    enc.need(3 + 8 * ndim + (0 if gathering else nbytes))
    s = enc.scratch
    pos = enc.pos
    s[pos] = TAG_ARRAY
    s[pos + 1] = code
    s[pos + 2] = ndim
    pos += 3
    if ndim:
        struct.pack_into(f"<{ndim}q", s, pos, *arr.shape)
        pos += 8 * ndim
    if gathering:
        enc.pos = pos
        # memoryview keeps `arr` (and thus any ascontiguousarray
        # temporary) alive until the send consumes the view.
        enc.gather(_byte_view(arr))
    else:
        s[pos : pos + nbytes] = (
            _byte_view(arr) if ndim else arr.tobytes()
        )
        enc.pos = pos + nbytes


def _write_str(enc: _Encoder, value: str) -> None:
    raw = value.encode("utf-8")
    enc.need(5 + len(raw))
    s = enc.scratch
    pos = enc.pos
    s[pos] = TAG_STRING
    struct.pack_into("<I", s, pos + 1, len(raw))
    s[pos + 5 : pos + 5 + len(raw)] = raw
    enc.pos = pos + 5 + len(raw)


def _write_bool(enc: _Encoder, value) -> None:
    enc.need(2)
    s = enc.scratch
    pos = enc.pos
    s[pos] = TAG_BOOL
    s[pos + 1] = 1 if value else 0
    enc.pos = pos + 2


def _write_int(enc: _Encoder, value) -> None:
    enc.need(9)
    pos = enc.pos
    enc.scratch[pos] = TAG_INT
    struct.pack_into("<q", enc.scratch, pos + 1, int(value))
    enc.pos = pos + 9


def _write_float(enc: _Encoder, value) -> None:
    enc.need(9)
    pos = enc.pos
    enc.scratch[pos] = TAG_FLOAT
    struct.pack_into("<d", enc.scratch, pos + 1, float(value))
    enc.pos = pos + 9


def _write_dict(enc: _Encoder, value: dict) -> None:
    enc.need(5)
    s = enc.scratch
    pos = enc.pos
    s[pos] = TAG_DICT
    struct.pack_into("<I", s, pos + 1, len(value))
    enc.pos = pos + 5
    for k, v in value.items():
        raw = str(k).encode("utf-8")
        enc.need(2 + len(raw))
        s = enc.scratch
        pos = enc.pos
        struct.pack_into("<H", s, pos, len(raw))
        s[pos + 2 : pos + 2 + len(raw)] = raw
        enc.pos = pos + 2 + len(raw)
        _write_value(enc, v)


def _write_list(enc: _Encoder, value) -> None:
    enc.need(5)
    pos = enc.pos
    enc.scratch[pos] = TAG_LIST
    struct.pack_into("<I", enc.scratch, pos + 1, len(value))
    enc.pos = pos + 5
    for v in value:
        _write_value(enc, v)


def _write_snapshot(enc: _Encoder, value: "PolicySnapshot") -> None:
    enc.need(9)
    pos = enc.pos
    enc.scratch[pos] = TAG_SNAPSHOT
    struct.pack_into("<Q", enc.scratch, pos + 1, value.version)
    enc.pos = pos + 9
    _write_value(enc, value.params)
    _write_value(enc, value.dtypes)


def _write_value(enc: _Encoder, value: Any) -> None:
    # Exact-type dispatch first (isinstance chains dominated the encode
    # profile); numpy scalars and subclasses fall through to an
    # isinstance chain ordered exactly like the legacy _encode_value so
    # semantics can't drift (pinned by test_encode_matches_legacy_fuzz).
    t = type(value)
    if t is np.ndarray:
        _write_array(enc, value)
    elif t is dict:
        _write_dict(enc, value)
    elif t is str:
        _write_str(enc, value)
    elif t is bool:
        _write_bool(enc, value)
    elif t is int:
        _write_int(enc, value)
    elif t is float:
        _write_float(enc, value)
    elif value is None:
        enc.need(1)
        enc.scratch[enc.pos] = TAG_NONE
        enc.pos += 1
    elif t is list or t is tuple:
        _write_list(enc, value)
    elif isinstance(value, (bool, np.bool_)):
        _write_bool(enc, value)
    elif isinstance(value, (int, np.integer)) and not isinstance(
        value, np.ndarray
    ):
        _write_int(enc, value)
    elif isinstance(value, (float, np.floating)):
        _write_float(enc, value)
    elif isinstance(value, str):
        _write_str(enc, value)
    elif isinstance(value, np.ndarray):
        _write_array(enc, value)
    elif isinstance(value, (list, tuple)):
        _write_list(enc, value)
    elif isinstance(value, dict):
        _write_dict(enc, value)
    elif isinstance(value, PolicySnapshot):
        _write_snapshot(enc, value)
    else:
        raise WireError(f"Cannot serialize {type(value)!r}")


def encode_into(value: Any, buf: SendBuffer) -> Tuple[List[memoryview], int]:
    """Scatter-gather encode: (iovec list, framed byte count). The first
    view starts with the u32 frame header; concatenated, the views are
    bit-identical to `encode_legacy(value)`. Single pass: scalar bytes
    land in buf.scratch (grow-only; growth allocates fresh so previous
    messages' outstanding views stay alive), large array payloads become
    direct views of the numpy buffers, and the length header is patched
    at the end. See the module docstring for lifetime rules."""
    enc = _Encoder(buf.scratch)
    enc.pos = 4  # leave room for the u32 frame header
    _write_value(enc, value)
    enc.flush()
    buf.scratch = enc.scratch  # may have grown
    payload_len = (enc.pos - 4) + enc.gathered
    if payload_len > 0xFFFFFFFF:
        raise WireError(f"Message too large for u32 framing: {payload_len}")
    struct.pack_into("<I", enc.scratch, 0, payload_len)
    mv = memoryview(enc.scratch)
    views = [
        mv[part[0] : part[1]] if type(part) is tuple else part
        for part in enc.parts
    ]
    return views, 4 + payload_len


def encode(value: Any) -> bytes:
    """Value -> framed message bytes (length prefix included)."""
    views, _ = encode_into(value, SendBuffer(initial_bytes=256))
    return b"".join(views)


def encode_legacy(value: Any) -> bytes:
    """The original copy-heavy encoder (BytesIO growth + tobytes).
    Kept as the format pin — tests assert encode()/encode_into() match it
    byte-for-byte — and as the baseline leg of benchmarks/wire_bench.py."""
    buf = io.BytesIO()
    _encode_value(buf, value)
    payload = buf.getvalue()
    return struct.pack("<I", len(payload)) + payload


def _decode_value(view: memoryview, offset: int):
    tag = view[offset]
    offset += 1
    if tag == TAG_NONE:
        return None, offset
    if tag == TAG_BOOL:
        return bool(view[offset]), offset + 1
    if tag == TAG_INT:
        (v,) = struct.unpack_from("<q", view, offset)
        return v, offset + 8
    if tag == TAG_FLOAT:
        (v,) = struct.unpack_from("<d", view, offset)
        return v, offset + 8
    if tag == TAG_STRING:
        (n,) = struct.unpack_from("<I", view, offset)
        offset += 4
        return bytes(view[offset : offset + n]).decode("utf-8"), offset + n
    if tag == TAG_ARRAY:
        code, ndim = struct.unpack_from("<BB", view, offset)
        offset += 2
        shape = struct.unpack_from(f"<{ndim}q", view, offset)
        offset += 8 * ndim
        dtype = _CODE_DTYPES.get(code)
        if dtype is None:
            raise WireError(f"Unknown dtype code {code}")
        # Untrusted dims off the socket: reject negatives, and bound the
        # byte count by the remaining payload before multiplying so a
        # wrapping product can't pass (mirrors csrc/wire.h). Any zero dim
        # makes the whole array empty regardless of the other dims.
        if any(d < 0 for d in shape):
            raise WireError(f"Negative array dim in {shape}")
        if 0 in shape:
            nbytes = 0
        else:
            remaining = len(view) - offset
            nbytes = dtype.itemsize
            for d in shape:
                if nbytes > remaining // d:
                    raise WireError("Array size exceeds payload")
                nbytes *= d
        arr = np.frombuffer(
            view[offset : offset + nbytes], dtype=dtype
        ).reshape(shape)
        return arr, offset + nbytes
    if tag == TAG_LIST:
        (n,) = struct.unpack_from("<I", view, offset)
        offset += 4
        out = []
        for _ in range(n):
            v, offset = _decode_value(view, offset)
            out.append(v)
        return out, offset
    if tag == TAG_DICT:
        (n,) = struct.unpack_from("<I", view, offset)
        offset += 4
        out = {}
        for _ in range(n):
            (klen,) = struct.unpack_from("<H", view, offset)
            offset += 2
            key = bytes(view[offset : offset + klen]).decode("utf-8")
            offset += klen
            v, offset = _decode_value(view, offset)
            out[key] = v
        return out, offset
    if tag == TAG_SNAPSHOT:
        (version,) = struct.unpack_from("<Q", view, offset)
        offset += 8
        params, offset = _decode_value(view, offset)
        dtypes, offset = _decode_value(view, offset)
        # Array leaves are zero-copy views like every decoded nest:
        # the receiving host must consume (copy/publish) the snapshot
        # before the next recv on the same buffer.
        return PolicySnapshot(version, params, dtypes), offset
    raise WireError(f"Unknown tag {tag:#x}")


def decode(payload) -> Any:
    """Payload bytes (no length prefix) -> value. Arrays are zero-copy
    views into `payload` (read-only). Accepts bytes or a memoryview (the
    RecvBuffer path passes a read-only view of the reusable buffer).

    Every malformed-frame failure surfaces as WireError: the actor/server
    recovery paths catch WireError to tear down one connection, so a
    corrupt frame must never escape as struct.error/ValueError and kill
    the whole thread instead.
    """
    try:
        value, offset = _decode_value(memoryview(payload), 0)
    except WireError:
        raise
    except (struct.error, ValueError, IndexError, UnicodeDecodeError,
            OverflowError) as e:
        raise WireError(f"Malformed frame: {e}") from e
    if offset != len(payload):
        raise WireError(
            f"Trailing garbage: decoded {offset} of {len(payload)} bytes"
        )
    return value


def _sendmsg_all(sock: socket.socket, views: List[memoryview],
                 total: int) -> None:
    """sendmsg the full iovec list, looping on partial sends. A single
    view goes through sendall directly (same zero-copy, and plain send
    is measurably cheaper than sendmsg under syscall emulation)."""
    if len(views) == 1:
        sock.sendall(views[0])
        return
    if len(views) > _IOV_MAX:
        sock.sendall(b"".join(views))
        return
    sent = sock.sendmsg(views)
    while sent < total:
        total -= sent
        rest: List[memoryview] = []
        for v in views:
            if not rest:
                n = len(v)
                if sent >= n:
                    sent -= n
                    continue
                rest.append(v[sent:] if sent else v)
                sent = 0
            else:
                rest.append(v)
        views = rest
        sent = sock.sendmsg(views)


def _timed_encode_into(value: Any, buf: SendBuffer):
    """encode_into + the wire.encode_s histogram (shared by the socket
    and shm transports so the instrumentation can't diverge)."""
    enc_h, _ = _instruments()
    t0 = time.perf_counter()
    out = encode_into(value, buf)
    enc_h.observe(time.perf_counter() - t0)
    return out


def _timed_decode(payload) -> Any:
    """decode + the wire.decode_s histogram (shared across transports)."""
    _, dec_h = _instruments()
    t0 = time.perf_counter()
    value = decode(payload)
    dec_h.observe(time.perf_counter() - t0)
    return value


def _frame_limit(max_frame_bytes: Optional[int]) -> int:
    return (
        DEFAULT_MAX_FRAME_BYTES if max_frame_bytes is None
        else int(max_frame_bytes)
    )


def send_message(sock: socket.socket, value: Any,
                 buf: Optional[SendBuffer] = None) -> int:
    """Send one framed message; returns the framed byte count (header
    included) so callers can feed wire-byte telemetry counters.

    With a per-connection SendBuffer, large array payloads are handed to
    socket.sendmsg directly from the numpy buffers (zero host copies);
    without one, falls back to a joined sendall."""
    if buf is None:
        enc_h, _ = _instruments()
        t0 = time.perf_counter()
        frame = encode(value)
        enc_h.observe(time.perf_counter() - t0)
        sock.sendall(frame)
        return len(frame)
    views, total = _timed_encode_into(value, buf)
    _sendmsg_all(sock, views, total)
    return total


def recv_message(sock: socket.socket) -> Optional[Any]:
    """Read one framed message; None on clean EOF at a frame boundary."""
    return recv_message_sized(sock)[0]


def recv_message_sized(sock: socket.socket, buf: "Optional[RecvBuffer]" = None,
                       max_frame_bytes: Optional[int] = None):
    """(value, framed byte count) — (None, 0) on clean EOF. The sized
    variant exists for per-connection byte accounting (telemetry
    wire.bytes_* counters) without re-encoding the message.

    With a per-connection RecvBuffer, header and payload are read via
    recv_into into the reusable buffer (zero steady-state allocations);
    the decoded nest is a view into it and must be consumed before the
    next recv on the same buffer. Frames longer than max_frame_bytes
    (default DEFAULT_MAX_FRAME_BYTES) raise WireError before any payload
    allocation."""
    limit = _frame_limit(max_frame_bytes)
    if buf is None:
        header = _recv_exact(sock, 4)
        if header is None:
            return None, 0
        (length,) = struct.unpack("<I", header)
        if length > limit:
            raise WireError(
                f"Frame length {length} exceeds max_frame_bytes {limit}"
            )
        payload = _recv_exact(sock, length)
        if payload is None:
            raise WireError("Connection closed mid-frame")
        return _timed_decode(payload), 4 + length
    mv = buf.view(4)
    if not _recv_into_exact(sock, mv, 4, eof_ok=True):
        return None, 0
    (length,) = struct.unpack_from("<I", mv, 0)
    if length > limit:
        raise WireError(
            f"Frame length {length} exceeds max_frame_bytes {limit}"
        )
    mv = buf.view(length)  # may swap buffers; the header is already parsed
    _recv_into_exact(sock, mv, length, eof_ok=False)
    return _timed_decode(mv[:length].toreadonly()), 4 + length


class RecvBuffer:
    """Grow-only per-connection receive buffer for recv_message_sized.

    Steady state does zero allocations: the bytearray grows to the
    largest frame seen and is reused for every subsequent receive.
    LIFETIME: a nest decoded from this buffer aliases it — consume or
    copy it before the next recv into the same buffer (growth allocates
    a fresh bytearray, so views from the message that *caused* growth
    stay valid; same-size successors overwrite)."""

    __slots__ = ("_buf", "_mv")

    def __init__(self, initial_bytes: int = 65536):
        self._buf = bytearray(max(int(initial_bytes), 4096))
        self._mv = memoryview(self._buf)

    @property
    def capacity(self) -> int:
        return len(self._buf)

    def view(self, n: int) -> memoryview:
        """A writable view of at least n bytes, growing if needed."""
        if len(self._buf) < n:
            self._mv.release()
            # Fresh allocation, not resize: decoded views from previous
            # frames keep the old bytearray alive independently.
            self._buf = bytearray(max(n, 2 * len(self._buf)))
            self._mv = memoryview(self._buf)
        return self._mv


def _recv_into_exact(sock: socket.socket, mv: memoryview, n: int,
                     eof_ok: bool) -> bool:
    """Fill mv[:n] from the socket. False on clean EOF before any byte
    (only when eof_ok); WireError on EOF mid-read."""
    got = 0
    while got < n:
        r = sock.recv_into(mv[got:n])
        if r == 0:
            if got == 0 and eof_ok:
                return False
            raise WireError("Connection closed mid-frame")
        got += r
    return True


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes. None on clean EOF before any byte; WireError
    on EOF mid-read."""
    chunks = []
    remaining = n
    while remaining:
        chunk = sock.recv(remaining)
        if not chunk:
            if remaining == n:
                return None
            raise WireError("Connection closed mid-frame")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)
