"""Wire protocol: framed binary nest-of-arrays messages over sockets.

Plays the role of the reference's proto2 `ArrayNest`/`Step`/`Action` messages
over gRPC bidi streams (/root/reference/src/proto/rpcenv.proto:21-48,
nest_serialize.h:22-69). This image has no C++ gRPC, so the transport is a
deliberately simple length-prefixed framing that is trivial to implement
identically in C++ (csrc/wire.h) and Python — no IDL, no codegen, zero-copy
reads on the receiving side where possible.

Frame:   [u32le payload_length][payload]
Payload (recursive value encoding, little-endian):
  0x01 ARRAY  : u8 dtype_code, u8 ndim, ndim * i64 shape, C-order raw bytes
  0x02 LIST   : u32 count, then count values
  0x03 DICT   : u32 count, then count * (u16 keylen, utf8 key, value)
  0x04 NONE
  0x05 INT    : i64
  0x06 FLOAT  : f64
  0x07 BOOL   : u8
  0x08 STRING : u32 len, utf8 bytes

Arrays are always serialized C-contiguous (the reference had a regression
around non-contiguous numpy arrays, rpcenv.cc:166-170 /
tests/contiguous_arrays_test.py — here np.ascontiguousarray normalizes on
encode, and the property is pinned by tests/test_wire.py).
"""

import io
import socket
import struct
from typing import Any, Optional

import numpy as np

TAG_ARRAY = 0x01
TAG_LIST = 0x02
TAG_DICT = 0x03
TAG_NONE = 0x04
TAG_INT = 0x05
TAG_FLOAT = 0x06
TAG_BOOL = 0x07
TAG_STRING = 0x08

# Stable dtype codes shared with the C++ implementation.
_DTYPE_CODES = {
    np.dtype(np.uint8): 0,
    np.dtype(np.int8): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.float32): 4,
    np.dtype(np.float64): 5,
    np.dtype(np.bool_): 6,
    np.dtype(np.uint16): 7,
    np.dtype(np.int16): 8,
    np.dtype(np.uint32): 9,
    np.dtype(np.uint64): 10,
    np.dtype(np.float16): 11,
}
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}


class WireError(Exception):
    pass


def _encode_value(buf: io.BytesIO, value: Any) -> None:
    if value is None:
        buf.write(bytes([TAG_NONE]))
    elif isinstance(value, bool) or isinstance(value, np.bool_):
        buf.write(bytes([TAG_BOOL]))
        buf.write(struct.pack("<B", 1 if value else 0))
    elif isinstance(value, (int, np.integer)) and not isinstance(
        value, np.ndarray
    ):
        buf.write(bytes([TAG_INT]))
        buf.write(struct.pack("<q", int(value)))
    elif isinstance(value, (float, np.floating)):
        buf.write(bytes([TAG_FLOAT]))
        buf.write(struct.pack("<d", float(value)))
    elif isinstance(value, str):
        raw = value.encode("utf-8")
        buf.write(bytes([TAG_STRING]))
        buf.write(struct.pack("<I", len(raw)))
        buf.write(raw)
    elif isinstance(value, np.ndarray):
        # NB: np.ascontiguousarray promotes 0-d to 1-d, so only normalize
        # when actually needed (0-d arrays are always contiguous).
        arr = value
        if not arr.flags["C_CONTIGUOUS"]:
            arr = np.ascontiguousarray(arr)
        code = _DTYPE_CODES.get(arr.dtype)
        if code is None:
            raise WireError(f"Unsupported array dtype {arr.dtype}")
        buf.write(bytes([TAG_ARRAY]))
        buf.write(struct.pack("<BB", code, arr.ndim))
        buf.write(struct.pack(f"<{arr.ndim}q", *arr.shape))
        buf.write(arr.tobytes())
    elif isinstance(value, (list, tuple)):
        buf.write(bytes([TAG_LIST]))
        buf.write(struct.pack("<I", len(value)))
        for v in value:
            _encode_value(buf, v)
    elif isinstance(value, dict):
        buf.write(bytes([TAG_DICT]))
        buf.write(struct.pack("<I", len(value)))
        for k, v in value.items():
            raw = str(k).encode("utf-8")
            buf.write(struct.pack("<H", len(raw)))
            buf.write(raw)
            _encode_value(buf, v)
    else:
        raise WireError(f"Cannot serialize {type(value)!r}")


def _decode_value(view: memoryview, offset: int):
    tag = view[offset]
    offset += 1
    if tag == TAG_NONE:
        return None, offset
    if tag == TAG_BOOL:
        return bool(view[offset]), offset + 1
    if tag == TAG_INT:
        (v,) = struct.unpack_from("<q", view, offset)
        return v, offset + 8
    if tag == TAG_FLOAT:
        (v,) = struct.unpack_from("<d", view, offset)
        return v, offset + 8
    if tag == TAG_STRING:
        (n,) = struct.unpack_from("<I", view, offset)
        offset += 4
        return bytes(view[offset : offset + n]).decode("utf-8"), offset + n
    if tag == TAG_ARRAY:
        code, ndim = struct.unpack_from("<BB", view, offset)
        offset += 2
        shape = struct.unpack_from(f"<{ndim}q", view, offset)
        offset += 8 * ndim
        dtype = _CODE_DTYPES.get(code)
        if dtype is None:
            raise WireError(f"Unknown dtype code {code}")
        # Untrusted dims off the socket: reject negatives, and bound the
        # byte count by the remaining payload before multiplying so a
        # wrapping product can't pass (mirrors csrc/wire.h). Any zero dim
        # makes the whole array empty regardless of the other dims.
        if any(d < 0 for d in shape):
            raise WireError(f"Negative array dim in {shape}")
        if 0 in shape:
            nbytes = 0
        else:
            remaining = len(view) - offset
            nbytes = dtype.itemsize
            for d in shape:
                if nbytes > remaining // d:
                    raise WireError("Array size exceeds payload")
                nbytes *= d
        arr = np.frombuffer(
            view[offset : offset + nbytes], dtype=dtype
        ).reshape(shape)
        return arr, offset + nbytes
    if tag == TAG_LIST:
        (n,) = struct.unpack_from("<I", view, offset)
        offset += 4
        out = []
        for _ in range(n):
            v, offset = _decode_value(view, offset)
            out.append(v)
        return out, offset
    if tag == TAG_DICT:
        (n,) = struct.unpack_from("<I", view, offset)
        offset += 4
        out = {}
        for _ in range(n):
            (klen,) = struct.unpack_from("<H", view, offset)
            offset += 2
            key = bytes(view[offset : offset + klen]).decode("utf-8")
            offset += klen
            v, offset = _decode_value(view, offset)
            out[key] = v
        return out, offset
    raise WireError(f"Unknown tag {tag:#x}")


def encode(value: Any) -> bytes:
    """Value -> framed message bytes (length prefix included)."""
    buf = io.BytesIO()
    _encode_value(buf, value)
    payload = buf.getvalue()
    return struct.pack("<I", len(payload)) + payload


def decode(payload: bytes) -> Any:
    """Payload bytes (no length prefix) -> value. Arrays are zero-copy
    views into `payload` (read-only).

    Every malformed-frame failure surfaces as WireError: the actor/server
    recovery paths catch WireError to tear down one connection, so a
    corrupt frame must never escape as struct.error/ValueError and kill
    the whole thread instead.
    """
    try:
        value, offset = _decode_value(memoryview(payload), 0)
    except WireError:
        raise
    except (struct.error, ValueError, IndexError, UnicodeDecodeError,
            OverflowError) as e:
        raise WireError(f"Malformed frame: {e}") from e
    if offset != len(payload):
        raise WireError(
            f"Trailing garbage: decoded {offset} of {len(payload)} bytes"
        )
    return value


def send_message(sock: socket.socket, value: Any) -> int:
    """Send one framed message; returns the framed byte count (header
    included) so callers can feed wire-byte telemetry counters."""
    frame = encode(value)
    sock.sendall(frame)
    return len(frame)


def recv_message(sock: socket.socket) -> Optional[Any]:
    """Read one framed message; None on clean EOF at a frame boundary."""
    return recv_message_sized(sock)[0]


def recv_message_sized(sock: socket.socket):
    """(value, framed byte count) — (None, 0) on clean EOF. The sized
    variant exists for per-connection byte accounting (telemetry
    wire.bytes_* counters) without re-encoding the message."""
    header = _recv_exact(sock, 4)
    if header is None:
        return None, 0
    (length,) = struct.unpack("<I", header)
    payload = _recv_exact(sock, length)
    if payload is None:
        raise WireError("Connection closed mid-frame")
    return decode(payload), 4 + length


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes. None on clean EOF before any byte; WireError
    on EOF mid-read."""
    chunks = []
    remaining = n
    while remaining:
        chunk = sock.recv(remaining)
        if not chunk:
            if remaining == n:
                return None
            raise WireError("Connection closed mid-frame")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)
