"""Runtime error types shared across jax-free and jax-bound modules.

`StateTablePoisonedError` is raised by the (jax-heavy) DeviceStateTable
but must be CAUGHT by the actor pool and the inference supervisor —
both importable without jax. Defining it here keeps the catch sites
free of a module-level jax import; `runtime.state_table` re-exports it
as the canonical public name.
"""


class StateTablePoisonedError(RuntimeError):
    """A table-mutating dispatch failed after its buffer was donated:
    the table may be consumed and must not serve another request. The
    inference supervisor (resilience/supervisor.py) catches exactly
    this type to rebuild the table and restart the serving thread, and
    the actor pool treats it as a budgeted rollout retry (the rebuild
    is in flight); anything else that escapes a serving loop is a real
    bug and stays fatal."""


class ShedError(RuntimeError):
    """The typed shed reply (ISSUE 14): the admission gate refused this
    inference request — either at enqueue (bounded queue depth, the
    serving tier is over capacity) or at dequeue (the request sat in
    the queue past its --request_deadline_ms budget and serving it
    would only return an answer nobody can use in time).

    A shed is FLOW CONTROL, never a failure: the actor pool catches
    exactly this type in its request path and re-submits the SAME env
    step after a jittered backoff, so a shed can never retire an actor
    or lose a rollout (the C++ pool carries the same contract in
    csrc/actor_pool.h; `_tbt_core.ShedError` subclasses this class).
    `expired` distinguishes the dequeue-side deadline expiry from the
    enqueue-side depth rejection."""

    def __init__(self, message: str, expired: bool = False):
        super().__init__(message)
        self.expired = expired
