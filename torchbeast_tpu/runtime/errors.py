"""Runtime error types shared across jax-free and jax-bound modules.

`StateTablePoisonedError` is raised by the (jax-heavy) DeviceStateTable
but must be CAUGHT by the actor pool and the inference supervisor —
both importable without jax. Defining it here keeps the catch sites
free of a module-level jax import; `runtime.state_table` re-exports it
as the canonical public name.
"""


class StateTablePoisonedError(RuntimeError):
    """A table-mutating dispatch failed after its buffer was donated:
    the table may be consumed and must not serve another request. The
    inference supervisor (resilience/supervisor.py) catches exactly
    this type to rebuild the table and restart the serving thread, and
    the actor pool treats it as a budgeted rollout retry (the rebuild
    is in flight); anything else that escapes a serving loop is a real
    bug and stays fatal."""
