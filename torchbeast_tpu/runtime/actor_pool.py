"""Actor pool: N concurrent loops streaming env steps through dynamic
inference batching into the learner queue.

The reference's C++ `ActorPool` (/root/reference/src/cc/actorpool.cc:342-564)
re-designed for the framed-socket transport: each actor loop connects to an
env-server address, reads the initial Step, and then repeats
  compute(env_outputs, agent_state) -> action
  send(action) -> recv(next Step)
accumulating unroll_length+1 steps per rollout with the same invariants as
the sync collector (rollout.py): overlap-by-one (the last step of rollout k
is slot 0 of rollout k+1, actorpool.cc:414,443), agent-output pairing, and
agent-state carry with `initial_agent_state` captured at rollout entry
(actorpool.cc:449).

Rollouts are enqueued as (rollout_nest, initial_agent_state) onto the
learner BatchingQueue batched along time dim 0 with a [T+1, 1, ...] layout,
so the queue's batch_dim=1 concatenation yields [T+1, B, ...] learner
batches (reference actorpool.cc:443-447, polybeast_learner.py:306).

Threads instead of std::async tasks: the loops spend their time blocked in
socket IO and in compute() (both release the GIL); the C++ pool in csrc/
takes over when Python-thread overhead shows up in profiles.
"""

import logging
import threading
import time
from typing import Any, Callable, List, Optional

import numpy as np

from torchbeast_tpu import nest
from torchbeast_tpu import telemetry
from torchbeast_tpu.resilience.backoff import Backoff
from torchbeast_tpu.runtime import transport as transport_lib
from torchbeast_tpu.runtime import wire
from torchbeast_tpu.runtime.errors import ShedError, StateTablePoisonedError
from torchbeast_tpu.runtime.queues import (
    AsyncError,
    BatchingQueue,
    ClosedBatchingQueue,
    DynamicBatcher,
)

log = logging.getLogger(__name__)

_ENV_KEYS = (
    "frame", "reward", "done", "episode_step", "episode_return",
    "last_action",
)


class ActorPool:
    def __init__(
        self,
        unroll_length: int,
        learner_queue: BatchingQueue,
        inference_batcher: DynamicBatcher,
        env_server_addresses: List[str],
        initial_agent_state: Any,
        connect_timeout_s: float = 600,
        max_reconnects: int = 3,
        state_table=None,
        max_frame_bytes: Optional[int] = None,
        backoff_factory: Optional[Callable[[], Backoff]] = None,
        transport_wrap: Optional[Callable] = None,
        shed_backoff_factory: Optional[Callable[[], Backoff]] = None,
        slo_target_s: Optional[float] = None,
        record_policy_lag: bool = False,
    ):
        self._unroll_length = unroll_length
        self._learner_queue = learner_queue
        self._inference_batcher = inference_batcher
        self._addresses = list(env_server_addresses)
        self._initial_agent_state = initial_agent_state
        self._connect_timeout_s = connect_timeout_s
        self._max_frame_bytes = max_frame_bytes
        # Device-resident agent state (runtime/state_table.py): actor i
        # owns table slot i; requests carry {"slot", "advance"} instead
        # of agent_state, replies carry outputs only, and the rollout-
        # boundary initial_agent_state comes from a once-per-unroll
        # read_slot fetch instead of riding every reply.
        self._state_table = state_table
        if state_table is not None and state_table.num_slots < len(
            self._addresses
        ):
            raise ValueError(
                f"state table has {state_table.num_slots} slots for "
                f"{len(self._addresses)} actors"
            )
        # Elastic actors (beyond the reference's fail-fast): on a TRANSPORT
        # failure (env-server death / stream cut) or a failed inference
        # batch (a recovering serving thread), an actor may retry up to
        # max_reconnects times with a fresh env + reset agent state
        # (the partial rollout is discarded; learner batches stay valid).
        # Retries go through jittered exponential backoff — a dead
        # server must not be re-dialed in a tight loop, and a mass
        # server restart must not thundering-herd the fresh listener.
        # Deterministic env errors (error frames) remain fatal.
        self._max_reconnects = max_reconnects
        self._backoff_factory = backoff_factory or (
            lambda: Backoff(base_s=0.1, cap_s=2.0)
        )
        # Chaos hook (resilience/chaos.py): wraps every fresh transport
        # so the fault plan can sever/delay/corrupt it mid-stream.
        self._transport_wrap = transport_wrap
        # Shed handling (ISSUE 14): a ShedError from compute() is FLOW
        # CONTROL, not a failure — the SAME env step is re-submitted
        # after a jittered backoff, outside the reconnect budget, so a
        # shed can never retire an actor or lose a rollout. The backoff
        # starts smaller than the reconnect one (overload drains in
        # batches, not in server-restart time) and resets per request.
        self._shed_backoff_factory = shed_backoff_factory or (
            lambda: Backoff(base_s=0.05, cap_s=1.0)
        )
        # Per-connection SLO (ISSUE 14 satellite): RTTs above the
        # target count as breaches; the driver exports {target, p99,
        # breaches} as the `slo` block on every telemetry line — the
        # same number the shed gate's deadline uses.
        self._slo_target_s = slo_target_s
        # Replica serving (serving/replica.py): replies served from a
        # snapshot carry a policy_lag leaf; central-path replies don't.
        # Normalizing the missing leaf to 0 keeps rollouts that mix
        # both paths structurally uniform for the learner queue.
        self._record_policy_lag = record_policy_lag
        self._count = 0  # guarded-by: self._count_lock
        self._reconnects = 0  # guarded-by: self._count_lock
        self._dead = 0  # guarded-by: self._count_lock
        self._count_lock = threading.Lock()
        # Appended by N actor threads, read by the pool runner and the
        # driver monitor (RACE burn-down, ISSUE 7).
        self._errors: List[BaseException] = []  # guarded-by: self._count_lock
        # Per-connection wire accounting + request RTT (ISSUE 2).
        # "up" = env-server -> this process (observations rising toward
        # the learner), "down" = actions back out — the same direction
        # convention as polybeast's per-step acting-path gauges.
        reg = telemetry.get_registry()
        self._tm_bytes_up = reg.counter("wire.bytes_up")
        self._tm_bytes_down = reg.counter("wire.bytes_down")
        self._tm_rtt = reg.histogram("actor.request_rtt_s")
        self._tm_steps = reg.counter("actor.env_steps")
        self._tm_connects = reg.counter("actor.connects")
        # Recovery accounting (ISSUE 6): the chaos harness asserts these
        # against the injected fault counts, so each counter covers ONE
        # failure class — transport failures (reconnects) and failed
        # inference batches (rollout retries) never share a series.
        self._tm_reconnects = reg.counter("recovery.actor_reconnects")
        self._tm_retries = reg.counter("recovery.batch_retries")
        # Shed accounting twin (serving/admission.py): incremented once
        # per ShedError received, so serving.resubmitted ==
        # serving.shed + serving.expired holds exactly — the invariant
        # chaos_run asserts to prove a shed is never a lost rollout.
        self._tm_resubmits = reg.counter("serving.resubmitted")
        self._tm_slo_breaches = reg.counter("slo.rtt_breaches")
        self._tracer = telemetry.get_tracer()
        # Sampled per-request pipeline traces: one in _TRACE_EVERY
        # computes rides a StageTrace through the batcher (enqueue ->
        # batch -> reply), bounding trace overhead on the hot path.
        self._trace_tick = 0
        # The C++ batcher's compute() has no trace parameter; only the
        # Python DynamicBatcher threads StageTraces through.
        self._traceable = isinstance(inference_batcher, DynamicBatcher)

    _TRACE_EVERY = 256

    def count(self) -> int:
        """Total env steps taken (reference actorpool.cc:478,557)."""
        with self._count_lock:
            return self._count

    @property
    def errors(self) -> List[BaseException]:
        with self._count_lock:
            return list(self._errors)

    @property
    def reconnects(self) -> int:
        """COMPLETED recoveries (the stream re-established AND
        delivering again), not granted retry attempts — a recovery
        that needs several dials (a stale socket file, a mid-respawn
        handshake) counts ONCE, which is what lets chaos_run assert
        reconnects == injected faults exactly on both runtimes
        (ISSUE 12; the C++ pool shares this contract)."""
        with self._count_lock:
            return self._reconnects

    def reconnect_count(self) -> int:
        """Method form matching the native pool's API."""
        return self.reconnects

    def live_actors(self) -> int:
        """Actor loops still running. The driver's health machine runs
        DEGRADED while this stays >= --min_live_actors and halts (clean
        checkpoint-and-exit) below it."""
        with self._count_lock:
            return len(self._addresses) - self._dead

    def run(self):
        """Run one loop per address; blocks until all exit. First error is
        re-raised (reference surfaces only the first future's exception,
        actorpool.cc:470-475)."""
        threads = [
            threading.Thread(
                target=self._guarded_loop, args=(i, addr), daemon=True
            )
            for i, addr in enumerate(self._addresses)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        with self._count_lock:
            errors = list(self._errors)
        if errors:
            raise errors[0]

    def _guarded_loop(self, index: int, address: str):
        try:
            self._recovering_loop(index, address)
        finally:
            # Any exit — clean shutdown or a burned budget — retires
            # this actor; live_actors() feeds the health machine.
            with self._count_lock:
                self._dead += 1

    def _shutting_down(self) -> bool:
        return (
            self._inference_batcher.is_closed()
            or self._learner_queue.is_closed()
        )

    def _recovering_loop(self, index: int, address: str):
        failures = 0  # transport failures + batch retries, refillable
        backoff = self._backoff_factory()
        progress = [0]  # this actor's env steps (across reconnects)
        # A granted transport retry is COUNTED only once the new stream
        # delivers (the initial step lands, _loop clears the flag) —
        # attempts that die before streaming are budget, not
        # recoveries (see the `reconnects` property contract).
        reconnect_pending = [False]
        while True:
            steps_at_connect = progress[0]
            try:
                self._loop(index, address, progress, reconnect_pending)
                return
            except ClosedBatchingQueue:
                return  # clean shutdown (reference actorpool.cc:452-459)
            except (AsyncError, ShedError, StateTablePoisonedError) as e:
                # ShedError only escapes _request's in-place retry when
                # the pipeline is already closing (re-raised there);
                # the _shutting_down() check below turns it into the
                # clean exit it is.
                # A broken inference promise mid-training — or a DIRECT
                # table call (the unroll-boundary read_slot, the
                # connect-time reset) landing inside the poison-to-
                # rebuild window. During shutdown that's expected;
                # otherwise the failure may come from a RECOVERING
                # serving thread (state-table rebuild) — discard the
                # partial rollout and retry the stream under the same
                # budget/backoff as a reconnect, instead of retiring
                # the actor for good.
                if self._shutting_down():
                    return
                if progress[0] - steps_at_connect >= self._unroll_length:
                    failures = 0
                    backoff.reset()
                if failures < self._max_reconnects:
                    failures += 1
                    self._tm_retries.inc()
                    delay = backoff.sleep()
                    log.warning(
                        "Actor %d (%s): inference/state-table failure "
                        "(%s); retry %d/%d after %.2fs backoff",
                        index, address, e, failures,
                        self._max_reconnects, delay,
                    )
                    continue
                log.exception("Actor %d (%s) failed", index, address)
                with self._count_lock:
                    self._errors.append(e)
                return
            except (ConnectionError, TimeoutError, OSError,
                    wire.WireError) as e:
                # Transport failure: the env server died or the stream was
                # cut. During pipeline shutdown that's expected — exit
                # cleanly instead of burning the reconnect budget against
                # deliberately-stopped servers.
                if self._shutting_down():
                    return
                # A full recovery (at least one unroll streamed since the
                # last connect) earns the budget back — long runs survive
                # any number of spaced-out server redeploys.
                if progress[0] - steps_at_connect >= self._unroll_length:
                    failures = 0
                    backoff.reset()
                if failures < self._max_reconnects:
                    failures += 1
                    reconnect_pending[0] = True
                    delay = backoff.sleep()
                    log.warning(
                        "Actor %d (%s): transport failure (%s); "
                        "reconnect %d/%d after %.2fs backoff",
                        index, address, e, failures,
                        self._max_reconnects, delay,
                    )
                    continue
                log.exception("Actor %d (%s) failed", index, address)
                with self._count_lock:
                    self._errors.append(e)
                return
            except BaseException as e:  # noqa: BLE001
                log.exception("Actor %d (%s) failed", index, address)
                with self._count_lock:
                    self._errors.append(e)
                return

    def _connect(self, address: str, index: int):
        """Transport connect with retries until the deadline (the
        reference's 10-minute WaitForConnected semantics,
        actorpool.cc:354-372) — SocketTransport for tcp/unix addresses,
        ShmTransport (handshaken rings) for shm://. The chaos wrap (if
        armed) goes on here so injected faults see every connection,
        including post-reconnect ones."""
        sock = transport_lib.connect_transport(
            address, timeout_s=self._connect_timeout_s,
            max_frame_bytes=self._max_frame_bytes,
        )
        if self._transport_wrap is not None:
            sock = self._transport_wrap(sock, index)
        return sock

    @staticmethod
    def _env_outputs(msg) -> dict:
        if msg is None:
            raise ConnectionError("Env server closed the stream")
        if msg.get("type") == "error":
            raise RuntimeError(f"Env server error: {msg.get('message')}")
        # [T=1, B=1] leading dims so rollout stacking and queue batching
        # are pure concatenations (reference array_pb_to_nest prepends
        # [1, 1], actorpool.cc:480-491).
        # COPY, not view: decoded arrays alias the transport's reusable
        # receive buffer (RecvBuffer / shm ring), which the next recv on
        # this connection overwrites — while the rollout keeps these
        # steps alive for unroll_length receives (wire.py lifetime rule).
        return {
            k: np.asarray(msg[k])[None, None].copy() for k in _ENV_KEYS
        }

    def _recv_step(self, stream):
        msg, nbytes = stream.recv_sized()
        self._tm_bytes_up.inc(nbytes)
        return self._env_outputs(msg)

    def _loop(self, index: int, address: str, progress=None,
              reconnect_pending=None):
        progress = progress if progress is not None else [0]
        reconnect_pending = (
            reconnect_pending if reconnect_pending is not None else [False]
        )
        table = self._state_table
        sock = self._connect(address, index)
        self._tm_connects.inc()
        try:
            if table is not None:
                # Fresh stream => fresh recurrent state. This also covers
                # reconnects: the partial rollout was discarded, so the
                # slot must restart from the initial state.
                table.reset([index])
                initial_agent_state = table.initial_state_host
            else:
                initial_agent_state = self._initial_agent_state
            env_outputs = self._recv_step(sock)
            if reconnect_pending[0]:
                # The stream is re-established AND delivering: the
                # granted retry counts as a completed recovery now.
                reconnect_pending[0] = False
                with self._count_lock:
                    self._reconnects += 1
                self._tm_reconnects.inc()
            agent_state = self._initial_agent_state
            agent_outputs, agent_state = self._compute(
                index, env_outputs, agent_state, advance=False
            )
            rollout = [(env_outputs, agent_outputs)]
            while True:
                agent_outputs, agent_state = self._compute(
                    index, env_outputs, agent_state, advance=True
                )
                action = int(np.asarray(agent_outputs["action"]).reshape(()))
                self._tm_bytes_down.inc(
                    sock.send({"type": "action", "action": action})
                )
                env_outputs = self._recv_step(sock)
                progress[0] += 1
                self._tm_steps.inc()
                with self._count_lock:
                    self._count += 1
                rollout.append((env_outputs, agent_outputs))
                if len(rollout) == self._unroll_length + 1:
                    self._enqueue_rollout(rollout, initial_agent_state)
                    rollout = [rollout[-1]]  # overlap-by-one
                    # Boundary state for the NEXT rollout: with a state
                    # table, one read_slot fetch per unroll (the only
                    # time agent state crosses the host boundary);
                    # legacy mode carries it from the last reply.
                    if table is not None:
                        initial_agent_state = table.read_slot(index)
                    else:
                        initial_agent_state = agent_state
        finally:
            # shm connections: unlink the ring segments on every
            # teardown. A SIGKILL'd env server can't clean up its own
            # segments (/dev/shm would fill across chaos cycles); for a
            # live server this merely pre-empts the unlink its stream
            # teardown does anyway (rings are per-connection, never
            # re-attached — see ShmRing.unlink).
            try:
                sweep = getattr(sock, "unlink_segments", None)
                if sweep is not None:
                    sweep()
            finally:
                sock.close()

    def _request(self, inputs, index: int):
        """One batcher round-trip with RTT telemetry and a sampled
        per-request StageTrace (enqueue -> batch -> reply).

        Shed contract (ISSUE 14): a ShedError reply — the admission
        gate refused the request at enqueue, or its deadline expired in
        the queue — re-submits the SAME inputs after a jittered
        backoff, forever (overload is transient by construction: the
        gate sheds to protect drain rate). Shutdown cuts the loop via
        ClosedBatchingQueue from compute() or the re-raised ShedError
        when the pipeline is already closing. RTT and SLO breaches are
        observed for SERVED requests only — a shed's fast rejection
        must not read as a latency win."""
        trace = None
        if self._traceable:
            # beastlint: disable=RACE  sampling cadence, not an exact count: N actor threads may lose increments, which only shifts WHICH request gets traced
            self._trace_tick += 1
            if self._trace_tick % self._TRACE_EVERY == 0:
                trace = self._tracer.stage("actor.request", actor=index)
        shed_backoff = None
        while True:
            t0 = time.perf_counter()
            try:
                if trace is not None:
                    outputs = self._inference_batcher.compute(
                        inputs, trace=trace
                    )
                else:
                    outputs = self._inference_batcher.compute(inputs)
            except ShedError as e:
                # Counted BEFORE any early exit: every ShedError raised
                # is counted exactly once, which is what makes the
                # resubmitted == shed + expired audit exact.
                self._tm_resubmits.inc()
                if trace is not None:
                    if not getattr(e, "expired", False):
                        # Admission-path shed: the trace never entered
                        # the queue; close it here. (Expired sheds were
                        # finished by the batcher's dequeue gate.)
                        trace.stamp("shed")
                    trace.finish()
                    trace = None
                if self._shutting_down():
                    raise
                if shed_backoff is None:
                    shed_backoff = self._shed_backoff_factory()
                # Sliced sleep so shutdown never waits out a backoff
                # (the C++ twin's abort_shed callback, actor_pool.h);
                # a shutdown mid-sleep falls through to compute(),
                # which raises ClosedBatchingQueue -> clean exit.
                deadline = time.monotonic() + shed_backoff.next_delay()
                while (
                    time.monotonic() < deadline
                    and not self._shutting_down()
                ):
                    time.sleep(0.05)
                continue
            rtt = time.perf_counter() - t0
            self._tm_rtt.observe(rtt)
            if (
                self._slo_target_s is not None
                and rtt > self._slo_target_s
            ):
                self._tm_slo_breaches.inc()
            return outputs

    def _normalize_lag(self, agent_outputs):
        """Central-path replies carry no policy_lag leaf; replicas tag
        theirs. With lag recording on, default the missing leaf to 0 so
        a rollout mixing both serving paths stacks uniformly."""
        if (
            self._record_policy_lag
            and "policy_lag" not in agent_outputs
        ):
            agent_outputs["policy_lag"] = np.zeros((1, 1), np.int32)
        return agent_outputs

    def _compute(self, index: int, env_outputs, agent_state, advance: bool):
        if self._state_table is not None:
            # [1, 1]-shaped ids so queue batching along batch_dim=1
            # concatenates them like every other leaf.
            outputs = self._request(
                {
                    "env": env_outputs,
                    "slot": np.full((1, 1), index, np.int32),
                    "advance": np.full((1, 1), advance, bool),
                },
                index,
            )
            return self._normalize_lag(outputs["outputs"]), agent_state
        outputs = self._request(
            {"env": env_outputs, "agent_state": agent_state}, index
        )
        new_state = outputs["agent_state"]
        agent_outputs = self._normalize_lag(outputs["outputs"])
        if not advance:
            new_state = agent_state
        return agent_outputs, new_state

    def _enqueue_rollout(self, rollout, initial_agent_state):
        env_steps = [env for env, _ in rollout]
        agent_steps = [agent for _, agent in rollout]
        stacked = {
            k: np.concatenate([s[k] for s in env_steps], axis=0)
            for k in _ENV_KEYS
        }
        for key in agent_steps[0]:
            stacked[key] = np.concatenate(
                [np.asarray(s[key]) for s in agent_steps], axis=0
            )
        self._learner_queue.enqueue(
            {"batch": stacked, "initial_agent_state": initial_agent_state}
        )
