"""Single-host IMPALA trainer (the reference MonoBeast's role,
/root/reference/torchbeast/monobeast.py), re-designed TPU-first.

Architecture difference, deliberate: the reference forks actor processes
that each run the policy on CPU against a shared-memory model the learner
overwrites in place (monobeast.py:128-191, 295). On TPU, per-actor host
inference would starve the chip, so acting is *centrally batched*: env
processes only step environments; every env step is one jitted `[1, B]`
policy call on the TPU, and every unroll ends in one jitted update step. No
weight copies at all — actor and learner share the same on-device params
pytree. Policy lag is exactly zero by default (strictly stronger than the
reference's queue-backpressure guarantee); `--overlap_collect` trades it
for lag exactly 1 so the update chain hides behind env stepping.

Run:  python -m torchbeast_tpu.monobeast --env Mock --total_steps 20000
"""

import argparse
import functools
import logging
import os
import time

import jax
import numpy as np

from torchbeast_tpu import learner as learner_lib
from torchbeast_tpu import precision as precision_lib
from torchbeast_tpu import telemetry
from torchbeast_tpu.envs import create_env
from torchbeast_tpu.envs.vec import ProcessEnvPool, SerialEnvPool
from torchbeast_tpu.models import create_model
from torchbeast_tpu.rollout import (
    PipelinedRolloutCollector,
    RolloutCollector,
)
from torchbeast_tpu.utils import (
    FileWriter,
    Timings,
    load_checkpoint,
    save_checkpoint,
)

log = logging.getLogger("torchbeast_tpu.monobeast")


def _configure_logging():
    """Called from main(), NOT at import: importing this module (as
    every test does, and as polybeast does for its shared helpers) must
    not mutate global logging state."""
    logging.basicConfig(
        format=(
            "[%(levelname)s:%(process)d %(module)s:%(lineno)d "
            "%(asctime)s] %(message)s"
        ),
        level=logging.INFO,
    )


def make_parser():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--env", type=str, default="PongNoFrameskip-v4",
                        help="Gym environment (or Mock / Counting).")
    parser.add_argument("--mode", default="train",
                        choices=["train", "test"])
    parser.add_argument("--xpid", default=None, help="Experiment id.")
    # Training settings.
    parser.add_argument("--savedir", default="~/logs/torchbeast_tpu",
                        help="Root dir for experiment data.")
    parser.add_argument("--num_actors", type=int, default=8,
                        help="Parallel environments (= acting batch).")
    parser.add_argument("--total_steps", type=int, default=100000,
                        help="Total environment frames to train for.")
    parser.add_argument("--batch_size", type=int, default=8,
                        help="Learner batch size.")
    parser.add_argument("--vtrace_impl", default="associative",
                        choices=["sequential", "associative", "pallas"],
                        help="V-trace backward recursion: "
                             "lax.associative_scan (O(log T) depth, the "
                             "default), lax.scan (the reference's "
                             "T-dependent-steps formulation), or the "
                             "fused Pallas kernel (vs + advantages in "
                             "one VMEM pass; TPU-compiled, interpreted "
                             "elsewhere).")
    parser.add_argument("--unroll_length", type=int, default=80,
                        help="The unroll length (time dimension).")
    parser.add_argument("--model", default="shallow",
                        choices=["shallow", "deep", "mlp", "pipelined_mlp", "transformer", "pipelined_transformer"],
                        help="Model family (Mono used shallow; Poly deep; "
                             "mlp for tiny frames).")
    parser.add_argument("--use_lstm", action="store_true",
                        help="Use LSTM in the agent model.")
    parser.add_argument("--precision", default="f32",
                        choices=["f32", "bf16_compute", "bf16_train"],
                        help="Precision policy (torchbeast_tpu/"
                             "precision.py): f32 everywhere; "
                             "bf16_compute flips trunk compute to "
                             "bfloat16; bf16_train additionally makes "
                             "params/activations bf16-RESIDENT (f32 "
                             "master in the optimizer state, f32 "
                             "accumulate), stages the batch's float "
                             "leaves as bf16, and stores the RMSprop "
                             "second moment bf16 — the HBM-roofline "
                             "policy.")
    parser.add_argument("--model_dtype", default=None,
                        choices=["float32", "bfloat16"],
                        help="DEPRECATED alias: bfloat16 maps to "
                             "--precision bf16_compute (with a "
                             "warning); conflicts with an explicit "
                             "bf16_train.")
    parser.add_argument("--factored_opt_state", action="store_true",
                        help="Opt-in factored RMSprop second moment "
                             "(row/col EMAs for matrices, Adafactor-"
                             "style O(n+m) state; an approximation — "
                             "not torch-parity).")
    parser.add_argument("--trunk_channels", default="",
                        help="Opt-in deep-trunk widths as a comma list "
                             "(e.g. 32,64,64). Default: the reference's "
                             "16/32/32. A 16-channel conv fills 16 of an "
                             "MXU tile's 128 output lanes — wider trunks "
                             "buy capacity at far under proportional "
                             "step-time (benchmarks/mfu_ablation.py "
                             "measures the scaling). Deep model only.")
    parser.add_argument("--serial_envs", action="store_true",
                        help="Step envs in-process (tests/cheap envs).")
    parser.add_argument("--attention_impl", default="dense",
                        choices=["dense", "pallas"],
                        help="Transformer attention implementation: XLA "
                             "dense ops, or the fused Pallas kernel "
                             "(single-chip; compiled on TPU, interpreted "
                             "elsewhere).")
    parser.add_argument("--sequence_parallel", type=int, default=0,
                        help="Shard the transformer's unroll (time) axis "
                             "over N devices: in-unroll attention runs as "
                             "ring attention over a `seq` mesh axis "
                             "(model=transformer only; pick unroll_length "
                             "so T+1 is divisible by N — short/acting "
                             "forwards fall back to dense with the same "
                             "params).")
    parser.add_argument("--pipeline_parallel", type=int, default=0,
                        help="Run the pipelined_mlp / "
                             "pipelined_transformer tower as a GPipe "
                             "pipeline over N devices (a `pipe` mesh "
                             "axis; stage params one-per-chip, "
                             "activations rotate via ppermute).")
    parser.add_argument("--pipeline_microbatches", type=int, default=0,
                        help="Microbatch count M for the GPipe schedule "
                             "(0, the default, means one per pipeline "
                             "device). Bubble "
                             "fraction is (P-1)/(M+P-1) per pass — raise "
                             "M to amortize it; the learner batch must "
                             "divide into M microbatches.")
    parser.add_argument("--pipeline_stages", type=int, default=0,
                        help="Total tower depth (pipelined_mlp stages / "
                             "pipelined_transformer layers). Default: "
                             "one stage per pipeline device for the MLP; "
                             "the model's own num_layers for the "
                             "transformer. A multiple k*N runs k looped "
                             "passes.")
    parser.add_argument("--num_experts", type=int, default=0,
                        help="Replace the transformer's FFN with a top-2 "
                             "mixture of N experts (model=transformer "
                             "only; adds a sown load-balance loss).")
    parser.add_argument("--expert_parallel", type=int, default=0,
                        help="Shard the MoE experts over N devices (an "
                             "`expert` mesh axis; dispatch/combine become "
                             "XLA all-to-alls). Needs --num_experts "
                             "divisible by N.")
    parser.add_argument("--sp_strategy", default="ring",
                        choices=["ring", "ulysses"],
                        help="Sequence-parallel strategy: ring rotates "
                             "K/V blocks via ppermute (best for huge T); "
                             "ulysses re-shards to full-sequence x "
                             "heads/N via two all-to-alls (needs "
                             "num_heads divisible by N).")
    parser.add_argument("--ring_schedule", default="contiguous",
                        choices=["contiguous", "zigzag"],
                        help="Ring attention block schedule: zigzag "
                             "balances causal work (~2x fewer busiest-"
                             "device FLOPs; needs T+1 divisible by 2N).")
    parser.add_argument("--num_learner_devices", type=int, default=1,
                        help="Data-parallel learner over N local chips: "
                             "params replicated, each learner batch "
                             "sharded over a `data` mesh axis with an "
                             "ICI grad all-reduce (batch_size divisible "
                             "by N). Composing DP with SP/EP/TP/PP "
                             "lives in the async driver (polybeast).")
    parser.add_argument("--device_split", default="",
                        help="Sebulba device split (runtime/placement."
                             "py): 'auto' or 'inf=K,learn=rest|M'. In "
                             "the sync trainer the split pins the "
                             "acting forward to the first inference "
                             "device (policy params re-placed there "
                             "device-to-device at each rebind) and "
                             "compiles the learner update over a DP "
                             "mesh of the learner devices — collect "
                             "and learn stop contending for one chip's "
                             "compute. Empty = time-shared; a single-"
                             "device process degrades to it with a "
                             "warning. The full per-slice serving "
                             "split (pinned slot tables, snapshot "
                             "publication) lives in the async driver.")
    parser.add_argument("--fleet", default=None,
                        help="Multi-host Sebulba fleet membership "
                             "(fleet/topology.py): 'host=<rank>/<n>,"
                             "coord=<host:port>'. The sync trainer is "
                             "single-host by design — the flag is "
                             "declared for driver parity and rejected "
                             "when set; fleet runs live in the async "
                             "driver (polybeast --fleet).")
    parser.add_argument("--min_live_hosts", type=int, default=1,
                        help="Fleet degradation floor (--fleet runs; "
                             "async driver). Declared for driver "
                             "parity; no effect in the sync trainer.")
    parser.add_argument("--transformer_remat", action="store_true",
                        help="DEPRECATED spelling of --remat with the "
                             "transformer blocks stage at 'all' "
                             "(conflicts with an explicit --remat).")
    parser.add_argument("--remat", default=None,
                        help="Rematerialization plan over the model's "
                             "remat-able stages (runtime/remat_plan.py: "
                             "the ResNet trunk's per-stage none/front/"
                             "all, the transformer families' block "
                             "remat, the LSTM scan): 'auto' picks the "
                             "minimum-recompute plan whose XLA-measured "
                             "peak fits --hbm_budget_gb; 'all'/'none' "
                             "force every stage; 'stage0=front,"
                             "stage1=all,core=none' pins per stage. "
                             "Default: the static pre-planner defaults "
                             "(trunk all-remat, transformer per "
                             "--transformer_remat, LSTM scan saved). "
                             "The chosen plan is logged and exported "
                             "as the learner.remat_plan telemetry "
                             "static.")
    parser.add_argument("--hbm_budget_gb", type=float, default=0.0,
                        help="HBM envelope for --remat auto, in GiB "
                             "covering one live update dispatch "
                             "(params + optimizer state + staged "
                             "[K, T+1, B] stack + XLA temps). 0 = the "
                             "device's reported limit, else the "
                             "15.75 GiB v5e default.")
    parser.add_argument("--opt_impl", default="xla",
                        choices=["xla", "pallas"],
                        help="Optimizer-tail implementation: 'xla' "
                             "composes the optax chain; 'pallas' runs "
                             "grad-clip finalize -> torch-RMSprop/"
                             "momentum -> f32 master write -> bf16 "
                             "narrowing cast as ONE VMEM-resident "
                             "kernel per leaf (ops/pallas_opt.py; "
                             "TPU-compiled, interpreted elsewhere; "
                             "identical numerics, pinned by test).")
    parser.add_argument("--overlap_collect", action="store_true",
                        help="Act on params that are one dispatched "
                             "unroll-batch behind the learner head, so "
                             "the update chain always hides behind env "
                             "stepping and no act blocks on it. Default "
                             "off = zero policy lag: the first act of "
                             "each unroll waits for the update chain "
                             "(the reference's actors lag by queue "
                             "depth, so either mode is stricter than "
                             "the reference).")
    parser.add_argument("--pipelined_collect", dest="pipelined_collect",
                        action="store_true", default=True,
                        help="Lag-1 pipelined rollout collection "
                             "(default): per env step only the action "
                             "crosses device->host; logits/baseline "
                             "materialize one tick behind (overlapped "
                             "with env stepping) and agent state never "
                             "leaves the device. Identical batches to "
                             "the synchronous schedule.")
    parser.add_argument("--no_pipelined_collect", dest="pipelined_collect",
                        action="store_false",
                        help="Synchronous collection: materialize every "
                             "policy result on host before stepping "
                             "envs (debugging / host-policy baselines).")
    parser.add_argument("--superstep_k", type=int, default=1,
                        help="Learner superstep: fuse K SGD updates "
                             "into ONE lax.scan dispatch over a "
                             "[K, T+1, B, ...] batch stack (schedules "
                             "tick per-update inside the scan; stats "
                             "come back [K]-stacked so the host syncs "
                             "once per K updates). Bit-identical to K "
                             "sequential dispatches. Requires "
                             "num_actors/batch_size divisible by K "
                             "(each collect dispatches whole "
                             "supersteps). 1 = today's per-update "
                             "dispatch.")
    parser.add_argument("--seed", type=int, default=1234)
    parser.add_argument("--env_seed", type=int, default=None,
                        help="Base seed for stochastic envs; env i draws "
                             "from env_seed+i, so actors stay decorrelated "
                             "but the run reproduces (with --serial_envs "
                             "and a fixed --seed, end-to-end). Default: OS "
                             "entropy per env.")
    parser.add_argument("--max_env_restarts", type=int, default=10,
                        help="Supervision budget for process-pool env "
                             "workers: a crashed worker respawns with a "
                             "fresh env, its slot emitting an episode "
                             "boundary. 0 = fail fast. (--serial_envs "
                             "has no workers to supervise.)")
    parser.add_argument("--checkpoint_interval_s", type=int, default=600,
                        help="Seconds between checkpoints (reference: 10min).")
    parser.add_argument("--learner_stall_timeout_s", type=float,
                        default=300.0,
                        help="Learner stall watchdog: no update "
                             "dispatch within this deadline transitions "
                             "health to DEGRADED and dumps thread-stack "
                             "diagnostics; dispatches resuming recovers "
                             "it. 0 disables the watchdog.")
    # Loss settings.
    parser.add_argument("--entropy_cost", type=float, default=0.0006)
    parser.add_argument("--entropy_cost_final", type=float, default=None,
                        help="Linearly anneal the entropy cost from "
                             "--entropy_cost to this value over "
                             "total_steps (default: constant). "
                             "High-early/low-late exploration escapes "
                             "compliance traps like the Memory probe's "
                             "(lstm_learning.md 4/4b).")
    parser.add_argument("--baseline_cost", type=float, default=0.5)
    parser.add_argument("--discounting", type=float, default=0.99)
    parser.add_argument("--reward_clipping", default="abs_one",
                        choices=["abs_one", "none"])
    parser.add_argument("--loss", default="vtrace",
                        choices=["vtrace", "impact"],
                        help="Objective family: IMPALA V-trace (the "
                             "default) or the IMPACT clipped "
                             "target-network surrogate (ops/impact.py) "
                             "— lag-tolerant, unlocks --replay_reuse.")
    parser.add_argument("--impact_clip", type=float, default=0.2,
                        help="IMPACT surrogate clip epsilon "
                             "(--loss impact).")
    parser.add_argument("--replay_reuse", type=int, default=1,
                        help="Consume each collected batch K' times "
                             "(--loss impact; 1 = on-policy). The "
                             "schedule clock scales with it.")
    parser.add_argument("--target_refresh_updates", type=int, default=8,
                        help="Refresh the IMPACT target network every "
                             "N optimizer updates (--loss impact).")
    # Optimizer settings.
    parser.add_argument("--learning_rate", type=float, default=4.8e-4)
    parser.add_argument("--alpha", type=float, default=0.99,
                        help="RMSProp smoothing constant.")
    parser.add_argument("--momentum", type=float, default=0.0)
    parser.add_argument("--epsilon", type=float, default=0.01,
                        help="RMSProp epsilon.")
    parser.add_argument("--grad_norm_clipping", type=float, default=40.0)
    # Misc.
    parser.add_argument("--num_test_episodes", type=int, default=10)
    parser.add_argument("--profile_dir", default=None,
                        help="If set, capture a jax.profiler trace here.")
    telemetry.add_arguments(parser)
    return parser


def hparams_from_flags(flags) -> learner_lib.HParams:
    policy = precision_lib.resolve_flags(flags)
    return learner_lib.HParams(
        discounting=flags.discounting,
        baseline_cost=flags.baseline_cost,
        entropy_cost=flags.entropy_cost,
        entropy_cost_final=getattr(flags, "entropy_cost_final", None),
        reward_clipping=flags.reward_clipping,
        learning_rate=flags.learning_rate,
        rmsprop_alpha=flags.alpha,
        rmsprop_eps=flags.epsilon,
        rmsprop_momentum=flags.momentum,
        grad_norm_clipping=flags.grad_norm_clipping,
        total_steps=flags.total_steps,
        unroll_length=flags.unroll_length,
        batch_size=flags.batch_size,
        vtrace_impl=getattr(flags, "vtrace_impl", "associative"),
        opt_state_dtype=policy.opt_state_dtype,
        param_dtype=policy.param_dtype,
        opt_factored=getattr(flags, "factored_opt_state", False),
        opt_impl=getattr(flags, "opt_impl", "xla"),
        loss=getattr(flags, "loss", "vtrace"),
        impact_clip=getattr(flags, "impact_clip", 0.2),
        replay_reuse=max(1, getattr(flags, "replay_reuse", 1) or 1),
    )


def _make_pool(flags, num_envs):
    # functools.partial (not a lambda): ProcessEnvPool pickles the factory
    # into spawn-context workers.
    env_seed = getattr(flags, "env_seed", None)
    env_fns = [
        functools.partial(
            create_env, flags.env,
            seed=None if env_seed is None else env_seed + i,
        )
        for i in range(num_envs)
    ]
    if flags.serial_envs:
        return SerialEnvPool(env_fns)
    return ProcessEnvPool(env_fns, max_restarts=flags.max_env_restarts)


def dummy_env_outputs(t, batch_size, frame_shape, frame_dtype):
    """The env-output schema every acting/learning path consumes —
    ONE definition (model init dummies and polybeast's inference
    prewarm both build from it, so schema drift breaks both loudly
    instead of silently desynchronizing a compiled signature)."""
    return {
        "frame": np.zeros(
            (t, batch_size) + tuple(frame_shape), frame_dtype
        ),
        "reward": np.zeros((t, batch_size), np.float32),
        "done": np.ones((t, batch_size), bool),
        "last_action": np.zeros((t, batch_size), np.int32),
    }


def _probe_env(flags):
    """One throwaway env instance -> (num_actions, frame shape/dtype)."""
    from torchbeast_tpu.envs import num_actions_of
    from torchbeast_tpu.envs.environment import Environment

    probe = create_env(flags.env)
    n = num_actions_of(probe)
    frame = Environment(probe).initial()["frame"]
    if hasattr(probe, "close"):
        probe.close()
    return int(n), frame.shape, frame.dtype


def _make_1d_mesh(n: int, axis: str, flag_name: str):
    """A 1-D device mesh over the first n devices, with the consistent
    too-few-devices error every parallelism flag shares."""
    from jax.sharding import Mesh

    devices = jax.devices()
    if len(devices) < n:
        raise ValueError(
            f"--{flag_name} {n} but only {len(devices)} devices are "
            "visible"
        )
    return Mesh(np.asarray(devices[:n]), (axis,))


def _init_model_and_params(flags, num_actions, batch_size, frame_shape,
                           frame_dtype=np.uint8, moe_mesh=None,
                           seq_mesh=None, pipe_mesh=None, unmeshed=False,
                           init_params=True):
    """Build the model + initial params from flags.

    `unmeshed=True` strips every mesh binding from the constructed model
    (same flags, same param tree — meshes only select compute paths /
    add sharding constraints, never parameters). The async driver uses
    this for its ACTING model on multi-host runs, where the learner
    model's constraints reference global-mesh devices a host-local
    inference jit cannot touch.

    moe_mesh / seq_mesh: optional externally-built meshes with an
    `expert` / `seq` axis — the async driver passes its composite
    (data x expert|seq) learner mesh here so the model's sharding
    constraints/shard_maps reference the SAME mesh the update step is
    jitted over (two different meshes in one program is an XLA error).
    A composite seq_mesh also sets the model's batch_axis to "data".
    When None, the flags build 1-D meshes.
    """
    import jax.numpy as jnp

    policy = precision_lib.resolve_flags(flags)
    dtype = policy.compute_dtype
    extra = {}
    # EVERY family threads head_dtype now (ISSUE 13 closed the
    # transformer gap: models/transformer.py, transformer_pp.py, and
    # pipelined.py grew the kwarg) — bf16_train no longer silently
    # falls back to bf16-trunk-only anywhere.
    if policy.head_dtype != jnp.float32:
        extra["head_dtype"] = policy.head_dtype
    attention_impl = getattr(flags, "attention_impl", "dense")
    if attention_impl != "dense":
        if flags.model != "transformer":
            raise ValueError(
                "--attention_impl applies to --model transformer only"
            )
        extra["attention_impl"] = attention_impl
    seq_par = getattr(flags, "sequence_parallel", 0)
    if (
        getattr(flags, "ring_schedule", "contiguous") != "contiguous"
        and not (seq_par and seq_par > 1)
    ):
        raise ValueError(
            "--ring_schedule only takes effect with --sequence_parallel "
            "> 1 (no ring attention runs without a seq mesh)"
        )
    if (
        getattr(flags, "sp_strategy", "ring") != "ring"
        and not (seq_par and seq_par > 1)
    ):
        raise ValueError(
            "--sp_strategy only takes effect with --sequence_parallel "
            "> 1 (no sequence-parallel attention runs without a seq mesh)"
        )
    if seq_par and seq_par > 1:
        if flags.model != "transformer":
            raise ValueError(
                "--sequence_parallel needs --model transformer (the "
                "conv+LSTM families have no sequence-sharded formulation)"
            )
        if attention_impl != "dense":
            # In _Block the ring branch wins whenever T divides the seq
            # axis, so the fused kernel would silently only serve the
            # T=1 acting path — reject instead of surprising the user.
            raise ValueError(
                "--attention_impl pallas and --sequence_parallel are "
                "mutually exclusive (the ring path replaces the fused "
                "kernel on the learner forward)"
            )
        ring_schedule = getattr(flags, "ring_schedule", "contiguous")
        sp_strategy = getattr(flags, "sp_strategy", "ring")
        if sp_strategy == "ulysses":
            if ring_schedule != "contiguous":
                raise ValueError(
                    "--ring_schedule applies to --sp_strategy ring only"
                )
            # num_heads divisibility is validated AFTER create_model below,
            # against the heads the model is actually constructed with.
            divisor = seq_par
        else:
            divisor = 2 * seq_par if ring_schedule == "zigzag" else seq_par
        if (flags.unroll_length + 1) % divisor != 0:
            # The learner forward sees T = unroll_length + 1 steps; if the
            # mesh doesn't divide it, the model would silently fall back
            # to dense attention — the opposite of what the flag asks for.
            raise ValueError(
                f"--sequence_parallel {seq_par} "
                f"({ring_schedule}) requires unroll_length+1 divisible "
                f"by {divisor} (got {flags.unroll_length + 1})"
            )
        if seq_mesh is not None:
            extra["mesh"] = seq_mesh
            extra["batch_axis"] = "data"
        elif getattr(flags, "expert_parallel", 0) > 1:
            # SP x EP on one (data=1, model=1, seq, expert) mesh: the
            # attention shard_maps use `seq`, the MoE constraints use
            # `expert` (parallel/mesh.py; parity pinned by
            # tests/test_composite_mesh.py).
            from torchbeast_tpu.parallel import create_mesh

            ep = flags.expert_parallel
            extra["mesh"] = create_mesh(
                seq_par * ep,
                expert_parallelism=ep,
                seq_parallelism=seq_par,
            )
            extra["batch_axis"] = "data"
        else:
            extra["mesh"] = _make_1d_mesh(
                seq_par, "seq", "sequence_parallel"
            )
        extra["ring_schedule"] = ring_schedule
        extra["sp_strategy"] = sp_strategy
    num_experts = getattr(flags, "num_experts", 0)
    expert_par = getattr(flags, "expert_parallel", 0)
    pipe_par = getattr(flags, "pipeline_parallel", 0)
    if expert_par and not num_experts:
        raise ValueError("--expert_parallel needs --num_experts")
    if (pipe_par or 0) > 1 and (
        (seq_par or 0) > 1 or (expert_par or 0) > 1
    ):
        # SP and EP compose on one multi-axis mesh (above); the GPipe
        # shard_map's own ring schedule does not — its stage rotation
        # would need interleaving with the attention/MoE collectives.
        raise ValueError(
            "--pipeline_parallel cannot combine with "
            "--sequence_parallel or --expert_parallel (the pipeline "
            "schedule owns its mesh; SP x EP do compose with each other "
            "and with data parallelism)"
        )
    pipelined_models = ("pipelined_mlp", "pipelined_transformer")
    # The stage-count kwarg differs by family: the MLP's tower depth is
    # num_stages, the transformer's is its layer count.
    stage_kwarg = (
        "num_layers" if flags.model == "pipelined_transformer"
        else "num_stages"
    )
    if pipe_par and pipe_par > 1:
        if flags.model not in pipelined_models:
            raise ValueError(
                "--pipeline_parallel needs --model pipelined_mlp or "
                "pipelined_transformer (the other families have no "
                "stage-uniform tower to pipeline)"
            )
        if pipe_mesh is not None:
            # Composite (data x pipe) mesh from the async driver: each
            # data group runs its own GPipe; microbatch rows shard over
            # `data` (parallel/pp.py batch_axis).
            extra["mesh"] = pipe_mesh
            extra["batch_axis"] = "data"
        else:
            extra["mesh"] = _make_1d_mesh(
                pipe_par, "pipe", "pipeline_parallel"
            )
        # Stage-count default differs by family: the MLP tower's depth is
        # a pipeline artifact (one stage per device, as documented); the
        # transformer's depth is an ARCHITECTURE choice, so it defaults
        # to the model's own num_layers — deriving it from the device
        # count would silently change the net (and break checkpoint
        # compatibility with non-pipelined runs).
        if flags.model == "pipelined_transformer":
            from torchbeast_tpu.models import PipelinedTransformerNet

            default_stages = PipelinedTransformerNet.num_layers
        else:
            default_stages = pipe_par
        n_stages = getattr(flags, "pipeline_stages", 0) or default_stages
        if n_stages % pipe_par != 0:
            raise ValueError(
                f"--pipeline_stages {n_stages} must be a multiple of "
                f"--pipeline_parallel {pipe_par}"
            )
        extra[stage_kwarg] = n_stages
        n_mb = getattr(flags, "pipeline_microbatches", 0)
        if n_mb < 0:
            raise ValueError(
                f"--pipeline_microbatches {n_mb} must be >= 0 "
                "(0 means the default: one microbatch per pipeline "
                "device)"
            )
        if n_mb:
            extra["n_microbatches"] = n_mb
        # The learner batch must divide into microbatches (default: one
        # per pipe device) or every training forward would silently take
        # the models' sequential fallback — the opposite of what the
        # flag asks for. (Acting/eval batches fall back by design.)
        from torchbeast_tpu.parallel.pp import can_pipeline

        if flags.model == "pipelined_transformer":
            pipelined_quantity, what = flags.batch_size, "batch_size"
        else:  # pipelined_mlp microbatches over flattened T*B tokens
            pipelined_quantity = (flags.unroll_length + 1) * flags.batch_size
            what = "(unroll_length+1)*batch_size"
        if not can_pipeline(
            extra["mesh"], pipelined_quantity,
            n_microbatches=extra.get("n_microbatches"),
            batch_axis=extra.get("batch_axis"),
        ):
            from torchbeast_tpu.parallel.pp import (
                default_n_microbatches,
            )

            m_eff = default_n_microbatches(
                extra["mesh"], "pipe", extra.get("n_microbatches")
            )
            raise ValueError(
                f"--pipeline_parallel {pipe_par} requires {what} "
                f"(= {pipelined_quantity}) divisible by the microbatch "
                f"count ({m_eff}; --pipeline_microbatches overrides the "
                "one-per-device default), and each microbatch's rows by "
                "the data axis when composing with DP — otherwise the "
                "learner step would silently run the sequential fallback"
            )
    elif flags.model in pipelined_models:
        # No mesh, but the requested tower depth still applies — a
        # silently different stage count would make checkpoints
        # shape-incompatible with a later pipelined run.
        n_stages = getattr(flags, "pipeline_stages", 0)
        if n_stages:
            extra[stage_kwarg] = n_stages
        logging.getLogger(__name__).info(
            "--model %s without --pipeline_parallel: the stage tower "
            "runs sequentially on one device", flags.model,
        )
    if num_experts:
        if flags.model != "transformer":
            raise ValueError(
                "--num_experts applies to --model transformer only (the "
                "conv/MLP families have no MoE formulation)"
            )
        extra["num_experts"] = num_experts
        if expert_par and expert_par > 1:
            if num_experts % expert_par != 0:
                raise ValueError(
                    f"--num_experts {num_experts} not divisible by "
                    f"--expert_parallel {expert_par}"
                )
            if moe_mesh is not None:
                extra["moe_mesh"] = moe_mesh
            elif "expert" in getattr(
                extra.get("mesh"), "shape", {}
            ):
                # The SP x EP composite mesh built above carries the
                # `expert` axis — MoE constraints use the same mesh.
                extra["moe_mesh"] = extra["mesh"]
            else:
                extra["moe_mesh"] = _make_1d_mesh(
                    expert_par, "expert", "expert_parallel"
                )
    if getattr(flags, "transformer_remat", False):
        if flags.model not in ("transformer", "pipelined_transformer"):
            raise ValueError(
                "--transformer_remat applies to the transformer families "
                "only (the conv trunk already remats by default, "
                "models/resnet.py `remat`)"
            )
        # The actual remat kwarg comes from the plan below (the flag is
        # the deprecated spelling of `--remat` blocks=all).
    trunk_channels = getattr(flags, "trunk_channels", "")
    if trunk_channels:
        if flags.model != "deep":
            raise ValueError(
                "--trunk_channels applies to --model deep only (the "
                "knob widens the ResNet conv trunk)"
            )
        try:
            widths = tuple(int(c) for c in trunk_channels.split(","))
        except ValueError:
            widths = ()
        if len(widths) != 3 or any(w < 1 for w in widths):
            raise ValueError(
                f"--trunk_channels {trunk_channels!r} must be three "
                "positive comma-separated ints (e.g. 32,64,64)"
            )
        extra["trunk_channels"] = widths
    if unmeshed:
        for key in ("mesh", "moe_mesh", "batch_axis"):
            extra.pop(key, None)
    # Rematerialization plan (--remat, runtime/remat_plan.py): resolves
    # the per-stage remat kwargs — the static pre-planner defaults when
    # the flag is unset, or the cost-model auto-tuner against
    # --hbm_budget_gb. Candidate models for `auto` build UNMESHED (the
    # mesh only adds sharding constraints; the per-chip envelope is the
    # conservative planning target) with the same family kwargs.
    from torchbeast_tpu.runtime import remat_plan as remat_plan_lib

    plan_extra = {
        k: v for k, v in extra.items()
        if k not in ("mesh", "moe_mesh", "batch_axis")
    }
    plan = remat_plan_lib.resolve_from_flags(
        flags, hparams_from_flags(flags), num_actions, frame_shape,
        frame_dtype, policy,
        build_model=lambda kw: create_model(
            flags.model, num_actions=num_actions,
            use_lstm=flags.use_lstm, dtype=dtype,
            **{**plan_extra, **kw},
        ),
    )
    extra.update(
        remat_plan_lib.model_kwargs(flags.model, plan.assignment)
    )
    model = create_model(
        flags.model, num_actions=num_actions, use_lstm=flags.use_lstm,
        dtype=dtype, **extra,
    )
    if not init_params:
        # Caller only wants the model object (e.g. polybeast's unmeshed
        # acting twin — its param tree is identical to the meshed
        # model's, so re-initializing would be pure waste).
        return model, None
    if (
        seq_par
        and seq_par > 1
        and extra.get("sp_strategy") == "ulysses"
        and model.num_heads % seq_par != 0
    ):
        # Validated against the CONSTRUCTED model (not the class default,
        # which would silently diverge if a num_heads flag/kwarg is ever
        # added): an indivisible head count makes the model fall back to
        # dense attention — the opposite of what the flag asks for.
        raise ValueError(
            f"--sp_strategy ulysses requires num_heads "
            f"({model.num_heads}) divisible by --sequence_parallel "
            f"{seq_par} (heads are the sharded resource)"
        )
    dummy = dummy_env_outputs(1, batch_size, frame_shape, frame_dtype)
    state = model.initial_state(batch_size)
    params = model.init(
        {
            "params": jax.random.PRNGKey(flags.seed),
            "action": jax.random.PRNGKey(flags.seed + 1),
        },
        dummy,
        state,
    )
    # bf16_train: params are bf16-RESIDENT from here on — every
    # consumer (acting, learner, checkpoint templates) sees bf16; the
    # f32 master materializes inside optimizer.init (learner.
    # _bf16_resident_params). Cross-precision checkpoint resume fails
    # loudly at the template match, by design.
    params = precision_lib.cast_params(params, policy)
    return model, params


def train(flags):
    if flags.num_actors % flags.batch_size != 0:
        raise ValueError(
            "num_actors must be a multiple of batch_size in the sync trainer "
            f"(got {flags.num_actors} vs {flags.batch_size})"
        )
    superstep_k = getattr(flags, "superstep_k", 1)
    if superstep_k < 1:
        raise ValueError(f"--superstep_k must be >= 1, got {superstep_k}")
    if getattr(flags, "fleet", None):
        raise ValueError(
            "--fleet needs the async driver (polybeast): the sync "
            "trainer is single-host by design"
        )
    if (flags.num_actors // flags.batch_size) % superstep_k != 0:
        # Each collect's sub-batches must split into whole supersteps —
        # a fixed-K scan cannot consume a partial group, and carrying
        # sub-batches across collects would silently change policy lag.
        raise ValueError(
            f"--superstep_k {superstep_k} must divide the "
            f"{flags.num_actors // flags.batch_size} learner sub-batches "
            "per collect (num_actors / batch_size)"
        )
    n_dev = getattr(flags, "num_learner_devices", 1)
    if n_dev > 1:
        # Pure flag predicates — reject BEFORE any side effects
        # (FileWriter dir, env probe, model init).
        if any(
            (getattr(flags, f, 0) or 0) > 1
            for f in ("sequence_parallel", "expert_parallel",
                      "pipeline_parallel")
        ):
            raise ValueError(
                "--num_learner_devices in the sync trainer is plain DP; "
                "composing DP with SP/EP/PP needs the async driver's "
                "composite meshes (polybeast)"
            )
        if flags.batch_size % n_dev != 0:
            raise ValueError(
                f"batch_size {flags.batch_size} not divisible by "
                f"num_learner_devices {n_dev}"
            )
        if getattr(flags, "opt_impl", "xla") == "pallas":
            raise ValueError(
                "--opt_impl pallas does not compose with "
                "--num_learner_devices > 1 yet (the fused tail is a "
                "per-chip kernel; its sharded-update story is the "
                "Sebulba item's)"
            )
    # Sebulba device split (ISSUE 15, runtime/placement.py): resolved
    # and composition-checked before any side effects. None covers the
    # single-device degradation.
    from torchbeast_tpu.runtime.placement import (
        resolve_device_split,
        validate_split_composition,
    )

    split = resolve_device_split(
        getattr(flags, "device_split", ""), jax.devices()
    )
    validate_split_composition(
        flags, split,
        parallel_flags=("sequence_parallel", "expert_parallel",
                        "pipeline_parallel"),
    )
    if split is not None and getattr(flags, "opt_impl", "xla") == "pallas":
        raise ValueError(
            "--opt_impl pallas does not compose with --device_split "
            "yet (the fused tail is a per-chip kernel)"
        )
    if flags.xpid is None:
        flags.xpid = "torchbeast-tpu-%s" % time.strftime("%Y%m%d-%H%M%S")
    plogger = FileWriter(
        xpid=flags.xpid, xp_args=vars(flags), rootdir=flags.savedir
    )
    checkpoint_path = os.path.join(
        os.path.expanduser(flags.savedir), flags.xpid, "model.ckpt"
    )
    # Telemetry (ISSUE 2): stage latencies, learner batch-size
    # distribution, and dispatch-queue occupancy land in
    # {xpid}/telemetry.jsonl on the 5s log cadence.
    tele = telemetry.DriverTelemetry(
        flags, plogger.paths["telemetry"], driver="monobeast"
    )
    telemetry_on = tele.enabled
    reg = tele.registry
    # Stall visibility (ISSUE 6): the sync trainer has no monitor
    # thread, so a wedged collect (dead env worker, hung device) used
    # to look like silence. The watchdog degrades health.state and
    # dumps thread stacks after --learner_stall_timeout_s of no update
    # dispatches.
    from torchbeast_tpu.resilience import LearnerWatchdog, PipelineHealth

    health = PipelineHealth(registry=reg)
    watchdog = LearnerWatchdog(
        getattr(flags, "learner_stall_timeout_s", 300.0),
        health=health,
        registry=reg,
    )

    hp = hparams_from_flags(flags)
    prec = precision_lib.resolve_flags(flags)
    num_actions, frame_shape, frame_dtype = _probe_env(flags)
    B = flags.num_actors
    T = flags.unroll_length

    model, params = _init_model_and_params(
        flags, num_actions, B, frame_shape, frame_dtype
    )
    # The resolved remat plan rides every telemetry line as a static
    # (same convention as polybeast's acting_path block).
    from torchbeast_tpu.runtime import remat_plan as remat_plan_lib

    remat_plan = remat_plan_lib.last_plan()
    if remat_plan is not None:
        tele.set_static("learner.remat_plan", remat_plan.summary())
    optimizer = learner_lib.make_optimizer(hp)
    opt_state = optimizer.init(params)

    step = 0
    stats = {}
    if os.path.exists(checkpoint_path):
        restored = load_checkpoint(
            checkpoint_path,
            params_template=params,
            opt_state_template=opt_state,
        )
        params, opt_state = restored["params"], restored["opt_state"]
        step = restored["step"]
        stats = restored["stats"]
        log.info("Resuming preempted job, current stats:\n%s", stats)

    # Zero-lag mode donates params (nothing references the old buffer
    # once the cell is swapped); overlap mode acts on the old params for
    # a whole unroll, so only the opt state may be donated.
    donate = "opt_only" if flags.overlap_collect else True
    n_dev = getattr(flags, "num_learner_devices", 1)
    K = superstep_k
    # --replay_reuse K': every staged batch is dispatched K' times
    # (IMPACT's sample reuse). Reused batches cannot be donated — the
    # second dispatch would read a donated buffer — so batch donation
    # stays a K'=1 optimization.
    reuse = max(1, hp.replay_reuse)
    # A split with ONE learner device takes the plain-jit path below
    # pinned by explicit placement — a 1-device mesh would pull the
    # update through the SPMD partitioner for nothing (measured ~1.7x
    # slower per update on the CPU lane).
    learner_device = None
    if split is not None and len(split.learner_devices) == 1:
        learner_device = split.learner_devices[0]
    use_mesh = n_dev > 1 or (
        split is not None and learner_device is None
    )
    if use_mesh:
        from torchbeast_tpu.parallel import (
            create_mesh,
            make_parallel_update_step,
            replicate,
            shard_batch,
        )

        # Under the split the mesh spans exactly the learner devices;
        # otherwise the first n_dev devices.
        if split is not None:
            mesh = create_mesh(devices=list(split.learner_devices))
        else:
            mesh = create_mesh(n_dev)
        params = replicate(mesh, params)
        opt_state = replicate(mesh, opt_state)
        # superstep_k > 1: the same K-scan wrapper, sharded — the staged
        # [K, T+1, B] stack is fresh (stack_superstep_columns copies),
        # consumed exactly once, so batch donation's consume-once
        # enforcement applies.
        update_step = make_parallel_update_step(
            model, optimizer, hp, mesh, donate=donate,
            superstep_k=K, donate_batch=K > 1 and reuse == 1,
        )
        place_sub = lambda b, s: shard_batch(  # noqa: E731
            mesh,
            precision_lib.cast_batch(b, prec.batch_dtype),
            precision_lib.cast_batch(s, prec.batch_dtype),
            leading_axes=1 if K > 1 else 0,
        )
        log.info(
            "Sync learner data-parallel over %d devices%s",
            int(mesh.shape["data"]),
            " (device split)" if split is not None else "",
        )
    else:
        if K > 1:
            # One dispatch = K scanned updates; the staged stack is a
            # fresh copy nothing re-reads, so donate it (consume-once
            # deletion — learner.consume_staged_inputs).
            update_step = learner_lib.make_update_superstep(
                model, optimizer, hp, K, donate=donate,
                donate_batch=reuse == 1,
            )
        else:
            # No donate_batch: update_body emits no batch-shaped outputs
            # to alias, so donating the staged batch frees nothing (see
            # learner.donate_argnums_for).
            update_step = learner_lib.make_update_step(
                model, optimizer, hp, donate=donate
            )
        # Explicit (async) placement: donation needs committed device
        # buffers — a host-numpy arg reaches the jit as an undonatable
        # transfer (and a warning); device_put also starts the H2D copy
        # before dispatch instead of inside it. The precision policy's
        # staging cast happens here (bf16_train: float32 leaves travel
        # host->device half-width; the learner upcasts at point of
        # use).
        if learner_device is not None:
            params = jax.device_put(params, learner_device)
            opt_state = jax.device_put(opt_state, learner_device)
        place_sub = lambda b, s: (  # noqa: E731
            jax.device_put(
                precision_lib.cast_batch(b, prec.batch_dtype),
                learner_device,
            ),
            jax.device_put(
                precision_lib.cast_batch(s, prec.batch_dtype),
                learner_device,
            ),
        )
    if telemetry_on:
        # Dispatch latency + batch transfer bytes per update (counts K
        # updates per superstep dispatch).
        update_step = learner_lib.instrument_update_step(
            update_step, superstep_k=K
        )
    count_host_sync = getattr(
        update_step, "count_host_sync", lambda: None
    )
    if K > 1:
        log.info("Learner supersteps: %d updates per dispatch", K)
    act_step = learner_lib.make_act_step(model)

    # Split acting placement: the policy forward runs pinned to the
    # first inference device — params re-placed there (one explicit
    # device-to-device copy) at every rebind, so collect and learn
    # never contend for one chip. Identity off-split.
    if split is not None:
        act_device = split.inference_devices[0]
        place_act = lambda p: jax.device_put(p, act_device)  # noqa: E731
        tele.set_static("device_split", split.describe())
        log.info(
            "Acting pinned to inference device %s",
            getattr(act_device, "id", act_device),
        )
    else:
        place_act = lambda p: p  # noqa: E731
    # The learner mesh shape rides every telemetry line (polybeast's
    # convention): the 1x1 placeholder for the single-device update.
    tele.set_static(
        "learner.mesh_shape",
        {k: int(v) for k, v in mesh.shape.items()}
        if use_mesh else {"data": 1, "model": 1},
    )

    # IMPACT target network (--loss impact): full-precision params
    # stamped every --target_refresh_updates updates ride the same
    # versioned store class as replica serving snapshots — the
    # "learner.target" namespace keeps its cadence out of the serving
    # counters, and cast_bf16=False because the target forward must
    # equal a forward of the exact stamped params.
    target_store = None
    target_forward = None
    updates_done = 0
    if hp.loss == "impact":
        from torchbeast_tpu.serving.snapshot import PolicySnapshotStore

        target_store = PolicySnapshotStore(
            max(1, getattr(flags, "target_refresh_updates", 8) or 1),
            registry=reg,
            namespace="learner.target",
            cast_bf16=False,
        )
        target_forward = learner_lib.make_target_forward(
            model, superstep_k=K
        )
        # v0 before any update: the first batches train against the
        # init params (ratio == 1, the V-trace-equivalent point).
        target_store.publish(0, params)
        log.info(
            "IMPACT loss: target network refresh every %d updates, "
            "replay reuse %d",
            target_store.refresh_updates, reuse,
        )

    pool = _make_pool(flags, B)
    # A failure between the pool spawn and the main try/finally
    # (collector priming, closure setup) must not leak the env
    # worker processes — same reaping contract as polybeast's
    # server group.
    try:
        rng = jax.random.PRNGKey(flags.seed + 2)

        # Mutable cell so the policy closure always samples with fresh rng.
        rng_cell = [rng]
        pipelined = getattr(flags, "pipelined_collect", True)

        def policy(env_output, agent_state):
            rng_cell[0], key = jax.random.split(rng_cell[0])
            model_inputs = {
                k: env_output[k]
                for k in ("frame", "reward", "done", "last_action")
            }
            out, new_state = act_step(params_cell[0], key, model_inputs, agent_state)
            if pipelined:
                # The lag-1 collector owns materialization: it fetches
                # the action per step and everything else one tick
                # behind; state stays on device end-to-end.
                return out, new_state
            return jax.device_get(out), new_state

        params_cell = [place_act(params)]
        collector_cls = (
            PipelinedRolloutCollector if pipelined else RolloutCollector
        )
        collector = collector_cls(
            pool, policy, model.initial_state(B), unroll_length=T
        )

        # Stage latencies (collect/learn) become driver.* histograms in
        # the snapshot; with telemetry off, a private registry keeps the
        # 5s log line working unchanged.
        timings = Timings(
            registry=reg if telemetry_on else None, prefix="driver."
        )
        # The sync trainer has no inter-thread queues; its occupancy
        # analog is the delayed-stats dispatch pipeline — update
        # batches dispatched whose stats the host has NOT yet flushed
        # (sampled at the log tick: 0 before the first dispatch /
        # after the final flush, B/batch_size in steady state).
        h_batch_size = reg.histogram("learner.batch_size")
        g_dispatch_q = reg.gauge("dispatch_queue.depth")
        g_sps = reg.gauge("learner.sps")
        # env vs learn throughput split (ISSUE 18): env_sps counts
        # unique environment frames; learn_sps counts frames consumed
        # by updates — env_sps x replay_reuse in steady state.
        # learner.sps stays the env-frame rate (back-compat).
        g_env_sps = reg.gauge("learner.env_sps")
        g_learn_sps = reg.gauge("learner.learn_sps")
        reg.gauge("learner.sample_reuse").set(reuse)
        last_checkpoint_time = time.time()
        last_log_time = time.time()
        last_log_step = step
        learn_step = step * reuse  # resume: exact split not persisted
        last_log_learn_step = learn_step

        if flags.profile_dir:
            jax.profiler.start_trace(flags.profile_dir)

        # One-iteration-delayed stats fetch: updates for unroll k are
        # DISPATCHED (async) and the host immediately starts collecting
        # unroll k+1; the blocking device_get of k's stats happens after
        # k+1's work is underway. What overlaps beyond that depends on the
        # policy-lag choice:
        # - default (zero lag): the first act of unroll k+1 data-depends on
        #   the updated params, so its device_get blocks until the update
        #   chain finishes — only the stats fetch is truly overlapped. This
        #   is a deliberate on-policy guarantee the reference does not have.
        # - --overlap_collect: acting adopts the chain head only after a
        #   full collect has passed since its dispatch, so the update chain
        #   always hides behind env stepping and no act ever blocks on it.
        #   The acting params trail the learner head by one dispatched
        #   unroll-batch — still strictly tighter than the reference, whose
        #   actors lag by queue depth (SURVEY.md, actorpool backpressure).
        pending = None  # (list of device stats, step after those updates)
        latest_params = params_cell[0]  # head of the update chain

        def flush_stats(pending_entry):
            device_stats, at_step = pending_entry
            sub_stats = jax.device_get(device_stats)  # one batched transfer
            count_host_sync()
            agg = {}
            for key in sub_stats[0]:
                # Each dispatch's stats leaves are scalars (K=1) or
                # [K]-stacked (supersteps): concatenate to per-UPDATE
                # rows so episode sums/counts SUM over every update and
                # loss keys MEAN over every update — identical
                # aggregation either way, no /K undercount.
                vals = np.concatenate([
                    np.atleast_1d(np.asarray(s[key], np.float64))
                    for s in sub_stats
                ])
                if key in ("episode_returns_sum", "episode_count"):
                    agg[key] = float(vals.sum())
                else:
                    agg[key] = float(vals.mean())
            out = learner_lib.episode_stat_postprocess(agg)
            out["step"] = at_step
            plogger.log(out)
            return out

        def merge_target(placed_batch, placed_state):
            """Thread the lagged target network's forward outputs into
            the staged batch (learner.TARGET_*_KEY) — computed once per
            FRESH batch and shared by all K' reuse dispatches, so the
            target is held fixed across the reuse epochs (IMPACT's
            contract). Identity under --loss vtrace."""
            if target_forward is None:
                return placed_batch
            _, tparams = target_store.latest()
            t_logits, t_base = target_forward(
                tparams, placed_batch, placed_state
            )
            return {
                **placed_batch,
                learner_lib.TARGET_LOGITS_KEY: t_logits,
                learner_lib.TARGET_BASELINE_KEY: t_base,
            }

        def maybe_refresh_target():
            # Between reuse groups only — never mid-reuse, so every
            # batch trains against exactly one target version.
            if target_store is not None and target_store.note_update(
                updates_done
            ):
                target_store.publish(updates_done, latest_params)

    except BaseException:
        pool.close()
        raise
    tracer = telemetry.get_tracer()
    watchdog.start()
    try:
        while step < flags.total_steps:
            timings.reset()
            with tracer.span("driver.collect", cat="driver"):
                batch, initial_agent_state = collector.collect()
            timings.time("collect")
            if flags.overlap_collect:
                # Adopt the chain head dispatched BEFORE this collect —
                # it had the whole collect to materialize, so the next
                # collect's first act won't block on it; the updates
                # dispatched below hide behind the NEXT collect the same
                # way. (Adopting before collect() would re-create the
                # zero-lag block: the head would be moments old.)
                params_cell[0] = place_act(latest_params)

            # Split the [T+1, num_actors] unroll into learner batches of
            # batch_size columns; aggregate stats over ALL sub-batches
            # (losses averaged, episode sums/counts summed). With
            # supersteps, K consecutive sub-batches stack into one
            # [K, T+1, batch_size] dispatch — the scan applies them in
            # the SAME order the per-update loop would, so the update
            # sequence (and with it every schedule tick) is identical.
            device_stats = []
            with tracer.span("driver.learn", cat="driver"):
                if K > 1:
                    group = K * flags.batch_size
                    for i in range(0, B, group):
                        stacked, stacked_state = (
                            learner_lib.stack_superstep_columns(
                                batch, initial_agent_state, K,
                                flags.batch_size, offset=i,
                            )
                        )
                        stacked, stacked_state = place_sub(
                            stacked, stacked_state
                        )
                        stacked = merge_target(stacked, stacked_state)
                        # --replay_reuse: the SAME placed batch is
                        # dispatched K' times (donation is off for
                        # K' > 1, so nothing invalidates the buffers);
                        # env frames advance on the first pass only.
                        for r in range(reuse):
                            for _ in range(K):
                                h_batch_size.observe(flags.batch_size)
                            latest_params, opt_state, train_stats = (
                                update_step(
                                    latest_params, opt_state, stacked,
                                    stacked_state,
                                )
                            )
                            device_stats.append(train_stats)
                            updates_done += K
                            if r == 0:
                                step += K * T * flags.batch_size
                            learn_step += K * T * flags.batch_size
                        maybe_refresh_target()
                else:
                    for i in range(0, B, flags.batch_size):
                        sub = {
                            k: v[:, i : i + flags.batch_size]
                            for k, v in batch.items()
                        }
                        sub_state = jax.tree_util.tree_map(
                            lambda s: s[:, i : i + flags.batch_size],
                            initial_agent_state,
                        )
                        sub, sub_state = place_sub(sub, sub_state)
                        sub = merge_target(sub, sub_state)
                        # Actual sub-batch columns, not the flag (honest
                        # even while train() enforces divisibility).
                        cols = min(i + flags.batch_size, B) - i
                        for r in range(reuse):
                            h_batch_size.observe(cols)
                            latest_params, opt_state, train_stats = (
                                update_step(
                                    latest_params, opt_state, sub,
                                    sub_state,
                                )
                            )
                            device_stats.append(train_stats)
                            updates_done += 1
                            if r == 0:
                                step += T * flags.batch_size
                            learn_step += T * flags.batch_size
                        maybe_refresh_target()
            if not flags.overlap_collect:
                params_cell[0] = place_act(latest_params)  # zero policy lag
            if pending is not None:
                stats = flush_stats(pending)
            pending = (device_stats, step)
            timings.time("learn")
            watchdog.ping()

            now = time.time()
            if now - last_log_time > 5:
                sps = (step - last_log_step) / (now - last_log_time)
                learn_sps = (learn_step - last_log_learn_step) / (
                    now - last_log_time
                )
                last_log_time, last_log_step = now, step
                last_log_learn_step = learn_step
                g_sps.set(sps)
                g_env_sps.set(sps)
                g_learn_sps.set(learn_sps)
                # Dispatched-unflushed UPDATES at this instant (the
                # delayed-stats pipeline's real occupancy; a superstep
                # dispatch holds K updates, so count K per entry).
                g_dispatch_q.set(len(pending[0]) * K if pending else 0)
                tele.write(extra={"step": step})
                means = timings.means()
                log.info(
                    "Steps %d @ %.1f SPS. Loss %s. "
                    "[collect %.0fms learn %.0fms] %s",
                    step,
                    sps,
                    # First log can precede the first (delayed) stats
                    # fetch — print a placeholder, not a scary nan.
                    (
                        f"{stats['total_loss']:.4f}"
                        if "total_loss" in stats
                        else "--"
                    ),
                    1000 * means.get("collect", 0.0),
                    1000 * means.get("learn", 0.0),
                    f"Return {stats['mean_episode_return']:.1f}."
                    if "mean_episode_return" in stats
                    else "",
                )

            if now - last_checkpoint_time > flags.checkpoint_interval_s:
                save_checkpoint(
                    checkpoint_path,
                    params=latest_params,
                    opt_state=opt_state,
                    step=step,
                    flags=vars(flags),
                    stats=stats,
                )
                last_checkpoint_time = now
        successful = True
    except KeyboardInterrupt:
        log.info("Interrupted; saving final checkpoint.")
        successful = True
    except BaseException:
        successful = False
        raise
    finally:
        watchdog.stop()
        # Flush the one-iteration-delayed stats so the final checkpoint
        # and return value are current even on interrupt (guarded: an
        # async XLA error may surface here instead of at dispatch).
        if pending is not None:
            try:
                stats = flush_stats(pending)
            except Exception:
                log.exception("Could not flush final stats")
            pending = None
        g_dispatch_q.set(0)  # everything flushed (or abandoned) now
        if flags.profile_dir:
            jax.profiler.stop_trace()
        save_checkpoint(
            checkpoint_path,
            params=latest_params,
            opt_state=opt_state,
            step=step,
            flags=vars(flags),
            stats=stats,
        )
        tele.shutdown(step=step)
        plogger.close(successful=successful)
        pool.close()
    log.info("Learning finished after %d steps.", step)
    return stats


def test(flags):
    """Greedy evaluation episodes (reference monobeast.py:508-542)."""
    if flags.xpid is None:
        checkpoint_path = os.path.expanduser(
            os.path.join(flags.savedir, "latest", "model.ckpt")
        )
    else:
        checkpoint_path = os.path.expanduser(
            os.path.join(flags.savedir, flags.xpid, "model.ckpt")
        )

    num_actions, frame_shape, frame_dtype = _probe_env(flags)
    model, params = _init_model_and_params(
        flags, num_actions, 1, frame_shape, frame_dtype
    )
    if os.path.exists(checkpoint_path):
        hp = hparams_from_flags(flags)
        optimizer = learner_lib.make_optimizer(hp)
        restored = load_checkpoint(
            checkpoint_path,
            params_template=params,
            opt_state_template=optimizer.init(params),
        )
        params = restored["params"]
        log.info("Loaded checkpoint from %s", checkpoint_path)
    else:
        log.warning("No checkpoint at %s; testing random init.", checkpoint_path)

    from torchbeast_tpu.envs.environment import Environment

    # Same seed contract as training: --env_seed pins the eval env's
    # draw stream so repeated evaluations of a checkpoint reproduce.
    env = Environment(
        create_env(flags.env, seed=getattr(flags, "env_seed", None))
    )
    act = jax.jit(
        lambda p, inputs, state: model.apply(
            p, inputs, state, sample_action=False
        )
    )

    returns = []
    observation = env.initial()
    agent_state = model.initial_state(1)
    while len(returns) < flags.num_test_episodes:
        inputs = {
            k: np.asarray(observation[k])[None, None]
            for k in ("frame", "reward", "done", "last_action")
        }
        out, agent_state = act(params, inputs, agent_state)
        observation = env.step(int(out.action[0, 0]))
        if observation["done"]:
            returns.append(float(observation["episode_return"]))
            log.info("Episode ended after %d steps. Return: %.1f",
                     int(observation["episode_step"]), returns[-1])
    env.close()
    log.info(
        "Average returns over %i episodes: %.1f",
        len(returns), sum(returns) / len(returns),
    )
    return returns


def main(flags):
    _configure_logging()
    if flags.mode == "train":
        return train(flags)
    return test(flags)


def cli():
    from torchbeast_tpu.utils import install_preemption_handler

    install_preemption_handler()  # SIGTERM -> clean checkpointed exit
    # Make the JAX_PLATFORMS env var authoritative even when a site hook
    # (e.g. a TPU-plugin sitecustomize) already forced a platform list.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    main(make_parser().parse_args())


if __name__ == "__main__":
    cli()
