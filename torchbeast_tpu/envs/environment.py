"""Environment adapter: raw env -> numpy EnvOutput steps with episode
accounting.

Re-design of the reference's gym->tensor adapter
(/root/reference/torchbeast/core/environment.py:30-69). Differences:
- numpy instead of torch; frames stay HWC uint8 (TPU-native NHWC layout).
- unbatched: returns scalar/array fields per env; drivers batch across envs
  (the reference baked [T=1,B=1] dims in here because its actors were
  single-env processes).
- speaks both the gymnasium 5-tuple API and a minimal `reset()->obs /
  step(a)->(obs, reward, done)` protocol (our Mock envs).

Episode accounting lives here, as in the reference (episode_step/
episode_return travel with each step so the learner can extract returns of
episodes that ended inside a batch, SURVEY.md §5.5). The initial state has
done=True, reward=0, last_action=0 (reference environment.py:31-45), and the
env auto-resets on done with counters zeroed for the following step.
"""

from typing import Any, Dict

import numpy as np


def _step_env(env, action):
    """Normalize gymnasium's 5-tuple and the minimal 3-tuple protocols."""
    result = env.step(action)
    if len(result) == 5:
        obs, reward, terminated, truncated, _info = result
        return obs, float(reward), bool(terminated or truncated)
    obs, reward, done = result[:3]
    return obs, float(reward), bool(done)


def _reset_env(env):
    result = env.reset()
    if isinstance(result, tuple) and len(result) == 2:
        return result[0]  # gymnasium: (obs, info)
    return result


class Environment:
    """Stateful single-env stepper producing EnvOutput-shaped dicts."""

    def __init__(self, env):
        self._env = env
        self._episode_return = 0.0
        self._episode_step = 0

    def initial(self) -> Dict[str, Any]:
        self._episode_return = 0.0
        self._episode_step = 0
        frame = _reset_env(self._env)
        return {
            "frame": np.asarray(frame),
            "reward": np.float32(0.0),
            "done": True,  # marks the boundary step (reference convention)
            "episode_return": np.float32(0.0),
            "episode_step": np.int32(0),
            "last_action": np.int32(0),
        }

    def step(self, action: int) -> Dict[str, Any]:
        frame, reward, done = _step_env(self._env, int(action))
        self._episode_step += 1
        self._episode_return += reward
        episode_step = self._episode_step
        episode_return = self._episode_return
        if done:
            frame = _reset_env(self._env)
            # Counters reported with THIS step keep the finished episode's
            # totals; they restart on the next step (reference
            # environment.py:49-62, rpcenv.cc:106-119).
            self._episode_step = 0
            self._episode_return = 0.0
        return {
            "frame": np.asarray(frame),
            "reward": np.float32(reward),
            "done": done,
            "episode_return": np.float32(episode_return),
            "episode_step": np.int32(episode_step),
            "last_action": np.int32(action),
        }

    def close(self):
        if hasattr(self._env, "close"):
            self._env.close()
