"""Vectorized environment pools: B envs behind one batched step() call.

The actor-parallelism layer (reference: `num_actors` forked processes each
owning one env, monobeast.py:362-381). Here the batching is explicit because
acting is centrally batched on the TPU: the driver calls `pool.step(actions)`
with a `[B]` action vector and gets `[B, ...]`-stacked EnvOutput dicts back.

Two implementations:
- SerialEnvPool: in-process loop — zero IPC, right for cheap/mock envs and
  tests.
- ProcessEnvPool: one OS process per env (spawn context so workers never
  inherit JAX/TPU state), pipes carrying numpy arrays. Equivalent role to the
  reference's actor processes; the heavy C++ shared-memory transport arrives
  with the native runtime.
"""

import logging
import multiprocessing as mp
from typing import Callable, Dict, List

import numpy as np

from torchbeast_tpu.envs.environment import Environment

log = logging.getLogger(__name__)


def _stack(outputs: List[Dict]) -> Dict[str, np.ndarray]:
    return {
        k: np.stack([o[k] for o in outputs], axis=0) for k in outputs[0]
    }


class SerialEnvPool:
    def __init__(self, env_fns: List[Callable]):
        self._envs = [Environment(fn()) for fn in env_fns]
        self._pending = None

    def __len__(self):
        return len(self._envs)

    def initial(self) -> Dict[str, np.ndarray]:
        return _stack([e.initial() for e in self._envs])

    def step(self, actions) -> Dict[str, np.ndarray]:
        return _stack(
            [e.step(int(a)) for e, a in zip(self._envs, actions)]
        )

    # step_async/step_wait: the split-phase contract the lag-1 pipelined
    # collector overlaps against (rollout.py). Serially there is nothing
    # to overlap — the step runs inside step_async — but the API holds,
    # so collectors need no pool-type branches.
    def step_async(self, actions) -> None:
        if self._pending is not None:
            raise RuntimeError("step_async called with a step in flight")
        self._pending = self.step(actions)

    def step_wait(self) -> Dict[str, np.ndarray]:
        if self._pending is None:
            raise RuntimeError("step_wait without step_async")
        out, self._pending = self._pending, None
        return out

    def close(self):
        for e in self._envs:
            e.close()


def _env_worker(conn, env_fn):
    """Child process body: owns one Environment, serves initial/step."""
    try:
        env = Environment(env_fn())
        while True:
            cmd, arg = conn.recv()
            if cmd == "initial":
                conn.send(env.initial())
            elif cmd == "step":
                conn.send(env.step(arg))
            elif cmd == "close":
                env.close()
                conn.send(None)
                break
    except (EOFError, KeyboardInterrupt):
        pass


class ProcessEnvPool:
    """One OS process per env, with worker SUPERVISION: a crashed
    worker (env segfault, OOM-kill) is respawned with a fresh env and
    its slot emits that env's `initial()` — which IS the boundary-step
    convention (done=True, reward 0), so the learner sees a normal
    episode boundary and resets the slot's agent state. `max_restarts`
    (cumulative, 0 = fail fast) caps crash-looping; exhaustion raises
    with the transport error chained. A revived seeded env restarts
    its draw stream (crash recovery trades a replayed stream for the
    run surviving)."""

    def __init__(self, env_fns: List[Callable], ctx: str = "spawn",
                 max_restarts: int = 10):
        self._ctx = mp.get_context(ctx)
        self._env_fns = list(env_fns)
        self.max_restarts = max_restarts
        self.restarts = 0
        self._inflight = None  # step_async's send-phase death record
        n = len(self._env_fns)
        self._parents = [None] * n
        self._procs = [None] * n
        for i in range(n):
            self._spawn(i)

    def _spawn(self, i: int) -> None:
        parent, child = self._ctx.Pipe()
        proc = self._ctx.Process(
            target=_env_worker, args=(child, self._env_fns[i]),
            daemon=True,
        )
        proc.start()
        child.close()
        self._parents[i] = parent
        self._procs[i] = proc

    def _revive(self, i: int, cause: BaseException) -> Dict:
        # The revival is supervised by the SAME budget: a replacement
        # that dies before answering its first "initial" (deterministic
        # constructor crash, immediate re-OOM) consumes another restart
        # and retries, and exhaustion always raises the documented
        # RuntimeError with the transport error chained.
        while True:
            if self.restarts >= self.max_restarts:
                raise RuntimeError(
                    f"env worker {i} died and the restart budget "
                    f"({self.max_restarts}) is exhausted"
                ) from cause
            self.restarts += 1
            log.warning(
                "env worker %d died (%s); respawning with a fresh env "
                "(restart %d/%d) — its slot emits an episode boundary.",
                i, cause, self.restarts, self.max_restarts,
            )
            old = self._procs[i]
            self._parents[i].close()
            old.kill()
            old.join(timeout=5)
            self._spawn(i)
            try:
                self._parents[i].send(("initial", None))
                return self._parents[i].recv()
            except (BrokenPipeError, EOFError, OSError) as e:
                cause = e

    def __len__(self):
        return len(self._procs)

    def initial(self) -> Dict[str, np.ndarray]:
        # Two-phase like step(): send to every live worker first so all
        # B env resets run concurrently (a serialized send+recv loop
        # would multiply reset latency by the pool size).
        dead = {}
        for i, p in enumerate(self._parents):
            try:
                p.send(("initial", None))
            except (BrokenPipeError, OSError) as e:
                dead[i] = e
        outs = []
        for i, p in enumerate(self._parents):
            if i in dead:
                outs.append(self._revive(i, dead[i]))
                continue
            try:
                outs.append(p.recv())
            except (EOFError, OSError) as e:
                outs.append(self._revive(i, e))
        return _stack(outs)

    def step(self, actions) -> Dict[str, np.ndarray]:
        self.step_async(actions)
        return self.step_wait()

    def step_async(self, actions) -> None:
        """Send phase only: every live worker starts stepping and the
        caller gets control back while the envs run — the overlap window
        the lag-1 pipelined collector uses to materialize the previous
        tick's device results (rollout.py). Send-side deaths are
        recorded and revived in step_wait."""
        if self._inflight is not None:
            raise RuntimeError("step_async called with a step in flight")
        dead = {}
        for i, (p, a) in enumerate(zip(self._parents, actions)):
            try:
                p.send(("step", int(a)))
            except (BrokenPipeError, OSError) as e:
                dead[i] = e
        self._inflight = dead

    def step_wait(self) -> Dict[str, np.ndarray]:
        """Receive phase: blocks for every worker's step result."""
        if self._inflight is None:
            raise RuntimeError("step_wait without step_async")
        dead, self._inflight = self._inflight, None
        outs = []
        for i, p in enumerate(self._parents):
            if i in dead:
                outs.append(self._revive(i, dead[i]))
                continue
            try:
                outs.append(p.recv())
            except (EOFError, OSError) as e:
                outs.append(self._revive(i, e))
        return _stack(outs)

    def close(self):
        for p in self._parents:
            try:
                p.send(("close", None))
                p.recv()
            except (BrokenPipeError, EOFError):
                pass
        for proc in self._procs:
            proc.join(timeout=5)
            if proc.is_alive():
                # Full escalation (terminate -> join -> kill -> join):
                # terminate-without-join strands spawn-context children
                # when SIGTERM lands mid-bootstrap and leaves zombies
                # otherwise — the same reaping contract as polybeast's
                # _reap_servers.
                proc.terminate()
                proc.join(timeout=5)
                if proc.is_alive():
                    proc.kill()
                    proc.join(timeout=5)
