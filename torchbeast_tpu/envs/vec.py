"""Vectorized environment pools: B envs behind one batched step() call.

The actor-parallelism layer (reference: `num_actors` forked processes each
owning one env, monobeast.py:362-381). Here the batching is explicit because
acting is centrally batched on the TPU: the driver calls `pool.step(actions)`
with a `[B]` action vector and gets `[B, ...]`-stacked EnvOutput dicts back.

Two implementations:
- SerialEnvPool: in-process loop — zero IPC, right for cheap/mock envs and
  tests.
- ProcessEnvPool: one OS process per env (spawn context so workers never
  inherit JAX/TPU state), pipes carrying numpy arrays. Equivalent role to the
  reference's actor processes; the heavy C++ shared-memory transport arrives
  with the native runtime.
"""

import multiprocessing as mp
from typing import Callable, Dict, List

import numpy as np

from torchbeast_tpu.envs.environment import Environment


def _stack(outputs: List[Dict]) -> Dict[str, np.ndarray]:
    return {
        k: np.stack([o[k] for o in outputs], axis=0) for k in outputs[0]
    }


class SerialEnvPool:
    def __init__(self, env_fns: List[Callable]):
        self._envs = [Environment(fn()) for fn in env_fns]

    def __len__(self):
        return len(self._envs)

    def initial(self) -> Dict[str, np.ndarray]:
        return _stack([e.initial() for e in self._envs])

    def step(self, actions) -> Dict[str, np.ndarray]:
        return _stack(
            [e.step(int(a)) for e, a in zip(self._envs, actions)]
        )

    def close(self):
        for e in self._envs:
            e.close()


def _env_worker(conn, env_fn):
    """Child process body: owns one Environment, serves initial/step."""
    try:
        env = Environment(env_fn())
        while True:
            cmd, arg = conn.recv()
            if cmd == "initial":
                conn.send(env.initial())
            elif cmd == "step":
                conn.send(env.step(arg))
            elif cmd == "close":
                env.close()
                conn.send(None)
                break
    except (EOFError, KeyboardInterrupt):
        pass


class ProcessEnvPool:
    def __init__(self, env_fns: List[Callable], ctx: str = "spawn"):
        mp_ctx = mp.get_context(ctx)
        self._parents = []
        self._procs = []
        for fn in env_fns:
            parent, child = mp_ctx.Pipe()
            proc = mp_ctx.Process(
                target=_env_worker, args=(child, fn), daemon=True
            )
            proc.start()
            child.close()
            self._parents.append(parent)
            self._procs.append(proc)

    def __len__(self):
        return len(self._procs)

    def initial(self) -> Dict[str, np.ndarray]:
        for p in self._parents:
            p.send(("initial", None))
        return _stack([p.recv() for p in self._parents])

    def step(self, actions) -> Dict[str, np.ndarray]:
        for p, a in zip(self._parents, actions):
            p.send(("step", int(a)))
        return _stack([p.recv() for p in self._parents])

    def close(self):
        for p in self._parents:
            try:
                p.send(("close", None))
                p.recv()
            except (BrokenPipeError, EOFError):
                pass
        for proc in self._procs:
            proc.join(timeout=5)
            if proc.is_alive():
                # Full escalation (terminate -> join -> kill -> join):
                # terminate-without-join strands spawn-context children
                # when SIGTERM lands mid-bootstrap and leaves zombies
                # otherwise — the same reaping contract as polybeast's
                # _reap_servers.
                proc.terminate()
                proc.join(timeout=5)
                if proc.is_alive():
                    proc.kill()
                    proc.join(timeout=5)
