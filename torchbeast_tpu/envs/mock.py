"""Deterministic test/demo environments (no gym dependency).

MockEnv mirrors the reference's trivial Mock env for manual runs
(/root/reference/torchbeast/polybeast_env.py:39-46). CountingEnv is the
deterministic frame-counting env used to verify on-policy bookkeeping
invariants (modeled on the behavior of the reference's agent-state test env,
tests/core_agent_state_env.py: frame counts steps, episode ends every
`episode_length` steps)."""

import numpy as np


def parse_memory_id(name: str):
    """Memory-probe env ids -> corridor length, or None if `name` is not
    a Memory id. "Memory" = default length; "Memory-L41" = length 41.
    ONE grammar shared by the host create_env and the jittable
    create_jax_env so the id set cannot drift between drivers."""
    if name == "Memory":
        return MemoryChainEnv.__init__.__defaults__[0]  # default length
    if name.startswith("Memory-L"):
        suffix = name[len("Memory-L"):]
        if not suffix.isdigit():
            raise ValueError(
                f"Bad Memory id {name!r}: expected Memory-L<n> with a "
                "positive integer length (e.g. Memory-L41)"
            )
        return int(suffix)
    return None


class MockEnv:
    """Fixed-length episodes, constant reward, zero frames."""

    def __init__(self, frame_shape=(84, 84, 4), num_actions=6, episode_length=200):
        self.frame_shape = tuple(frame_shape)
        self.num_actions = num_actions
        self.episode_length = episode_length
        self._t = 0

    def reset(self):
        self._t = 0
        return np.zeros(self.frame_shape, dtype=np.uint8)

    def step(self, action):
        self._t += 1
        done = self._t >= self.episode_length
        frame = np.full(self.frame_shape, self._t % 255, dtype=np.uint8)
        return frame, 1.0, done


class CatchEnv:
    """Host-side (numpy) Catch — same rules as the jittable CatchJax
    (envs/jax_env.py): ball falls rows-1 steps; move the paddle under it;
    +1/-1 at episode end. A real learnable task for end-to-end learning
    tests of the host drivers (Mock/Counting carry no learnable signal)."""

    def __init__(self, rows=10, cols=5, seed=None):
        self.rows, self.cols = rows, cols
        self.num_actions = 3
        # seed=None: each instance draws OS entropy, so parallel actors
        # see independent ball trajectories (pass a seed for determinism).
        self._rng = np.random.default_rng(seed)
        self._ball_row = 0
        self._ball_col = 0
        self._paddle_col = cols // 2

    def _frame(self):
        frame = np.zeros((self.rows, self.cols, 1), np.uint8)
        frame[min(self._ball_row, self.rows - 1), self._ball_col, 0] = 255
        frame[self.rows - 1, self._paddle_col, 0] = 255
        return frame

    def reset(self):
        self._ball_row = 0
        self._ball_col = int(self._rng.integers(0, self.cols))
        self._paddle_col = self.cols // 2
        return self._frame()

    def step(self, action):
        self._paddle_col = int(
            np.clip(self._paddle_col + int(action) - 1, 0, self.cols - 1)
        )
        self._ball_row += 1
        done = self._ball_row >= self.rows - 1
        reward = 0.0
        if done:
            reward = 1.0 if self._paddle_col == self._ball_col else -1.0
        return self._frame(), reward, done


class CountingEnv:
    """Frame value == step index within the episode; done every N steps.

    Frame after reset is all-zero, so tests can assert that boundary steps
    observed by the learner carry reset frames (reference
    core_agent_state_test.py:81-84). The default 48px frame is the smallest
    square the shallow conv trunk accepts, so the driver can run on
    --env Counting too."""

    def __init__(self, frame_shape=(48, 48, 1), num_actions=2, episode_length=5):
        self.frame_shape = tuple(frame_shape)
        self.num_actions = num_actions
        self.episode_length = episode_length
        self._t = 0

    def reset(self):
        self._t = 0
        return np.zeros(self.frame_shape, dtype=np.uint8)

    def step(self, action):
        self._t += 1
        done = self._t >= self.episode_length
        frame = np.full(self.frame_shape, self._t, dtype=np.uint8)
        return frame, float(self._t), done


class MemoryChainEnv:
    """T-maze memory probe: a binary cue is visible ONLY in the reset
    frame, a featureless corridor follows, a distinct QUERY frame marks
    the decision step, and the final action must reproduce the cue
    (+1 / −1). Every pre-decision step demands the `forward` action
    (2) — anything else costs −0.5.

    Why it exists: Catch is solvable reactively, so a feed-forward
    policy learning it proves nothing about the recurrent core. Here
    nothing the decision-step policy can SEE correlates with the cue:
    the query frame is cue-independent, reward before the decision
    depends only on the agent's own compliance, and — the subtle leak —
    the model's last-action input cannot be used as a relay (encode the
    cue in a₀, then copy last action forward to the query). The best
    such relay is ASYMMETRIC: encode cue 0 as FORWARD (penalty-free)
    and only cue 1 as a non-forward action, paying the corridor tax in
    one branch. Its expected return is 1 − (length−1)·0.25 (half the
    episodes relay for free, half pay (length−1)·0.5), versus ≈ 0 for
    honest play (forward corridor, coin-flip at the query). The relay
    is strictly losing only when (length−1)·0.25 > 1, i.e. length ≥ 6
    — hence the constructor floor below; at length 5 the relay ties
    honest play and below that it WINS, breaking the probe. With
    length ≥ 6 a feed-forward policy caps at expected return ≈ 0,
    while a recurrent core that carries the cue across the unroll (the
    machinery the reference's core_agent_state_test pins,
    monobeast.py:599-611) reaches +1. The FF-vs-LSTM gap on this env
    is the direct functional proof that --use_lstm carries memory.
    """

    FORWARD = 2

    def __init__(self, length=6, seed=None):
        if length < 6:
            raise ValueError(
                "length must be >= 6: below that the asymmetric "
                "last-action relay (cue 0 -> FORWARD, cue 1 -> "
                "non-forward) returns 1 - (length-1)*0.25 >= 0 and a "
                "feed-forward policy can match or beat honest play, "
                "voiding the FF-vs-LSTM differential the probe exists "
                "to measure"
            )
        self.length = length
        self.num_actions = 3  # 0/1 = answers, 2 = forward
        # seed=None: OS entropy per instance so parallel actors see
        # independent cue draws (pass a seed for determinism).
        self._rng = np.random.default_rng(seed)
        self._cue = 0
        self._t = 0

    def _frame(self):
        # (4, 1, 1): rows 0/1 = cue indicators, 2 = corridor beacon,
        # 3 = query beacon.
        frame = np.zeros((4, 1, 1), np.uint8)
        if self._t == 0:
            frame[self._cue, 0, 0] = 255
        elif self._t == self.length - 1:
            frame[3, 0, 0] = 255
        else:
            frame[2, 0, 0] = 255
        return frame

    def reset(self):
        self._cue = int(self._rng.integers(0, 2))
        self._t = 0
        return self._frame()

    def step(self, action):
        at_query = self._t == self.length - 1  # action answers the query
        self._t += 1
        done = self._t >= self.length
        if at_query:
            reward = 1.0 if int(action) == self._cue else -1.0
        else:
            reward = 0.0 if int(action) == self.FORWARD else -0.5
        return self._frame(), reward, done
