"""Environment construction.

`create_env(name, ...)` mirrors the reference's `create_env(flags)`
(monobeast.py:638-646, polybeast_env.py:49-58): "Mock"/"Counting" build the
dependency-free test envs, "Catch"/"Memory" the dependency-free LEARNABLE
tasks (Memory requires a recurrent core — see MemoryChainEnv); anything
else is treated as a gymnasium Atari id and gets the DeepMind
preprocessing stack.
"""

from torchbeast_tpu.envs.environment import Environment  # noqa: F401
from torchbeast_tpu.envs.mock import (  # noqa: F401
    CatchEnv,
    CountingEnv,
    MemoryChainEnv,
    MockEnv,
    parse_memory_id,
)


def num_actions_of(env) -> int:
    """Discrete action count of a raw env (our minimal protocol's
    `num_actions` attribute, or a gym(nasium) `action_space.n`)."""
    if hasattr(env, "num_actions"):
        return int(env.num_actions)
    return int(env.action_space.n)


def create_env(name: str, seed=None, **kwargs):
    """`seed=None` (default) keeps the historical behavior: stochastic
    envs draw OS entropy per instance so parallel actors decorrelate.
    A seed makes the instance's draw stream deterministic — the driver
    layer derives per-actor seeds from `--env_seed` so runs reproduce
    while actors STAY decorrelated (seed + actor index)."""
    if name == "Mock":
        return MockEnv(**kwargs)  # deterministic; nothing to seed
    if name == "Counting":
        return CountingEnv(**kwargs)  # deterministic; nothing to seed
    if name == "Catch":
        return CatchEnv(seed=seed, **kwargs)
    # Parameterized corridor ids: "Memory" (default length) or
    # "Memory-L41" (cue 40 steps before the query) — id-encoded like
    # gym's "-v4"-style suffixes so every driver reads them from the
    # one --env flag (parse shared with the jittable twin).
    memory_length = parse_memory_id(name)
    if memory_length is not None:
        return MemoryChainEnv(length=memory_length, seed=seed, **kwargs)
    from torchbeast_tpu.envs.atari import create_atari_env

    return create_atari_env(name, seed=seed, **kwargs)
