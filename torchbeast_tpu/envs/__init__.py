"""Environment construction.

`create_env(name, ...)` mirrors the reference's `create_env(flags)`
(monobeast.py:638-646, polybeast_env.py:49-58): "Mock"/"Counting" build the
dependency-free test envs, "Catch"/"Memory" the dependency-free LEARNABLE
tasks (Memory requires a recurrent core — see MemoryChainEnv); anything
else is treated as a gymnasium Atari id and gets the DeepMind
preprocessing stack.
"""

from torchbeast_tpu.envs.environment import Environment  # noqa: F401
from torchbeast_tpu.envs.mock import (  # noqa: F401
    CatchEnv,
    CountingEnv,
    MemoryChainEnv,
    MockEnv,
)


def num_actions_of(env) -> int:
    """Discrete action count of a raw env (our minimal protocol's
    `num_actions` attribute, or a gym(nasium) `action_space.n`)."""
    if hasattr(env, "num_actions"):
        return int(env.num_actions)
    return int(env.action_space.n)


def create_env(name: str, **kwargs):
    if name == "Mock":
        return MockEnv(**kwargs)
    if name == "Counting":
        return CountingEnv(**kwargs)
    if name == "Catch":
        return CatchEnv(**kwargs)
    if name == "Memory":
        return MemoryChainEnv(**kwargs)
    from torchbeast_tpu.envs.atari import create_atari_env

    return create_atari_env(name, **kwargs)
