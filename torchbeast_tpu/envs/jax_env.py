"""Jittable (on-device) environments for the Anakin trainer.

The reference runs every environment on CPU behind IPC (its only option —
Atari is C++/OpenCV). For envs expressible in JAX, the Podracer "Anakin"
pattern (arXiv:2104.06272) instead steps the env INSIDE the jitted training
program: `lax.scan` over the unroll, vmap over the batch, zero host
round-trips. This module defines the env protocol and a classic benchmark
env (Catch, from bsuite) plus the episode-accounting wrapper that produces
the same EnvOutput fields the learner batch expects (frame, reward, done,
episode_return, episode_step, last_action).

Protocol (functional, gymnax-style):
    env.reset(key)            -> state            (pytree)
    env.step(state, action)   -> (state, frame, reward, done)
    env.num_actions, env.frame_shape
Auto-reset lives in the wrapper so `scan` never branches on done.
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class CatchState(NamedTuple):
    ball_row: jnp.ndarray  # i32
    ball_col: jnp.ndarray  # i32
    paddle_col: jnp.ndarray  # i32
    key: jnp.ndarray


class CatchJax:
    """Catch (bsuite): a ball falls down a rows x cols board; move the
    paddle to be under it. Reward +1 on catch, -1 on miss, at episode end
    (rows - 1 steps). Fully branch-free and jittable."""

    def __init__(self, rows: int = 10, cols: int = 5):
        self.rows = rows
        self.cols = cols
        self.num_actions = 3  # left, stay, right
        self.frame_shape = (rows, cols, 1)

    def reset(self, key) -> CatchState:
        key, sub = jax.random.split(key)
        ball_col = jax.random.randint(sub, (), 0, self.cols)
        return CatchState(
            ball_row=jnp.int32(0),
            ball_col=ball_col.astype(jnp.int32),
            paddle_col=jnp.int32(self.cols // 2),
            key=key,
        )

    def step(self, state: CatchState, action):
        paddle = jnp.clip(
            state.paddle_col + action.astype(jnp.int32) - 1, 0, self.cols - 1
        )
        ball_row = state.ball_row + 1
        done = ball_row >= self.rows - 1
        reward = jnp.where(
            done,
            jnp.where(paddle == state.ball_col, 1.0, -1.0),
            0.0,
        ).astype(jnp.float32)
        new_state = CatchState(
            ball_row=ball_row, ball_col=state.ball_col,
            paddle_col=paddle, key=state.key,
        )
        return new_state, self.observe(new_state), reward, done

    def observe(self, state: CatchState):
        frame = jnp.zeros((self.rows, self.cols), jnp.uint8)
        frame = frame.at[
            jnp.clip(state.ball_row, 0, self.rows - 1), state.ball_col
        ].set(255)
        frame = frame.at[self.rows - 1, state.paddle_col].set(255)
        return frame[..., None]


class MemoryState(NamedTuple):
    cue: jnp.ndarray  # i32 in {0, 1}
    t: jnp.ndarray  # i32 step within the episode
    key: jnp.ndarray


class MemoryChainJax:
    """Jittable twin of envs/mock.py:MemoryChainEnv (same rules, same
    frame layout): cue visible only at t=0, corridor demands the
    `forward` action (−0.5 otherwise, which breaks the last-action
    relay), a distinct query frame at t=length−1, and the query action
    must reproduce the cue (+1/−1). Branch-free; solvable only by a
    recurrent core — the on-device probe for anakin's `--use_lstm`
    state carry (see benchmarks/artifacts/lstm_learning.md)."""

    FORWARD = 2

    def __init__(self, length: int = 6):
        if length < 6:
            # Same floor (and same reason) as MemoryChainEnv: below 6
            # the asymmetric last-action relay (cue 0 -> FORWARD free,
            # cue 1 -> one fully-penalised branch) returns
            # 1-(length-1)*0.25 >= 0, so feed-forward matches honest
            # play and the probe's FF-vs-LSTM differential guarantee
            # is void.
            raise ValueError("length must be >= 6 (see MemoryChainEnv)")
        self.length = length
        self.num_actions = 3  # 0/1 = answers, 2 = forward
        self.frame_shape = (4, 1, 1)

    def reset(self, key) -> MemoryState:
        key, sub = jax.random.split(key)
        cue = jax.random.randint(sub, (), 0, 2)
        return MemoryState(
            cue=cue.astype(jnp.int32), t=jnp.int32(0), key=key
        )

    def observe(self, state: MemoryState):
        # Rows 0/1 = cue indicators (t == 0), 2 = corridor beacon,
        # 3 = query beacon (t == length − 1).
        row = jnp.where(
            state.t == 0,
            state.cue,
            jnp.where(state.t == self.length - 1, 3, 2),
        )
        frame = jnp.zeros((4,), jnp.uint8).at[row].set(255)
        return frame.reshape(self.frame_shape)

    def step(self, state: MemoryState, action):
        action = action.astype(jnp.int32)
        at_query = state.t == self.length - 1
        t = state.t + 1
        done = t >= self.length
        reward = jnp.where(
            at_query,
            jnp.where(action == state.cue, 1.0, -1.0),
            jnp.where(action == self.FORWARD, 0.0, -0.5),
        ).astype(jnp.float32)
        new_state = MemoryState(cue=state.cue, t=t, key=state.key)
        return new_state, self.observe(new_state), reward, done


class AccountedState(NamedTuple):
    env_state: Any
    episode_return: jnp.ndarray
    episode_step: jnp.ndarray


class JaxEnvironment:
    """Episode accounting + auto-reset around a jittable env — the
    on-device analog of envs/environment.py: produces the same EnvOutput
    dict fields with the same semantics (counters reported WITH the done
    step; auto-reset before the next step)."""

    def __init__(self, env):
        self.env = env
        self.num_actions = env.num_actions
        self.frame_shape = env.frame_shape

    def initial(self, key) -> Tuple[AccountedState, dict]:
        env_state = self.env.reset(key)
        out = {
            "frame": self.env.observe(env_state),
            "reward": jnp.float32(0.0),
            "done": jnp.bool_(True),  # boundary-step convention
            "episode_return": jnp.float32(0.0),
            "episode_step": jnp.int32(0),
            "last_action": jnp.int32(0),
        }
        return AccountedState(env_state, jnp.float32(0.0), jnp.int32(0)), out

    def step(self, state: AccountedState, action) -> Tuple[AccountedState, dict]:
        env_state, frame, reward, done = self.env.step(
            state.env_state, action
        )
        episode_return = state.episode_return + reward
        episode_step = state.episode_step + 1

        # Auto-reset: compute the reset branch unconditionally (cheap,
        # branch-free) and select. Counters restart AFTER the done step.
        reset_state = self.env.reset(env_state.key)
        next_env_state = jax.tree_util.tree_map(
            lambda r, c: jnp.where(done, r, c), reset_state, env_state
        )
        frame = jnp.where(done, self.env.observe(reset_state), frame)

        out = {
            "frame": frame,
            "reward": reward,
            "done": done,
            "episode_return": episode_return,
            "episode_step": episode_step,
            "last_action": action.astype(jnp.int32),
        }
        next_state = AccountedState(
            env_state=next_env_state,
            episode_return=jnp.where(done, 0.0, episode_return).astype(
                jnp.float32
            ),
            episode_step=jnp.where(done, 0, episode_step).astype(jnp.int32),
        )
        return next_state, out


_JAX_ENVS = {
    "Catch": CatchJax,
    "Memory": MemoryChainJax,
}


def create_jax_env(name: str, **kwargs) -> JaxEnvironment:
    from torchbeast_tpu.envs.mock import parse_memory_id

    # Same parameterized-corridor ids as the host-side create_env
    # (ONE grammar, envs/mock.py:parse_memory_id), so every driver
    # including anakin reads them from the one --env flag.
    memory_length = parse_memory_id(name)
    if memory_length is not None:
        return JaxEnvironment(MemoryChainJax(length=memory_length, **kwargs))
    try:
        cls = _JAX_ENVS[name]
    except KeyError:
        raise ValueError(
            f"Unknown jittable env {name!r}; available: {sorted(_JAX_ENVS)}"
        ) from None
    return JaxEnvironment(cls(**kwargs))
