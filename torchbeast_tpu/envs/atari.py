"""Atari preprocessing: the DeepMind stack, built on gymnasium's maintained
wrappers instead of hand-vendored baselines code.

The reference vendors ~340 LoC of openai/baselines wrappers
(/root/reference/torchbeast/atari_wrappers.py: NoopReset(30), MaxAndSkip(4),
EpisodicLife, FireReset, WarpFrame 84x84 gray, ClipReward, FrameStack(4),
ImageToPyTorch CHW). gymnasium.wrappers.AtariPreprocessing covers
noop/skip-max/warp/grayscale natively; FrameStackObservation covers the
stack. EpisodicLife and FireReset are not in gymnasium core, so they are
implemented here as gymnasium.Wrapper subclasses. Frames come out HWC uint8
[84, 84, 4] (TPU NHWC layout — no CHW transpose, unlike the reference's
wrap_pytorch).

Both reference drivers use clip_rewards=False (clipping happens in the
learner), frame_stack=True, scale=False (monobeast.py:638-646,
polybeast_env.py:49-58) — same defaults here.

gymnasium is a baked dependency; ale_py (the Atari ROMs/emulator) is gated
with a clear error when missing.
"""

import gymnasium
import numpy as np


class EpisodicLifeWrapper(gymnasium.Wrapper):
    """End episodes on life loss, but only truly reset when the game is
    over. Same behavior as the reference's EpisodicLifeEnv
    (atari_wrappers.py:84-118)."""

    def __init__(self, env):
        super().__init__(env)
        self.lives = 0
        self.was_real_done = True

    def reset(self, **kwargs):
        if self.was_real_done:
            obs, info = self.env.reset(**kwargs)
        else:
            # no-op step to advance from the life-lost state
            obs, _, terminated, truncated, info = self.env.step(0)
            if terminated or truncated:
                obs, info = self.env.reset(**kwargs)
        self.lives = self.env.unwrapped.ale.lives()
        return obs, info

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        self.was_real_done = terminated or truncated
        lives = self.env.unwrapped.ale.lives()
        if 0 < lives < self.lives:
            terminated = True
        self.lives = lives
        return obs, reward, terminated, truncated, info


class FireResetWrapper(gymnasium.Wrapper):
    """Press FIRE after reset for envs that need it (reference
    atari_wrappers.py:64-82)."""

    def reset(self, **kwargs):
        obs, info = self.env.reset(**kwargs)
        obs, _, terminated, truncated, info = self.env.step(1)
        if terminated or truncated:
            obs, info = self.env.reset(**kwargs)
        obs, _, terminated, truncated, info = self.env.step(2)
        if terminated or truncated:
            obs, info = self.env.reset(**kwargs)
        return obs, info


class StackToHWC(gymnasium.ObservationWrapper):
    """FrameStackObservation yields [stack, H, W]; models want [H, W, stack]."""

    def __init__(self, env):
        super().__init__(env)
        old = env.observation_space
        self.observation_space = gymnasium.spaces.Box(
            low=np.moveaxis(old.low, 0, -1),
            high=np.moveaxis(old.high, 0, -1),
            dtype=old.dtype,
        )

    def observation(self, obs):
        return np.moveaxis(np.asarray(obs), 0, -1)


def create_atari_env(
    env_name: str,
    *,
    frame_stack: int = 4,
    episodic_life: bool = True,
    noop_max: int = 30,
    seed=None,
):
    """Build the full preprocessing stack -> HWC uint8 [84, 84, frame_stack]."""
    if env_name.startswith("tbt/"):
        # Registers the dependency-free ALE-compatible cabinet ids.
        import torchbeast_tpu.envs.miniatari  # noqa: F401
    else:
        try:
            import ale_py

            gymnasium.register_envs(ale_py)
        except ImportError as e:
            raise ImportError(
                f"Env {env_name!r} needs ale_py; install it, or use "
                "--env tbt/MiniAtari-v0 (dependency-free Atari-like, same "
                "preprocessing stack) or --env Mock."
            ) from e

    env = gymnasium.make(env_name, frameskip=1)  # AtariPreprocessing skips
    env = gymnasium.wrappers.AtariPreprocessing(
        env,
        noop_max=noop_max,
        frame_skip=4,
        screen_size=84,
        grayscale_obs=True,
        scale_obs=False,
    )
    if episodic_life:
        env = EpisodicLifeWrapper(env)
    if "FIRE" in env.unwrapped.get_action_meanings():
        env = FireResetWrapper(env)
    env = gymnasium.wrappers.FrameStackObservation(env, stack_size=frame_stack)
    env = StackToHWC(env)
    if seed is not None:
        # Gymnasium seeds at reset; seeding once here pins np_random's
        # stream, and the subsequent unseeded resets (Environment's
        # initial/auto-reset) continue it deterministically.
        env.reset(seed=int(seed))
    return env
