"""MiniAtari: a dependency-free, ALE-compatible game cabinet.

The reference's raison d'être is Atari IMPALA, but the Atari emulator
(ale_py) is an optional heavyweight dependency. This module provides a
self-contained game that exposes EXACTLY the surface the DeepMind
preprocessing stack consumes — `_frameskip`, `get_action_meanings()`, and
an `ale` object with `lives()` / `getScreenRGB(buf)` /
`getScreenGrayscale(buf)` (in-place, like the real ALE) — so the full
`create_atari_env` stack (gymnasium AtariPreprocessing noop/skip/max/warp,
EpisodicLife, FireReset, FrameStack; reference atari_wrappers.py:23-336)
executes and trains without ale_py.

The game is a Pong-serve catcher at native Atari resolution (210x160 RGB):
a ball drops from the top with horizontal drift, the bottom paddle must
catch it. +1 per catch (auto-serves the next ball), -1 and a lost life per
miss; 5 lives; FIRE serves the first ball of an episode (exercising the
FireReset wrapper — with an auto-serve failsafe so NOOP policies are not
stuck). Random play returns ~-4; a tracking policy catches every ball, so
learning shows up quickly and unambiguously in mean_episode_return.

Registered as "tbt/MiniAtari-v0"; `create_env("tbt/MiniAtari-v0")` builds
the full preprocessing stack on it.
"""

import gymnasium
import numpy as np

SCREEN_H, SCREEN_W = 210, 160
PADDLE_W, PADDLE_H = 24, 4
PADDLE_Y = 192  # top of the paddle
PADDLE_SPEED = 6
BALL_W, BALL_H = 4, 4
BALL_VY = 3
SERVE_Y = 20
START_LIVES = 5
AUTO_SERVE_AFTER = 60  # frames without a ball before it serves itself

_BG_RGB = (0, 0, 40)
_BALL_RGB = (236, 236, 236)
_PADDLE_RGB = (213, 130, 74)


def _luma(rgb):
    r, g, b = rgb
    return int(round(0.299 * r + 0.587 * g + 0.114 * b))


_BG_GRAY = _luma(_BG_RGB)
_BALL_GRAY = _luma(_BALL_RGB)
_PADDLE_GRAY = _luma(_PADDLE_RGB)


class _MiniALE:
    """The 'emulator': game state + in-place screen getters, mirroring the
    ALE interface AtariPreprocessing binds to (atari_preprocessing.py:
    151-184 of gymnasium)."""

    def __init__(self):
        self._rng = np.random.default_rng(0)
        self.reset(self._rng)

    def reset(self, rng):
        self._rng = rng
        self._lives = START_LIVES
        self.paddle_x = (SCREEN_W - PADDLE_W) // 2
        self.in_play = False
        self.idle_frames = 0
        self.ball_x = 0.0
        self.ball_y = 0.0
        self.ball_vx = 0
        self.game_over = False

    def lives(self) -> int:
        return self._lives

    def _serve(self):
        self.ball_x = float(self._rng.integers(0, SCREEN_W - BALL_W))
        self.ball_y = float(SERVE_Y)
        self.ball_vx = int(self._rng.integers(-2, 3))
        self.in_play = True
        self.idle_frames = 0

    def act(self, action: int):
        """One raw frame. Returns (reward, terminated)."""
        if self.game_over:
            return 0.0, True
        if action == 2:  # RIGHT
            self.paddle_x = min(SCREEN_W - PADDLE_W, self.paddle_x + PADDLE_SPEED)
        elif action == 3:  # LEFT
            self.paddle_x = max(0, self.paddle_x - PADDLE_SPEED)
        elif action == 1 and not self.in_play:  # FIRE serves
            self._serve()

        reward = 0.0
        if not self.in_play:
            self.idle_frames += 1
            if self.idle_frames >= AUTO_SERVE_AFTER:
                self._serve()
            return reward, False

        self.ball_y += BALL_VY
        self.ball_x += self.ball_vx
        if self.ball_x < 0:
            self.ball_x = -self.ball_x
            self.ball_vx = -self.ball_vx
        elif self.ball_x > SCREEN_W - BALL_W:
            self.ball_x = 2 * (SCREEN_W - BALL_W) - self.ball_x
            self.ball_vx = -self.ball_vx

        if self.ball_y + BALL_H >= PADDLE_Y:
            caught = (
                self.ball_x + BALL_W > self.paddle_x
                and self.ball_x < self.paddle_x + PADDLE_W
            )
            if caught:
                reward = 1.0
                self._serve()  # next ball immediately, dense signal
            else:
                reward = -1.0
                self._lives -= 1
                self.in_play = False  # FIRE (or auto-serve) restarts play
                if self._lives <= 0:
                    self.game_over = True
                    return reward, True
        return reward, False

    # -- screen getters (ALE fills caller-provided buffers in place) --

    def _draw(self, buf, bg, ball, paddle):
        buf[...] = bg
        buf[PADDLE_Y : PADDLE_Y + PADDLE_H,
            self.paddle_x : self.paddle_x + PADDLE_W] = paddle
        if self.in_play:
            y, x = int(self.ball_y), int(self.ball_x)
            buf[max(0, y) : y + BALL_H, max(0, x) : x + BALL_W] = ball

    def getScreenRGB(self, buf):  # noqa: N802 — ALE spelling
        self._draw(buf, _BG_RGB, _BALL_RGB, _PADDLE_RGB)

    def getScreenGrayscale(self, buf):  # noqa: N802 — ALE spelling
        self._draw(buf, _BG_GRAY, _BALL_GRAY, _PADDLE_GRAY)


class MiniAtariEnv(gymnasium.Env):
    """gymnasium face of the cabinet (raw frames; the preprocessing stack
    goes on top, exactly as with a real ALE env)."""

    metadata = {"render_modes": ["rgb_array"]}

    def __init__(self, frameskip: int = 1, render_mode=None,
                 max_frames: int = 20000):
        if frameskip != 1:
            raise ValueError(
                "MiniAtariEnv is always frameskip=1; AtariPreprocessing "
                "does the skipping (pass frameskip=1, as create_atari_env "
                "does)."
            )
        self._frameskip = frameskip
        self.render_mode = render_mode
        self.max_frames = max_frames
        self.ale = _MiniALE()
        self._frame = 0
        self.action_space = gymnasium.spaces.Discrete(4)
        self.observation_space = gymnasium.spaces.Box(
            low=0, high=255, shape=(SCREEN_H, SCREEN_W, 3), dtype=np.uint8
        )

    def get_action_meanings(self):
        return ["NOOP", "FIRE", "RIGHT", "LEFT"]

    def _rgb(self):
        buf = np.empty((SCREEN_H, SCREEN_W, 3), np.uint8)
        self.ale.getScreenRGB(buf)
        return buf

    def reset(self, *, seed=None, options=None):
        super().reset(seed=seed)
        self.ale.reset(self.np_random)
        self._frame = 0
        return self._rgb(), {}

    def step(self, action):
        reward, terminated = self.ale.act(int(action))
        self._frame += 1
        truncated = self._frame >= self.max_frames
        return self._rgb(), reward, terminated, truncated, {}

    def render(self):
        return self._rgb()


gymnasium.register(
    id="tbt/MiniAtari-v0",
    entry_point=MiniAtariEnv,
)
