"""Nested-structure ("nest") utilities over JAX pytrees.

The reference ships a standalone C++ pybind11 package `nest`
(/root/reference/nest/nest/nest.h:34-325, nest_pybind.cc:43-80) because torch
had no pytree story. JAX does: `jax.tree_util` is the native, registered-
everywhere equivalent. This module provides the reference's Python API surface
(`map`, `map_many`, `map_many2`, `flatten`, `pack_as`, `front`) as thin,
idiomatic wrappers over pytrees.

One deliberate semantic divergence: JAX pytrees traverse dict keys in
SORTED order, while the reference's C++ nest uses std::map (also sorted) but
its Python dicts were effectively insertion-ordered in user code. Here
`flatten`/`pack_as`/`front` follow pytree (sorted-key) order; any parallel
sequence you zip with `flatten(d)` must use the same order — use
`flatten`/`pack_as` round-trips rather than hand-built orderings.

The C++ runtime (under csrc/, built in a later stage) keeps its own Nest<T>
for carrying arrays through the native layers, matching reference component
N1 (SURVEY.md §2.1).
"""

from typing import Any, Callable, List, Sequence

import jax


def map(fn: Callable[[Any], Any], nest: Any) -> Any:  # noqa: A001
    """Apply fn to every leaf, preserving structure (nest_pybind.cc:44)."""
    return jax.tree_util.tree_map(fn, nest)


def map_many(fn: Callable[..., Any], *nests: Any) -> Any:
    """Apply fn(leaf0, leaf1, ...) across structurally-equal nests
    (nest_pybind.cc:45-56)."""
    if not nests:
        raise ValueError("map_many requires at least one nest")
    return jax.tree_util.tree_map(fn, nests[0], *nests[1:])


def map_many2(fn: Callable[[Any, Any], Any], nest1: Any, nest2: Any) -> Any:
    """Binary variant with the reference's name (nest_pybind.cc:57-67)."""
    return jax.tree_util.tree_map(fn, nest1, nest2)


def flatten(nest: Any) -> List[Any]:
    """Depth-first list of leaves (nest.h:135-158)."""
    return jax.tree_util.tree_leaves(nest)


def pack_as(nest: Any, flat: Sequence[Any]) -> Any:
    """Inverse of flatten against a template structure (nest.h:160-194)."""
    treedef = jax.tree_util.tree_structure(nest)
    flat = list(flat)
    if treedef.num_leaves != len(flat):
        raise ValueError(
            f"Structure had {treedef.num_leaves} leaves, but {len(flat)} "
            "values were given to pack_as"
        )
    return jax.tree_util.tree_unflatten(treedef, flat)


def front(nest: Any) -> Any:
    """First leaf in depth-first order (nest.h:74-95)."""
    leaves = jax.tree_util.tree_leaves(nest)
    if not leaves:
        raise ValueError("front() called on empty nest")
    return leaves[0]
