"""Rollout collection with the reference's on-policy bookkeeping invariants.

This replaces the reference's per-actor unroll loop (monobeast.py:128-191 and
the C++ ActorPool hot loop, actorpool.cc:408-450) with a single vectorized
collector: one batched policy call per env step for all B envs at once —
the TPU-friendly formulation (one big `[1, B]` forward instead of B tiny
ones).

Invariants preserved exactly (these are what the reference's agent-state
integration test pins down, SURVEY.md §4):
- **Overlap-by-one**: slot 0 of rollout k+1 == slot T of rollout k (both env
  and agent sides).
- **Pairing**: the agent output stored at slot i was computed from the env
  output at slot i-1 (slot 0's agent output is never consumed by the
  learner, which time-shifts it away).
- **Agent-state carry**: `initial_agent_state` returned with a rollout is
  the recurrent state entering the rollout's first policy call; state is
  carried across rollouts and reset inside the model wherever done is set.

Two schedules over the SAME data flow:

- `RolloutCollector` (synchronous): materializes every policy result on
  host before stepping envs — the full AgentOutput (and, with a naive
  policy fn, the recurrent state) crosses the host boundary every step.
- `PipelinedRolloutCollector` (lag-1): per env step, ONLY the action is
  fetched (one small explicit device_get); policy logits/baseline stay on
  device and the host materializes tick t-1's results while the envs step
  tick t (the pool's step_async/step_wait window), one dispatch behind
  the device — the same one-deep pipeline runtime/inference.py uses for
  batched replies. Agent state never crosses at all: it flows device →
  device between policy calls, and the learner consumes the on-device
  `initial_agent_state` directly (tests/test_state_table.py pins the
  zero-host-round-trips property with jax.transfer_guard). Batches are
  BIT-IDENTICAL to the synchronous collector's — the lag is in when the
  host *retrieves* results, never in what the policy saw — so every
  invariant above holds unchanged (test_rollout.py runs both).
"""

from typing import Any, Callable, Dict, List, Tuple

import jax
import numpy as np

from torchbeast_tpu.types import AgentOutput

# policy(env_output [B,...] dict, agent_state) -> (AgentOutput [B,...], state)
PolicyFn = Callable[[Dict[str, np.ndarray], Any], Tuple[AgentOutput, Any]]


def _build_batch(
    env_steps: List[Dict[str, np.ndarray]], agent_steps: List[AgentOutput]
) -> Dict[str, np.ndarray]:
    """Stack T+1 env dicts + host AgentOutputs into the [T+1, B] batch."""
    batch = {
        k: np.stack([s[k] for s in env_steps], axis=0) for k in env_steps[0]
    }
    batch["action"] = np.stack([np.asarray(a.action) for a in agent_steps])
    batch["policy_logits"] = np.stack(
        [np.asarray(a.policy_logits) for a in agent_steps]
    )
    batch["baseline"] = np.stack(
        [np.asarray(a.baseline) for a in agent_steps]
    )
    return batch


class RolloutCollector:
    def __init__(self, pool, policy: PolicyFn, initial_agent_state, unroll_length: int):
        self._pool = pool
        self._policy = policy
        self._unroll_length = unroll_length
        self._agent_state = initial_agent_state

        self._pending_env = pool.initial()
        # Prime the boundary agent output; the state advance is discarded —
        # the first in-rollout policy call re-consumes this env output with
        # the state advancing for real (reference monobeast.py:145-147).
        self._pending_agent, _ = policy(self._pending_env, self._agent_state)

    def collect(self) -> Tuple[Dict[str, np.ndarray], Any]:
        """Run one unroll; return (batch [T+1, B, ...], initial_agent_state).

        The batch dict carries both env fields (frame, reward, done,
        episode_return, episode_step, last_action) and behavior-agent fields
        (action, policy_logits, baseline).
        """
        T = self._unroll_length
        initial_agent_state = self._agent_state

        env_steps = [self._pending_env]
        agent_steps = [self._pending_agent]
        for _ in range(T):
            agent_out, self._agent_state = self._policy(
                self._pending_env, self._agent_state
            )
            self._pending_env = self._pool.step(np.asarray(agent_out.action))
            env_steps.append(self._pending_env)
            agent_steps.append(agent_out)
        self._pending_agent = agent_steps[-1]

        return _build_batch(env_steps, agent_steps), initial_agent_state


class PipelinedRolloutCollector:
    """Lag-1 pipelined collector (see module docstring).

    Per tick: dispatch the policy call, fetch ONLY its action (explicit
    device_get), hand the actions to the pool's async send phase, then —
    while the env workers step — materialize the PREVIOUS tick's full
    AgentOutput. The device result for tick t reaches the host at tick
    t+1 (or in the single batched end-of-unroll fetch for the last tick):
    host retrieval runs exactly one dispatch behind.

    The policy must return its AgentOutput/state WITHOUT materializing
    them (no device_get inside — monobeast wires this with
    `pipelined=True`). Pools without step_async (e.g. a bare object with
    only step()) degrade to the synchronous phase order, same results.
    """

    def __init__(self, pool, policy: PolicyFn, initial_agent_state,
                 unroll_length: int):
        self._pool = pool
        self._policy = policy
        self._unroll_length = unroll_length
        self._agent_state = initial_agent_state
        self._split_step = hasattr(pool, "step_async")

        self._pending_env = pool.initial()
        # Same priming contract as the sync collector; kept on device —
        # it is materialized lazily by the first collect()'s bulk fetch.
        self._pending_agent, _ = policy(self._pending_env, self._agent_state)

    def collect(self) -> Tuple[Dict[str, np.ndarray], Any]:
        """One unroll; identical contract/results to RolloutCollector.

        `initial_agent_state` is returned as-is (on device when the
        policy keeps it there) — the learner consumes it without a host
        round trip.
        """
        T = self._unroll_length
        initial_agent_state = self._agent_state

        env_steps = [self._pending_env]
        # Mixed host/device AgentOutputs; device entries are materialized
        # one tick behind (or in the final bulk fetch).
        agent_steps: List[AgentOutput] = [self._pending_agent]
        for _ in range(T):
            agent_out, self._agent_state = self._policy(
                self._pending_env, self._agent_state
            )
            # The action is the only per-step device→host fetch on this
            # path (explicit: np.asarray would be an implicit transfer
            # under jax.transfer_guard).
            action = np.asarray(jax.device_get(agent_out.action))
            if self._split_step:
                self._pool.step_async(action)
                # Lag-1 window: envs are stepping; materialize the
                # previous tick's outputs behind them.
                agent_steps[-1] = jax.device_get(agent_steps[-1])
                self._pending_env = self._pool.step_wait()
            else:
                self._pending_env = self._pool.step(action)
            env_steps.append(self._pending_env)
            agent_steps.append(agent_out)

        # One batched fetch for whatever is still on device (always the
        # last tick; every tick when the pool had no split step phase).
        agent_steps = jax.device_get(agent_steps)
        self._pending_agent = agent_steps[-1]

        return _build_batch(env_steps, agent_steps), initial_agent_state
