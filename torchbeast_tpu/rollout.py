"""Rollout collection with the reference's on-policy bookkeeping invariants.

This replaces the reference's per-actor unroll loop (monobeast.py:128-191 and
the C++ ActorPool hot loop, actorpool.cc:408-450) with a single vectorized
collector: one batched policy call per env step for all B envs at once —
the TPU-friendly formulation (one big `[1, B]` forward instead of B tiny
ones).

Invariants preserved exactly (these are what the reference's agent-state
integration test pins down, SURVEY.md §4):
- **Overlap-by-one**: slot 0 of rollout k+1 == slot T of rollout k (both env
  and agent sides).
- **Pairing**: the agent output stored at slot i was computed from the env
  output at slot i-1 (slot 0's agent output is never consumed by the
  learner, which time-shifts it away).
- **Agent-state carry**: `initial_agent_state` returned with a rollout is
  the recurrent state entering the rollout's first policy call; state is
  carried across rollouts and reset inside the model wherever done is set.
"""

from typing import Any, Callable, Dict, Tuple

import numpy as np

from torchbeast_tpu.types import AgentOutput

# policy(env_output [B,...] dict, agent_state) -> (AgentOutput [B,...], state)
PolicyFn = Callable[[Dict[str, np.ndarray], Any], Tuple[AgentOutput, Any]]


class RolloutCollector:
    def __init__(self, pool, policy: PolicyFn, initial_agent_state, unroll_length: int):
        self._pool = pool
        self._policy = policy
        self._unroll_length = unroll_length
        self._agent_state = initial_agent_state

        self._pending_env = pool.initial()
        # Prime the boundary agent output; the state advance is discarded —
        # the first in-rollout policy call re-consumes this env output with
        # the state advancing for real (reference monobeast.py:145-147).
        self._pending_agent, _ = policy(self._pending_env, self._agent_state)

    def collect(self) -> Tuple[Dict[str, np.ndarray], Any]:
        """Run one unroll; return (batch [T+1, B, ...], initial_agent_state).

        The batch dict carries both env fields (frame, reward, done,
        episode_return, episode_step, last_action) and behavior-agent fields
        (action, policy_logits, baseline).
        """
        T = self._unroll_length
        initial_agent_state = self._agent_state

        env_steps = [self._pending_env]
        agent_steps = [self._pending_agent]
        for _ in range(T):
            agent_out, self._agent_state = self._policy(
                self._pending_env, self._agent_state
            )
            self._pending_env = self._pool.step(np.asarray(agent_out.action))
            env_steps.append(self._pending_env)
            agent_steps.append(agent_out)
        self._pending_agent = agent_steps[-1]

        batch = {
            k: np.stack([s[k] for s in env_steps], axis=0)
            for k in env_steps[0]
        }
        batch["action"] = np.stack([np.asarray(a.action) for a in agent_steps])
        batch["policy_logits"] = np.stack(
            [np.asarray(a.policy_logits) for a in agent_steps]
        )
        batch["baseline"] = np.stack(
            [np.asarray(a.baseline) for a in agent_steps]
        )
        return batch, initial_agent_state
