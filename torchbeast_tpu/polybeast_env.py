"""Env-server process group driver (the reference's polybeast_env.py role,
/root/reference/torchbeast/polybeast_env.py:61-89): spawn `num_servers`
processes, each serving environments on `{pipes_basename}.{i}` over the
framed-socket protocol.

Run:  python -m torchbeast_tpu.polybeast_env --num_servers 4 --env Mock
"""

import argparse
import functools
import itertools
import logging
import multiprocessing as mp
import time

logging.basicConfig(
    format=(
        "[%(levelname)s:%(process)d %(module)s:%(lineno)d %(asctime)s] "
        "%(message)s"
    ),
    level=logging.INFO,
)
log = logging.getLogger("torchbeast_tpu.polybeast_env")


def make_parser():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--pipes_basename", default="unix:/tmp/torchbeast_tpu",
                        help="Basename for the env-server addresses "
                             "(unix:/path or host:baseport).")
    parser.add_argument("--num_servers", type=int, default=4)
    parser.add_argument("--env", type=str, default="PongNoFrameskip-v4",
                        help="Gym environment (or Mock / Counting).")
    parser.add_argument("--env_seed", type=int, default=None,
                        help="Base seed for stochastic envs. Server i "
                             "seeds its streams from env_seed + i*1000 "
                             "+ stream index: every env instance draws a "
                             "distinct deterministic stream. Default: OS "
                             "entropy per env.")
    parser.add_argument("--native_server", action="store_true",
                        help="Serve with the C++ EnvServer (_tbt_core): "
                             "socket I/O and wire codec run GIL-free, the "
                             "GIL is taken only around env calls (the "
                             "reference's rpcenv.cc embedding).")
    return parser


def server_address(pipes_basename: str, index: int) -> str:
    """unix:/tmp/x -> unix:/tmp/x.{i};  host:port -> host:{port+i}."""
    if pipes_basename.startswith("unix:"):
        return f"{pipes_basename}.{index}"
    host, _, port = pipes_basename.rpartition(":")
    return f"{host}:{int(port) + index}"


def host_scoped_basename(pipes_basename: str, process_id: int,
                         num_servers: int) -> str:
    """Multi-host fan-out: each learner host gets its own address range so
    its actors connect to its OWN env servers (the reference's per-machine
    topology, polybeast_learner.py:436-444). unix paths get a -h{pid}
    suffix; host:port bases step by num_servers per host."""
    if process_id == 0:
        return pipes_basename
    if pipes_basename.startswith("unix:"):
        return f"{pipes_basename}-h{process_id}"
    host, _, port = pipes_basename.rpartition(":")
    return f"{host}:{int(port) + process_id * num_servers}"


def _serve(env_name: str, address: str, native: bool = False,
           seed_base=None):
    # Child process body. Import here: workers must never inherit JAX state.
    from torchbeast_tpu.envs import create_env

    if seed_base is None:
        env_init = functools.partial(create_env, env_name)
    else:
        # Fresh env per actor stream (both server impls call env_init
        # once per connection): stream s draws seed_base + s. The
        # counter is GIL-guarded — the native server, too, invokes
        # env_init holding the GIL. Reproducible seed SET; which stream
        # gets which seed follows connection order.
        counter = itertools.count()

        def env_init():
            return create_env(env_name, seed=seed_base + next(counter))
    if native:
        from torchbeast_tpu.runtime.native import import_native

        core = import_native()
        if core is None:
            raise RuntimeError(
                "--native_server requested but _tbt_core is not built; "
                "run scripts/build_native.sh"
            )
        core.EnvServer(env_init, address).run()
        return
    from torchbeast_tpu.runtime.env_server import EnvServer

    EnvServer(env_init, address).run()


def start_servers(flags, ctx_name: str = "spawn", pipes_basename=None,
                  env_seed=None):
    basename = pipes_basename or flags.pipes_basename
    native = getattr(flags, "native_server", False)
    if env_seed is None:
        env_seed = getattr(flags, "env_seed", None)
    ctx = mp.get_context(ctx_name)
    processes = []
    for i in range(flags.num_servers):
        address = server_address(basename, i)
        seed_base = None if env_seed is None else env_seed + i * 1000
        p = ctx.Process(
            target=_serve, args=(flags.env, address, native, seed_base),
            daemon=True,
        )
        p.start()
        processes.append(p)
    log.info("Starting %d env servers on %s", len(processes),
             flags.pipes_basename)
    return processes


def main(flags):
    processes = start_servers(flags)
    try:
        while True:
            time.sleep(10)
            for i, p in enumerate(processes):
                if not p.is_alive():
                    log.error("Env server %d died (exit %s)", i, p.exitcode)
    except KeyboardInterrupt:
        pass
    finally:
        for p in processes:
            p.terminate()


def cli():
    main(make_parser().parse_args())


if __name__ == "__main__":
    cli()
