"""Env-server process group driver (the reference's polybeast_env.py role,
/root/reference/torchbeast/polybeast_env.py:61-89): spawn `num_servers`
processes, each serving environments on `{pipes_basename}.{i}` over the
framed-socket protocol.

Run:  python -m torchbeast_tpu.polybeast_env --num_servers 4 --env Mock
"""

import argparse
import functools
import itertools
import logging
import multiprocessing as mp
import threading
import time

from torchbeast_tpu import telemetry
from torchbeast_tpu.resilience.backoff import Backoff

log = logging.getLogger("torchbeast_tpu.polybeast_env")


def _configure_logging():
    """Called from main(), NOT at import: importing this module (as the
    learner driver and every test does) must not mutate global logging
    state."""
    logging.basicConfig(
        format=(
            "[%(levelname)s:%(process)d %(module)s:%(lineno)d "
            "%(asctime)s] %(message)s"
        ),
        level=logging.INFO,
    )


def make_parser():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--pipes_basename", default="unix:/tmp/torchbeast_tpu",
                        help="Basename for the env-server addresses "
                             "(unix:/path, host:baseport, or shm:/path "
                             "for shared-memory rings when the servers "
                             "are co-located with the learner host).")
    parser.add_argument("--num_servers", type=int, default=4)
    parser.add_argument("--env", type=str, default="PongNoFrameskip-v4",
                        help="Gym environment (or Mock / Counting).")
    parser.add_argument("--env_seed", type=int, default=None,
                        help="Base seed for stochastic envs. Server i "
                             "seeds its streams from env_seed + i*1000 "
                             "+ stream index: every env instance draws a "
                             "distinct deterministic stream. Default: OS "
                             "entropy per env.")
    parser.add_argument("--max_server_restarts", type=int, default=10,
                        help="Supervision budget: dead env servers are "
                             "respawned on their address up to this many "
                             "times per group (actors bridge the gap "
                             "with their reconnect budget). 0 disables "
                             "restarts.")
    parser.add_argument("--native_server", action="store_true",
                        help="Serve with the C++ EnvServer (_tbt_core): "
                             "socket I/O and wire codec run GIL-free, the "
                             "GIL is taken only around env calls (the "
                             "reference's rpcenv.cc embedding).")
    return parser


def server_address(pipes_basename: str, index: int) -> str:
    """unix:/tmp/x and shm:/tmp/x -> {base}.{i};  host:port ->
    host:{port+i}."""
    if pipes_basename.startswith(("unix:", "shm:")):
        return f"{pipes_basename}.{index}"
    host, _, port = pipes_basename.rpartition(":")
    return f"{host}:{int(port) + index}"


def host_scoped_basename(pipes_basename: str, process_id: int,
                         num_servers: int) -> str:
    """Multi-host fan-out: each learner host gets its own address range so
    its actors connect to its OWN env servers (the reference's per-machine
    topology, polybeast_learner.py:436-444). unix paths get a -h{pid}
    suffix; host:port bases step by num_servers per host."""
    if process_id == 0:
        return pipes_basename
    if pipes_basename.startswith(("unix:", "shm:")):
        return f"{pipes_basename}-h{process_id}"
    host, _, port = pipes_basename.rpartition(":")
    return f"{host}:{int(port) + process_id * num_servers}"


def _serve(env_name: str, address: str, native: bool = False,
           seed_base=None):
    # Child process body. Spawn-context children re-import this module
    # but never run main(), so the child configures its own logging
    # (INFO lines like "EnvServer listening" would otherwise be lost
    # now that import no longer calls basicConfig).
    _configure_logging()
    # SIGTERM (reap_group's terminate, a k8s preemption) must run this
    # child's teardown — for shm servers that is the owner-side ring
    # unlink sweep (EnvServer.stop). The default handler kills the
    # process without finally blocks, stranding /dev/shm segments.
    from torchbeast_tpu.utils import install_preemption_handler

    install_preemption_handler()
    # Import here: workers must never inherit JAX state.
    from torchbeast_tpu.envs import create_env

    if seed_base is None:
        env_init = functools.partial(create_env, env_name)
    else:
        # Fresh env per actor stream (both server impls call env_init
        # once per connection): stream s draws seed_base + s. The
        # counter is GIL-guarded — the native server, too, invokes
        # env_init holding the GIL. Reproducible seed SET; which stream
        # gets which seed follows connection order.
        counter = itertools.count()

        def env_init():
            return create_env(env_name, seed=seed_base + next(counter))
    if native:
        from torchbeast_tpu.runtime.native import import_native

        core = import_native()
        if core is None:
            raise RuntimeError(
                "--native_server requested but _tbt_core is not built; "
                "run scripts/build_native.sh"
            )
        core.EnvServer(env_init, address).run()
        return
    from torchbeast_tpu.runtime.env_server import EnvServer

    server = EnvServer(env_init, address)
    try:
        server.run()
    except KeyboardInterrupt:
        log.info("Env server on %s preempted; cleaning up.", address)
    finally:
        # stop() severs live streams and runs the owner-side shm
        # unlink sweep — the difference between a preempted shm server
        # and a /dev/shm leak.
        server.stop()


def reap_group(procs):
    """Terminate, join (bounded), then kill a spawned env-server group.
    Terminate-without-join strands spawn-context children when SIGTERM
    lands mid-bootstrap (observed: orphaned `spawn_main` processes after
    validation-failure runs) and leaves zombies otherwise."""
    for p in procs:
        p.terminate()
    for p in procs:
        p.join(timeout=5)
        if p.is_alive():
            p.kill()
            p.join(timeout=5)


class ServerSupervisor:
    """Owns an env-server process group and restarts members that die.

    The actor side has elastic reconnects (ActorPool's max_reconnects
    budget, runtime/actor_pool.py); this is the missing other half —
    someone to bring a dead server BACK. A member is respawned on its
    original address with its original seed base, so in-flight actors
    resume through their reconnect budget instead of exhausting it
    against a dead socket. `max_restarts` (per group, cumulative) caps
    crash-looping a deterministically broken env. The reference has no
    supervision at all: its env driver only LOGS a death
    (/root/reference/torchbeast/polybeast_env.py:61-75 serve loop; the
    gRPC server dying takes the slot down for good).
    """

    def __init__(self, flags, ctx_name: str = "spawn",
                 pipes_basename=None, env_seed=None, max_restarts=10,
                 poll_interval_s=1.0, backoff_factory=None,
                 stable_s=30.0):
        self._env_name = flags.env
        self._native = getattr(flags, "native_server", False)
        self._basename = pipes_basename or flags.pipes_basename
        if env_seed is None:
            env_seed = getattr(flags, "env_seed", None)
        self._env_seed = env_seed
        self._ctx = mp.get_context(ctx_name)
        self.max_restarts = max_restarts
        self.restarts = 0
        self._poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread = None
        self._budget_logged = set()  # indices already error-logged
        # Jittered exponential backoff per slot: a crash-looping env
        # must not be respawned every poll tick (and N servers dying
        # together must not restart in lockstep). A member that stayed
        # up for `stable_s` earns its slot's backoff reset.
        self._backoff_factory = backoff_factory or (
            lambda: Backoff(base_s=0.25, cap_s=10.0)
        )
        self._stable_s = stable_s
        self._backoffs = {}  # slot -> Backoff
        self._respawn_at = {}  # slot -> monotonic time respawn is due
        self._spawned_at = {}  # slot -> monotonic time of last spawn
        self._tm_restarts = telemetry.get_registry().counter(
            "recovery.server_restarts"
        )
        # The group list is MUTATED IN PLACE on restart so callers that
        # captured it (the driver's reap paths) always see the current
        # members.
        self.processes = []
        try:
            for i in range(flags.num_servers):
                self.processes.append(self._spawn(i))
        except BaseException:
            # A partial group must not outlive a failed construction —
            # the caller never gets a handle to reap.
            reap_group(self.processes)
            raise
        log.info("Starting %d supervised env servers on %s",
                 len(self.processes), self._basename)

    def _spawn(self, i):
        address = server_address(self._basename, i)
        seed_base = (
            None if self._env_seed is None else self._env_seed + i * 1000
        )
        p = self._ctx.Process(
            target=_serve,
            args=(self._env_name, address, self._native, seed_base),
            daemon=True,
        )
        p.start()
        # beastlint: disable=RACE  single-writer map: the constructor fills every slot before start_watch() creates the watcher (Thread.start publishes); afterwards _spawn runs only on the watcher thread
        self._spawned_at[i] = time.monotonic()
        return p

    def start_watch(self):
        self._thread = threading.Thread(
            target=self._watch, daemon=True, name="server-supervisor"
        )
        self._thread.start()

    def _watch(self):
        while not self._stop.wait(self._poll_interval_s):
            for i, p in enumerate(self.processes):
                if p.is_alive() or self._stop.is_set():
                    continue
                if self.restarts >= self.max_restarts:
                    if i not in self._budget_logged:
                        log.error(
                            "Env server %d died (exit %s) and the "
                            "restart budget (%d) is exhausted; leaving "
                            "this slot down.",
                            i, p.exitcode, self.max_restarts,
                        )
                        self._budget_logged.add(i)
                    continue
                now = time.monotonic()
                due = self._respawn_at.get(i)
                if due is None:
                    # First poll to see this death: schedule the
                    # respawn through jittered backoff, not
                    # immediately — a crash-looping env must not be
                    # respawned every tick, and simultaneous deaths
                    # must not restart in lockstep.
                    bo = self._backoffs.setdefault(
                        i, self._backoff_factory()
                    )
                    if now - self._spawned_at.get(i, now) >= self._stable_s:
                        bo.reset()  # the last incarnation was healthy
                    delay = bo.next_delay()
                    self._respawn_at[i] = now + delay
                    log.warning(
                        "Env server %d died (exit %s); respawning on "
                        "its address in %.2fs (jittered backoff).",
                        i, p.exitcode, delay,
                    )
                    continue
                if now < due:
                    continue
                # beastlint: disable=RACE  watcher-only read-modify-write; the driver's monitor reads an int that is torn-free under the GIL and only informational (stats line / chaos accounting)
                self.restarts += 1
                log.warning(
                    "Env server %d: restarting on its address "
                    "(restart %d/%d).",
                    i, self.restarts, self.max_restarts,
                )
                try:
                    replacement = self._spawn(i)
                except Exception:
                    # Spawn failure (fd/pid pressure is exactly when
                    # servers die) must not kill the watcher thread —
                    # that would END supervision silently. Refund the
                    # attempt and retry after another backoff step.
                    self.restarts -= 1
                    self._respawn_at[i] = (
                        time.monotonic() + self._backoffs[i].next_delay()
                    )
                    log.exception(
                        "Respawn of env server %d failed; backing off.",
                        i,
                    )
                    continue
                del self._respawn_at[i]
                self._tm_restarts.inc()
                if self._stop.is_set():
                    # stop() landed while we were spawning: the reap may
                    # already have iterated the group, so this member
                    # must die here, not serve forever unreaped.
                    reap_group([replacement])
                    return
                # beastlint: disable=RACE  single-reference slot store under the GIL; readers (driver reap, chaos injector) tolerate a momentarily stale member and re-check is_alive()/pid before acting on it
                self.processes[i] = replacement

    def stop(self):
        """Stop restarting. Call BEFORE terminating the group, or the
        watcher resurrects members mid-reap."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                log.error(
                    "server-supervisor watcher did not stop within 10s "
                    "(a respawn may still be in flight); its in-flight "
                    "member reaps itself on insert."
                )


def main(flags):
    _configure_logging()
    # SIGTERM must run the finally below: Python's default handler kills
    # the process without atexit/finally, orphaning the daemonic server
    # children (ppid 1, still serving their ports) — exactly what
    # `kill <group-launcher>` or a supervisor teardown sends. Observed:
    # every split-deployment test run leaked its server pair this way.
    import signal

    def _graceful_term(signum, frame):
        raise SystemExit(143)

    signal.signal(signal.SIGTERM, _graceful_term)

    supervisor = ServerSupervisor(
        flags, max_restarts=getattr(flags, "max_server_restarts", 10)
    )
    supervisor.start_watch()
    try:
        while True:
            time.sleep(10)
    except KeyboardInterrupt:
        pass
    finally:
        supervisor.stop()
        reap_group(supervisor.processes)


def cli():
    main(make_parser().parse_args())


if __name__ == "__main__":
    cli()
